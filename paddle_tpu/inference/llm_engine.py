"""Continuous-batching LLM serving engine with a paged KV cache.

The serving half of the framework the way `jit.TrainStep` is the
training half. The static-batch path (`GPTGenerationMixin.generate` +
the shape-bucketed `InferenceServer`) cannot admit a new request into a
running decode batch, so every mixed-length workload pays worst-case
padding and head-of-line blocking. This engine fixes both, TPU-style
(PAPERS.md "Ragged Paged Attention"; the capability the reference ships
as its analysis_predictor/serving stack):

* **Paged KV cache** — the cache is a pool of fixed-size pages
  [num_pages, page_size, heads, head_dim] per layer with per-sequence
  page tables. Pages are allocated as a sequence grows and freed the
  step it finishes, so HBM scales with LIVE TOKENS instead of
  batch × max_seq_len (padding-waste model: docs/PERF_NOTES.md
  "Serving"). Physical page 0 is a reserved trash page: padding-token
  writes land there and are never attended. The pool dtype is
  configurable (`kv_dtype` / PT_KV_DTYPE): "int8" runs the QUANTIZED
  pool — each written row carries a per-(token, head) fp32 scale in
  page-shaped scale planes, attention dequantizes on gather, and page
  bytes drop ~4× vs fp32 (~2× vs bf16), which is more live sequences
  per HBM byte (quantization runtime, docs/QUANTIZATION.md).

* **Continuous scheduler** — every step admits queued prompts into free
  decode slots, chunks their prefill into the running batch (a FLAT
  token budget: each step carries one decode token per running sequence
  plus as many prefill tokens as fit), samples at each sequence
  frontier, and evicts on EOS or token budget. Admission ORDER is the
  fleet_serving `SLAScheduler` — priority classes, per-tenant
  token-budget fair queuing, TTFT-SLO deadline boosting — which
  degrades to exact FIFO under the default single class. When the pool
  (or slot table) runs dry the lowest-priority / youngest sequence is
  preempted back to the queue (pages freed; greedy decode makes the
  re-run deterministic), after the prefix cache — when enabled — has
  given back its LRU unmapped pages.

* **Shared-prefix radix KV cache** (`LLMEngineConfig(prefix_cache=
  True)` / PT_PREFIX_CACHE) — fleet_serving.RadixPrefixCache indexes
  full prompt pages by token content; a new request whose prompt
  prefix is resident maps the shared pages copy-on-write into its page
  table and skips their prefill entirely, so a fleet sharing a system
  prompt pays its prefill once (docs/SERVING.md; greedy outputs stay
  token-identical — tests/test_fleet_serving.py pins it).

* **ONE compiled decode executable** — every scheduler tick calls the
  same fixed-shape program (`_CompiledPagedStep` over
  `GPTGenerationMixin._paged_decode_core`: token_budget flat tokens,
  num_slots page tables, the pools), so steady-state serving never
  recompiles. Built the `jit.TrainStep` way: weights thread through as
  jit ARGUMENTS (not baked constants — persistent-cache friendly) and
  the KV pools are DONATED, so the page writes are in-place HBM updates
  instead of per-step pool copies. The attention inside is
  `F.paged_attention` — jnp reference on CPU, the Pallas ragged kernel
  on real TPU.

Surface:

    server = inference.LLMServer(model)        # GPTForCausalLM
    with server:
        fut = server.submit(prompt_ids, max_new_tokens=64,
                            eos_token_id=50256)
        tokens = fut.result()   # np.int64 [prompt + generated]

Greedy decode is token-for-token identical to `generate()` (pinned by
tests/test_llm_engine.py); eos semantics follow the shared contract
(the emitted eos is kept, nothing after it).
"""
import collections
import itertools
import os
import queue
import threading
import time as _time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import metrics as _obs
from ..observability import reqtrace as _reqtrace
from ..observability.tracing import trace_span as _trace_span
from .structured.compiler import _STRUCT_CACHE_HITS, _STRUCT_REQS
from .fleet_serving import (Priority, RadixPrefixCache, RequestCancelled,
                            RequestShed, SLAScheduler, note_cancelled,
                            note_shed)
from .serving import _FutureQueueServer

__all__ = ["PagePool", "PoolExhausted", "LLMEngineConfig", "LLMEngine",
           "LLMServer"]

# serving telemetry (docs/OBSERVABILITY.md). Counters/histograms are
# process-global (engines in one process share them; `LLMServer.metrics()`
# reads this registry — the bench's attribution source). Gauges carry
# the most recent scheduler tick's view.
_REQS_TOTAL = _obs.counter("pt_llm_requests_total", "requests accepted")
_FINISHED_TOTAL = _obs.counter("pt_llm_finished_total",
                               "requests finished (eos or budget)")
_PREEMPTIONS_TOTAL = _obs.counter(
    "pt_llm_preemptions_total", "sequences preempted on a dry page pool")
_STEPS_TOTAL = _obs.counter("pt_llm_steps_total", "scheduler ticks")
_ABORTS_TOTAL = _obs.counter("pt_llm_aborts_total",
                             "abort_all events (device-error path)")
_TOKENS_TOTAL = _obs.counter(
    "pt_llm_tokens_total",
    "flat tokens through the compiled step: one decode token per "
    "sampling frontier, the rest chunked prefill",
    labelnames=("phase",))
_QUEUE_DEPTH = _obs.gauge("pt_llm_queue_depth", "requests waiting")
_LIVE_SLOTS = _obs.gauge("pt_llm_live_slots", "sequences decoding")
_SLOT_OCC = _obs.gauge("pt_llm_slot_occupancy",
                       "live slots / num_slots, last tick")
_PAGE_OCC = _obs.gauge("pt_llm_kv_page_occupancy",
                       "live KV pages / allocable pages")
_PAGE_FRAG = _obs.gauge(
    "pt_llm_kv_fragmentation",
    "internal fragmentation: 1 - written tokens / live page capacity")
_ADMIT_SECONDS = _obs.histogram("pt_llm_admission_seconds",
                                "submit -> first decode-slot admission")
_TTFT_SECONDS = _obs.histogram("pt_llm_ttft_seconds",
                               "submit -> first generated token")
_REQ_TOK_RATE = _obs.histogram(
    "pt_llm_request_tokens_per_sec",
    "per-request generated tok/s (admission -> finish)",
    buckets=(1, 2, 5, 10, 20, 50, 100, 200, 500, 1000, 2000, 5000,
             10000))
_KV_POOL_BYTES = _obs.gauge(
    "pt_kv_pool_bytes",
    "resident KV page-pool bytes (pools + int8 scale planes), by the "
    "pool dtype (quantized runtime: docs/QUANTIZATION.md)",
    labelnames=("dtype",))
# fused multi-token decode (docs/SERVING.md "Fused decode"): host
# round trips vs tokens produced — the dispatch-overhead economics the
# decode_k knob trades TTFT granularity for
_FUSED_STEPS = _obs.counter(
    "pt_decode_fused_steps",
    "fused k-step decode windows dispatched (one host sync per window)")
_DISPATCHES = _obs.counter(
    "pt_decode_dispatches_total",
    "compiled decode-step dispatches (host round trips), single-tick "
    "or fused window")
_TOK_PER_DISPATCH = _obs.gauge(
    "pt_decode_tokens_per_dispatch",
    "generated tokens the LAST compiled-step dispatch produced (the "
    "fused-decode amortization: up to num_slots on a k=1 tick — one "
    "per sampling frontier — and up to k*num_slots per fused window)")
# shared with jit.TrainStep's probe — ONE definition (the registry
# would raise on a labelnames divergence between two copies)
from ..jit import _DONATION_HELD


class PoolExhausted(RuntimeError):
    """No free KV pages (the scheduler preempts and retries on this)."""


def _payload_trace(payload):
    """The TraceContext a KVPagePayload carries (restored once and
    cached on the payload), or None — the disaggregated hand-off's
    identity continuity, shared by `LLMServer.submit` and
    `LLMEngine.add_request` so NEITHER ingress mints a fresh trace
    over a payload that already has one."""
    ctx = getattr(payload, "trace_ctx", None)
    if ctx is None and getattr(payload, "trace", None):
        ctx = _reqtrace.TraceContext.from_dict(payload.trace)
        payload.trace_ctx = ctx
    return ctx


class PagePool:  # ptlint: thread-shared (scraped by /metrics)
    """Refcounted fixed-size KV-page allocator. Physical page 0 is
    reserved as the trash page (padding-token writes), so pages
    1..num_pages-1 are allocable. `alloc()` hands out a page at
    refcount 1; `share()` adds a holder (the prefix cache's trie and
    every request mapping a shared page each hold one reference);
    `free()` drops one reference per page and only returns the page to
    the free list at refcount 0. Strict double-free / free-list
    corruption / leak checking — the invariants the soak and refcount
    tests pin (a free of an already-free page RAISES instead of
    silently double-inserting it into the free list, which would later
    hand the same page to two sequences)."""

    def __init__(self, num_pages, page_size):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is trash)")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # LIFO free stack, seeded so the first allocs hand out 1, 2, ...
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._ref = {}  # live page id -> refcount (>= 1)

    @property
    def num_free(self):
        return len(self._free)

    @property
    def num_live(self):
        return len(self._ref)

    @property
    def num_shared(self):
        # list() copy: the metrics HTTP scrape thread reads this while
        # the engine thread alloc/frees (dict resize mid-iteration)
        return sum(1 for c in list(self._ref.values()) if c > 1)

    def refcount(self, page):
        return self._ref.get(int(page), 0)

    def alloc(self):
        if not self._free:
            raise PoolExhausted(
                f"all {self.num_pages - 1} KV pages in use")
        p = self._free.pop()
        if p in self._ref:  # a corrupted free list must fail loudly
            raise RuntimeError(
                f"corrupt free list: page {p} is already live")
        self._ref[p] = 1
        return p

    def share(self, page):
        """Add one holder to a LIVE page (shared-prefix mapping).
        Sharing a freed page is a use-after-free — the page may already
        belong to another sequence — so it raises."""
        p = int(page)
        if p not in self._ref:
            raise RuntimeError(
                f"share of non-live KV page {p}: the page was freed "
                "(or never allocated) — stale prefix-cache mapping?")
        self._ref[p] += 1
        return p

    def free(self, pages):
        for p in pages:
            p = int(p)
            if p not in self._ref:
                raise RuntimeError(
                    f"double free of KV page {p} (live: "
                    f"{len(self._ref)})")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                del self._ref[p]
                self._free.append(p)

    def assert_consistent(self):
        if len(self._free) != len(set(self._free)):
            raise RuntimeError("corrupt free list: duplicate pages")
        both = set(self._free) & set(self._ref)
        if both:
            raise RuntimeError(
                f"pages both free and live: {sorted(both)}")
        if 0 in self._ref or 0 in self._free:
            raise RuntimeError("trash page 0 entered circulation")
        total = len(self._free) + len(self._ref)
        if total != self.num_pages - 1:
            raise RuntimeError(
                f"page leak: {len(self._free)} free + "
                f"{len(self._ref)} live != {self.num_pages - 1}")


class LLMEngineConfig:
    """Engine sizing. Defaults are safe (worst-case pool: no
    preemption); shrink `num_pages` to trade HBM for occasional
    preemption under load.

    num_slots     max concurrently-decoding sequences (the compiled
                  step's batch geometry)
    page_size     tokens per KV page
    num_pages     pool size incl. the trash page; default
                  num_slots * ceil(max_model_len / page_size) + 1
    max_model_len per-sequence token cap; default model max_seq_len
    token_budget  flat tokens per step (>= num_slots); the surplus over
                  the decode tokens is the chunked-prefill bandwidth.
                  Default num_slots + max(num_slots, 8).
    kv_dtype      pool dtype: "float32" | "bfloat16" | "int8" | "int4"
                  (the quantized runtime — int8/int4 pools carry
                  per-row scale planes and dequantize on gather; int4
                  packs two nibbles per byte along head_dim, ~1.9×
                  the equal-bytes page capacity of int8 and ~7× fp32,
                  at a coarser 15-level grid — docs/QUANTIZATION.md
                  "int4"). Default: the PT_KV_DTYPE env var, else the
                  model compute dtype.
    prefix_cache  enable the shared-prefix radix KV cache
                  (fleet_serving.RadixPrefixCache): requests with a
                  cached prompt prefix map shared pages read-only and
                  skip their prefill. Default: the PT_PREFIX_CACHE env
                  var, else off.
    hash_block_tokens
                  content-hash granularity of the prefix trie, in
                  tokens. Must be a positive multiple of `page_size`
                  (a trie node maps WHOLE pages; a block that ends
                  mid-page would alias half-written KV). Default:
                  page_size.
    sla_policy    fleet_serving.SLAPolicy for the admission scheduler
                  (priority classes, tenant fair queuing, TTFT SLO
                  boost). Default policy degrades to FIFO when every
                  request uses the default tenant/priority.
    decode_k      fused-decode window size: pure-decode ticks run k
                  tokens per compiled dispatch (a `lax.scan` with
                  in-executable sampling + EOS masking), so the host
                  syncs once per k tokens. 1 (the default / env
                  PT_DECODE_K) keeps the single-tick host loop.
                  Admission, preemption, SLO escalation, and
                  prefix-cache publication happen at window
                  BOUNDARIES (docs/SERVING.md has the TTFT/SLO
                  granularity contract).
    seed          engine PRNG seed for temperature/top-p sampling
                  (threaded through the compiled step as an argument —
                  `reseed()` never recompiles). Greedy decode ignores
                  it.
    draft_model   optional small draft model (same GPT family, tied
                  tokenizer — vocab ids must match) enabling
                  SPECULATIVE DECODING (inference/speculative.py,
                  docs/SERVING.md): the draft proposes spec_k tokens
                  per live sequence through its own mirrored paged KV
                  pool, the big model verifies all k+1 positions per
                  slot in ONE ragged batched dispatch, and lossless
                  exact-match acceptance keeps greedy AND sampled
                  outputs token-identical to the non-speculative
                  engine. None (default) keeps the PR-8 fused /
                  single-tick paths.
    spec_k        draft tokens proposed per speculative window.
                  Default: the PT_SPEC_K env var, else 4. Ignored
                  without speculation enabled.
    spec_mode     speculation source: None (off unless draft_model is
                  set, which implies "draft"), "draft" (requires
                  draft_model), or "ngram" — draft-model-FREE
                  prompt-lookup proposals (inference/structured/
                  ngram.py): the request's own prompt+generated
                  suffix proposes spec_k tokens into the SAME ragged
                  verify executable, no second model resident.
                  "ngram" with a draft_model is a config error.
    token_strs    per-token surface strings (len == vocab_size) —
                  enables STRUCTURED DECODING (inference/structured,
                  docs/SERVING.md "Structured decoding"): per-request
                  `grammar=` / `json_schema=` constraints compile to
                  token-level DFAs masked inside the compiled scans.
                  None (default) = constrained requests are rejected
                  loudly at submit.
    grammar_states
                  grammar-arena DFA state budget (table rows resident
                  at once across all live grammars; row 0 is the
                  mask-identity). A grammar over the budget raises
                  GrammarError at submit. Default 128; ignored
                  without token_strs (the arena collapses to the
                  identity row).
    kv_tier       hierarchical KV memory below the device pool
                  (fleet_serving.kv_tier; docs/SERVING.md "KV memory
                  hierarchy"). Falsy (default) = off. True enables the
                  host-RAM spill tier with defaults; a dict passes
                  `KVTierStore` knobs through (`ram_bytes`,
                  `disk_dir`, `disk_bytes`, `max_pending`). Requires
                  prefix_cache: the tier spills/prefetches TRIE nodes.
    session_ttl_s persistent-chat session TTL (seconds a session's
                  frontier stays tracked after its last turn;
                  default 600). See `LLMServer.submit(session_id=)`.
    session_max   LRU cap on tracked sessions (default 256).
    """

    def __init__(self, num_slots=4, page_size=16, num_pages=None,
                 max_model_len=None, token_budget=None, kv_dtype=None,
                 prefix_cache=None, hash_block_tokens=None,
                 sla_policy=None, decode_k=None, seed=0,
                 draft_model=None, spec_k=None, kv_tier=None,
                 session_ttl_s=None, session_max=None, spec_mode=None,
                 token_strs=None, grammar_states=None):
        self.num_slots = int(num_slots)
        self.page_size = int(page_size)
        self.num_pages = num_pages
        self.max_model_len = max_model_len
        self.token_budget = token_budget
        self.kv_dtype = kv_dtype
        if prefix_cache is None:
            prefix_cache = os.environ.get(
                "PT_PREFIX_CACHE", "0").strip().lower() in (
                    "1", "true", "yes", "on")
        self.prefix_cache = bool(prefix_cache)
        self.hash_block_tokens = int(
            self.page_size if hash_block_tokens is None
            else hash_block_tokens)
        self.sla_policy = sla_policy
        if decode_k is None:
            decode_k = int(os.environ.get("PT_DECODE_K", "1"))
        self.decode_k = int(decode_k)
        self.seed = int(seed)
        self.draft_model = draft_model
        if spec_k is None:
            spec_k = int(os.environ.get("PT_SPEC_K", "4"))
        self.spec_k = int(spec_k)
        if spec_mode is None and draft_model is not None:
            spec_mode = "draft"
        if spec_mode not in (None, "draft", "ngram"):
            raise ValueError(
                "spec_mode must be None, 'draft', or 'ngram', got "
                f"{spec_mode!r}")
        if spec_mode == "draft" and draft_model is None:
            raise ValueError(
                "spec_mode='draft' needs draft_model= (pass "
                "spec_mode='ngram' for draft-model-free speculation)")
        if spec_mode == "ngram" and draft_model is not None:
            raise ValueError(
                "spec_mode='ngram' is draft-model-free — drop "
                "draft_model= (or use spec_mode='draft')")
        self.spec_mode = spec_mode
        self.token_strs = (None if token_strs is None
                           else list(token_strs))
        self.grammar_states = int(128 if grammar_states is None
                                  else grammar_states)
        if self.grammar_states < 2:
            raise ValueError(
                "grammar_states must be >= 2 (row 0 is the reserved "
                f"mask-identity row), got {self.grammar_states}")
        self.kv_tier = kv_tier
        self.session_ttl_s = float(600.0 if session_ttl_s is None
                                   else session_ttl_s)
        self.session_max = int(256 if session_max is None
                               else session_max)
        if self.kv_tier and not self.prefix_cache:
            raise ValueError(
                "kv_tier requires prefix_cache=True: the tier "
                "spills and prefetches radix-trie nodes, so without "
                "the trie there is nothing to tier")
        if self.num_slots < 1:
            raise ValueError("num_slots must be >= 1")
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        if self.decode_k < 1:
            raise ValueError("decode_k must be >= 1")
        if self.spec_k < 1:
            raise ValueError("spec_k must be >= 1")
        if self.hash_block_tokens < 1:
            raise ValueError("hash_block_tokens must be >= 1")
        if self.prefix_cache and (
                self.hash_block_tokens % self.page_size != 0):
            # silent misalignment would map pages whose tail rows hold
            # a DIFFERENT request's tokens — reject loudly at config
            # time, not with corrupted logits at serve time
            raise ValueError(
                f"prefix_cache requires page_size ({self.page_size}) "
                f"to divide hash_block_tokens "
                f"({self.hash_block_tokens}): a trie block must cover "
                "an exact number of KV pages, otherwise a shared "
                "mapping would alias a partially-matching page")

    @staticmethod
    def kv_bytes_per_page(model_config, page_size, kv_dtype=None):
        """Bytes ONE page costs across every layer's k+v pool, scale
        planes included — the unit of the capacity math below. int8
        rows cost hd + 4 bytes per head; packed int4 rows cost hd/2 +
        4 (two nibbles per byte — the scale plane is shared machinery,
        so its 4 bytes/head weigh relatively more: equal-bytes
        capacity lands ≈ ×1.9 over int8, ≈ ×7 over fp32 at hd 32)."""
        from ..quantization import runtime as _qrt

        dt, quantized = _qrt.resolve_kv_dtype(kv_dtype, jnp.float32)
        nh = model_config.num_heads
        hd = model_config.hidden_size // nh
        if quantized == 4:
            per_row = nh * (hd // 2)      # packed nibbles
        else:
            per_row = nh * hd * jnp.dtype(dt).itemsize
        if quantized:
            per_row += nh * 4  # fp32 scale per (row, head)
        return 2 * model_config.num_layers * page_size * per_row

    @classmethod
    def for_pool_budget(cls, model_config, budget_bytes, page_size=16,
                        kv_dtype=None, **kw):
        """Size `num_pages` to a page-pool BYTE budget — the equal-bytes
        capacity comparison the quantized-KV acceptance pins (int8 pools
        admit ~4× the pages of fp32 at the same budget)."""
        per_page = cls.kv_bytes_per_page(model_config, page_size,
                                         kv_dtype)
        num_pages = max(2, int(budget_bytes) // per_page + 1)  # + trash
        return cls(page_size=page_size, num_pages=num_pages,
                   kv_dtype=kv_dtype, **kw)


class _CompiledStepBase:
    """Shared dispatch shell of every compiled decode executable
    (single-tick, fused window, speculative propose/verify): the
    first call compiles OUTSIDE the persistent cache — a cache-loaded
    donating executable on jax 0.4.x drops (or worse, mismatches) its
    aliasing map, measured 25% slower serving from the silent
    donation loss alone (docs/RESILIENCE.md) — and every later call
    dispatches the warm jit directly. Subclasses build `self._jit`
    (weights as ARGUMENTS, kv pytree DONATED) and call `_run`."""

    _jit = None
    _warm = False

    def _run(self, *args):
        if self._warm:
            return self._jit(*args)
        # guard the compile only: the no-persistent-cache flag is
        # process-global, so flipping it every tick from the serving
        # thread would race other threads' compiles
        from ..core.jax_compat import no_persistent_cache

        with no_persistent_cache():
            out = self._jit(*args)
        self._warm = True
        return out

    def cache_size(self):
        n = getattr(self._jit, "_cache_size", None)
        return int(n()) if callable(n) else -1


class _CompiledPagedStep(_CompiledStepBase):
    """The engine's ONE decode executable, built the `jit.TrainStep`
    way: a pure function over (param_vals, step arrays, kv pools) under
    `jax.jit`. Weights ride as ARGUMENTS (structurally-equal engines
    share one correct persistent-cache entry — the same reasoning as
    TrainStep's base-key-as-argument note), and the kv-pool pytree is
    DONATED so the paged cache writes update HBM in place instead of
    copying every pool every tick."""

    def __init__(self, model):
        self._params = list(model.state_dict().values())

        def pure(param_vals, tok, pos, sid, widx, pt, klen, smp,
                 kv_state):
            from ..autograd import engine as eng
            from ..tensor_core import Tensor

            def t(v):
                return Tensor(v, stop_gradient=True)

            # kv_state = (pools, scale planes, PRNG key) — scales empty
            # for float pools; ONE donated pytree so int8 pools, their
            # scales, and the sampling key update in place together.
            # The single-tick step never consumes randomness (sampling
            # rows draw on the host through the SAME sample_tokens
            # math), so the key passes through untouched.
            kv_vals, kv_scales, key = kv_state
            originals = [p._value for p in self._params]
            for p, v in zip(self._params, param_vals):
                p._value = v
            try:
                with eng.no_grad_guard():
                    out = model._paged_decode_core(
                        t(tok), t(pos), t(sid), t(widx), t(pt), t(klen),
                        t(smp), [t(v) for v in kv_vals],
                        kv_scales=(
                            [t(s) for s in kv_scales] if kv_scales
                            else None))
            finally:
                for p, v in zip(self._params, originals):
                    p._value = v
            logits, *new_kv = out
            n = len(kv_vals)
            return logits._value, ([x._value for x in new_kv[:n]],
                                   [x._value for x in new_kv[n:]], key)

        self._jit = jax.jit(pure, donate_argnums=(8,))

    def __call__(self, tok, pos, sid, widx, pt, klen, smp, kv_state):
        return self._run([p._value for p in self._params], tok, pos,
                         sid, widx, pt, klen, smp, kv_state)


class _CompiledFusedStep(_CompiledStepBase):
    """The engine's fused k-step decode executable: `lax.scan` over the
    paged step (`GPTGenerationMixin._paged_decode_fused`) with sampling
    and EOS/budget masking INSIDE the scan — one host round trip per k
    tokens. Built exactly like `_CompiledPagedStep` (weights as jit
    ARGUMENTS, the kv pytree — pools + scale planes + PRNG key —
    DONATED, first compile outside the persistent cache). k is baked
    into the scan length, so one engine holds ONE fused executable per
    (k, geometry); window spill (pool pressure / short budgets) rides
    the `rem` argument instead of re-tracing a shorter scan."""

    def __init__(self, model, k, page_size):
        self._params = list(model.state_dict().values())
        self.k = int(k)
        ps = int(page_size)

        def pure(param_vals, tok0, pos0, rem, fin0, eos, temps, top_ps,
                 streams, gstate0, gtrans, gmask, pt, kv_state):
            from ..autograd import engine as eng

            kv_vals, kv_scales, key = kv_state
            originals = [p._value for p in self._params]
            for p, v in zip(self._params, param_vals):
                p._value = v
            try:
                with eng.no_grad_guard():
                    emits, new_kv, new_scales = model._paged_decode_fused(
                        self.k, ps, tok0, pos0, rem, fin0, eos, temps,
                        top_ps, streams, pt, list(kv_vals),
                        list(kv_scales) if kv_scales else None, key,
                        gstate0=gstate0, gtrans=gtrans, gmask=gmask)
            finally:
                for p, v in zip(self._params, originals):
                    p._value = v
            return emits, (new_kv, new_scales, key)

        self._jit = jax.jit(pure, donate_argnums=(13,))

    def __call__(self, tok0, pos0, rem, fin0, eos, temps, top_ps,
                 streams, gstate0, gtrans, gmask, pt, kv_state):
        return self._run([p._value for p in self._params], tok0, pos0,
                         rem, fin0, eos, temps, top_ps, streams,
                         gstate0, gtrans, gmask, pt, kv_state)


class _Request:
    _ids = itertools.count()

    def __init__(self, tokens, max_new_tokens, eos_token_id, future,
                 tenant="default", priority=None, ttft_slo_s=None,
                 temperature=0.0, top_p=1.0):
        self.rid = next(_Request._ids)
        self.tokens = [int(t) for t in tokens]  # prompt, grows as decoded
        self.prompt_len = len(self.tokens)
        self.max_new = int(max_new_tokens)
        self.eos = eos_token_id
        self.future = future if future is not None else Future()
        self.target = None        # total-token cap, set at add_request
        self.slot = None
        self.pages = []           # physical page ids, logical order
        self.n_prefilled = 0      # kv-written tokens (reset on preempt)
        self.draft_prefilled = 0  # draft-pool valid prefix (speculative)
        self.admit_seq = None     # admission order (preemption picks max)
        self.preemptions = 0
        # fleet_serving fields (scheduler class / fairness / SLO)
        self.tenant = str(tenant)
        self.priority = int(Priority.STANDARD if priority is None
                            else priority)
        if self.priority < 0:
            # -1 is the scheduler's SLO-escalation rank: a client
            # priority below 0 would outrank every deadline-escalated
            # request AND compare its fair-queuing meter against their
            # absolute deadlines (meaningless tuple order)
            raise ValueError(
                f"priority must be >= 0, got {self.priority} "
                "(negative ranks are reserved for SLO escalation)")
        self.ttft_slo_s = ttft_slo_s
        # sampling contract: temperature 0 = greedy (the default,
        # token-identical to generate()); > 0 samples the temperature-
        # scaled top-p-truncated distribution, keyed on (engine seed,
        # sample_stream, position) — deterministic under preemption
        # replay and invariant to decode_k (gpt.py sample_tokens)
        self.temperature = float(temperature)
        self.top_p = float(top_p)
        if self.temperature < 0:
            raise ValueError(
                f"temperature must be >= 0, got {self.temperature}")
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p}")
        self.sample_stream = 0    # engine-assigned at add_request
        # disaggregated serving (fleet_serving.kv_transfer): a prefill-
        # only request stops AT its sampling frontier and resolves its
        # future to the exported KVPagePayload instead of tokens; a
        # request carrying _kv_import admits with its prompt KV written
        # from another replica's payload (consumed at admission — a
        # preemption replay falls back to ordinary prefill)
        self.prefill_only = False
        self._kv_import = None
        # persistent chat sessions (ISSUE 17): set by add_request;
        # _session_seen marks a RETURNING session (resume telemetry)
        self.session_id = None
        self._session_seen = False
        # structured decoding (inference/structured): the compiled
        # token-level DFA and the request's grammar-LOCAL state. The
        # state is a pure function of the generated tokens (the engine
        # replays every emitted token through `grammar.advance`), and
        # `tokens` survives preemption, so a preempted constrained
        # request resumes at the correct DFA state for free.
        self.grammar = None
        self.gstate = 0
        self.spec_off = False     # per-request spec_mode="off" opt-out
        self._arrival = None      # scheduler enqueue stamp
        self.cached_prefix = 0    # tokens served from the prefix cache
        self._cow_pending = 0     # COW splits taken by the last match
        self.published_blocks = 0  # trie blocks this mapping covers
        # telemetry stamps (admission latency / TTFT / per-request rate)
        self.t_submit = _time.perf_counter()
        self.t_first_admit = None
        self.t_first_token = None
        # hard deadline (absolute perf_counter; overload control plane)
        self.deadline_t = None
        # request-scoped trace identity + TTFT phase stamps
        # (observability.reqtrace; assigned by add_request)
        self.trace = None

    @property
    def do_sample(self):
        return self.temperature > 0.0

    @property
    def num_generated(self):
        return len(self.tokens) - self.prompt_len

    def result_array(self):
        return np.asarray(self.tokens, np.int64)


class LLMEngine:  # ptlint: thread-shared (scraped by /metrics)
    """Scheduler + paged-KV state around ONE compiled ragged decode step
    (module docstring has the design). Drive it directly —

        eng = LLMEngine(model)
        req = eng.add_request(prompt_ids, max_new_tokens=32)
        while eng.has_work():
            eng.step()
        tokens = req.future.result()

    — or through `LLMServer` for the threaded future/queue surface."""

    def __init__(self, model, config=None):
        model.eval()
        self.model = model
        mcfg = model.config
        cfg = config or LLMEngineConfig()
        self.num_slots = cfg.num_slots
        self.page_size = cfg.page_size
        self.max_model_len = int(cfg.max_model_len or mcfg.max_seq_len)
        if self.max_model_len > mcfg.max_seq_len:
            raise ValueError(
                f"max_model_len {self.max_model_len} exceeds the "
                f"model's max_seq_len {mcfg.max_seq_len}")
        self.pages_per_seq = -(-self.max_model_len // self.page_size)
        self.token_budget = int(
            cfg.token_budget
            or self.num_slots + max(self.num_slots, 8))
        if self.token_budget < self.num_slots:
            raise ValueError(
                f"token_budget {self.token_budget} < num_slots "
                f"{self.num_slots}: every running sequence needs one "
                "decode token per step")
        num_pages = int(cfg.num_pages
                        or self.num_slots * self.pages_per_seq + 1)
        self.pool = PagePool(num_pages, self.page_size)

        nh = mcfg.num_heads
        hd = mcfg.hidden_size // nh
        # pool in the configured kv_dtype (default: the model's compute
        # dtype — decode is HBM-bound, same reasoning as generate()'s
        # cache dtype; "int8" quantizes each written row per (token,
        # head) with fp32 scale planes alongside — quantization runtime,
        # docs/QUANTIZATION.md). The zero pools are COMMITTED with the
        # same replicated NamedSharding the step executable's outputs
        # carry (the TP layers' sharding constraints stamp the global
        # mesh on every output) — a placement mismatch between step 0's
        # pools and every later step's would cost a second
        # dispatch-cache entry (the zero-recompile probe would read 2
        # executables, not 1)
        from ..distributed import mesh as mesh_mod
        from ..quantization import runtime as _qrt

        compute_dt = model.gpt.wte.weight._value.dtype
        cache_dt, self.kv_quantized = _qrt.resolve_kv_dtype(
            cfg.kv_dtype, compute_dt)
        # kv_quantized is the code width (0 float / 8 / 4 — truthy when
        # quantized); int4 packs two nibbles per byte along head_dim,
        # so the pool's last dim is hd/2 and attention unpacks on
        # gather (the shape IS the codec discriminator — gpt.py
        # _paged_cache_write_quant / F.paged_attention)
        hd_store = hd
        if self.kv_quantized == 4:
            if hd % 2:
                raise ValueError(
                    f"kv_dtype='int4' needs an even head_dim, got {hd} "
                    "(nibble packing pairs head_dim elements)")
            hd_store = hd // 2
            self.kv_dtype = "int4"
        else:
            self.kv_dtype = str(jnp.dtype(cache_dt))
        sharding = mesh_mod.named_sharding()  # replicated on the mesh

        def _fresh_pools():
            pools = [
                jax.device_put(
                    jnp.zeros((num_pages, self.page_size, nh, hd_store),
                              cache_dt), sharding)
                for _ in range(2 * mcfg.num_layers)]
            scales = []
            if self.kv_quantized:
                sshape = _qrt.kv_scale_shape(num_pages, self.page_size,
                                             nh)
                scales = [
                    jax.device_put(jnp.zeros(sshape, jnp.float32),
                                   sharding)
                    for _ in range(2 * mcfg.num_layers)]
            return pools, scales

        self._fresh_pools = _fresh_pools
        self._kv, self._kv_scales = _fresh_pools()
        self._spec = None  # set below; pool_bytes() reads it
        _KV_POOL_BYTES.labels(dtype=self.kv_dtype).set(self.pool_bytes())
        self._page_tables = np.zeros(
            (self.num_slots, self.pages_per_seq), np.int32)
        self._slots = [None] * self.num_slots
        # fused multi-token decode (decode_k > 1): pure-decode ticks go
        # through ONE k-step scan executable; the engine-owned PRNG key
        # rides the same donated pytree as the pools. Committed to the
        # pools' sharding for the same one-executable reason.
        self.decode_k = int(cfg.decode_k)
        self._seed = int(cfg.seed)
        self._key = jax.device_put(
            jax.random.PRNGKey(cfg.seed), sharding)
        self._sample_streams = itertools.count()
        self._fused_fn = None     # built lazily on the first window
        self._host_sample = None  # jitted sample_tokens for host ticks
        # staging cache: per-tick host arrays whose values depend only
        # on slot MEMBERSHIP (sid / sample_idx) are device-committed
        # once per slot-assignment generation instead of rebuilt and
        # re-uploaded every decode tick
        self._slot_gen = 0
        self._stage = None
        # fleet_serving: SLA admission (default policy degrades to
        # FIFO) + optional shared-prefix radix cache over the pool
        self.sched = SLAScheduler(cfg.sla_policy)
        self.hash_block_tokens = int(cfg.hash_block_tokens)
        self.prefix_cache = (
            RadixPrefixCache(self.pool, self.page_size,
                             self.hash_block_tokens)
            if cfg.prefix_cache else None)
        self._admit_counter = itertools.count()
        # hierarchical KV memory (fleet_serving.kv_tier, ISSUE 17):
        # trie evictions spill D2H into the host-RAM/disk tiers; trie
        # misses probe the tier and prefetch H2D through the SAME
        # fixed-width import scatter every kv_import uses (one
        # executable — the zero-recompile contract covers prefetch)
        self.kv_tier = None
        if cfg.kv_tier:
            from .fleet_serving.kv_tier import KVTierStore

            kw = dict(cfg.kv_tier) if isinstance(cfg.kv_tier, dict) \
                else {}
            self.kv_tier = KVTierStore(**kw)
            self.prefix_cache.spill_fn = self._spill_node
        self._spill_count = 0     # spills queued (kv_spill stamping)
        # persistent chat sessions (docs/SERVING.md "KV memory
        # hierarchy"): session_id -> {last_used, turns}. The KV itself
        # is NOT here — a finished turn's blocks are published into
        # the trie (pinned) and age into the tier like any prefix;
        # this table only tracks liveness for TTL/LRU expiry and the
        # resumed/active telemetry. Engine-thread only.
        self._sessions = collections.OrderedDict()
        self.session_ttl_s = cfg.session_ttl_s
        self.session_max = cfg.session_max
        self._step_fn = _CompiledPagedStep(model)
        self.stats = {"steps": 0, "tokens_in": 0, "generated": 0,
                      "finished": 0, "preemptions": 0,
                      "occupancy_sum": 0.0, "fused_steps": 0,
                      "stage_hits": 0}
        # recent per-request phase timelines (reqtrace), appended at
        # first token / prefill export — the `metrics()` drill-down
        self._timelines = collections.deque(maxlen=64)
        # overload control plane (fleet_serving.overload): the brownout
        # caps dict is REPLACED whole by apply_brownout (GIL-atomic) and
        # read at host decision points only — never inside a trace
        self._brownout = {}
        self._spec_stash = None    # spec decoder parked by brownout L2
        self._deadlines_armed = False  # any deadline request ever seen
        # speculative decoding (draft_model configured): draft pools
        # mirror this pool's page ids, the big model verifies k+1
        # ragged positions per slot in one dispatch — the spec window
        # replaces the fused window for pure-decode ticks
        # (inference/speculative.py; late import: train-only use must
        # not drag the speculative machinery in)
        # structured decoding (inference/structured, docs/SERVING.md
        # "Structured decoding"): the grammar arena's device tables
        # thread through the fused/verify executables at an
        # engine-static shape — [grammar_states, vocab] when token_strs
        # is configured, the lone mask-identity row otherwise (so
        # engines that never see a constraint pay a few KB, not MB).
        # The compile cache is lock-guarded: `LLMServer.submit`
        # compiles grammars on the CALLER's thread (loud reject at
        # submit), while add_request may compile on the engine thread.
        self.spec_mode = cfg.spec_mode
        self.token_strs = (list(cfg.token_strs)
                           if cfg.token_strs is not None else None)
        if (self.token_strs is not None
                and len(self.token_strs) != mcfg.vocab_size):
            raise ValueError(
                f"token_strs has {len(self.token_strs)} entries but "
                f"the model vocab is {mcfg.vocab_size} — one surface "
                "string per token id")
        from .structured.arena import GrammarArena, GrammarCache

        self.grammar_arena = GrammarArena(
            mcfg.vocab_size,
            cfg.grammar_states if self.token_strs is not None else 1)
        self._grammar_cache = GrammarCache()
        self.stats["structured_requests"] = 0
        if cfg.draft_model is not None:
            from .speculative import SpeculativeDecoder

            self._spec = SpeculativeDecoder(self, cfg.draft_model,
                                            cfg.spec_k)
            _KV_POOL_BYTES.labels(dtype=self.kv_dtype).set(
                self.pool_bytes())
        elif cfg.spec_mode == "ngram":
            # draft-model-free speculation: the request's own token
            # history proposes into the same ragged verify executable
            # (inference/structured/ngram.py) — no draft pool, so
            # pool_bytes/brownout-L2 accounting are untouched
            from .structured.ngram import NgramSpeculator

            self._spec = NgramSpeculator(self, cfg.spec_k)

    @property
    def waiting(self):
        """The admission queue (fleet_serving.SLAScheduler). Supports
        len() / bool() / iteration; admission ORDER is the scheduler's
        (docs/SERVING.md), not necessarily arrival."""
        return self.sched

    # ---- structured decoding: the constraint surface ----

    def compile_constraint(self, grammar=None, json_schema=None,
                           eos_token_id=None):
        """Compile one per-request constraint to a `CompiledGrammar`,
        through the engine's hash-keyed cache (a hot schema compiles
        once per replica — `pt_structured_cache_hits` counts reuse).
        Thread-safe: `LLMServer.submit` calls this on the CALLER's
        thread so a bad grammar raises at submit() time, never inside
        the serve loop. Raises GrammarError (a ValueError) for
        unsupported syntax or a DFA over the arena budget."""
        from .structured import (GrammarError, compiler as _gcomp,
                                 schema_to_regex)

        if self.token_strs is None:
            raise GrammarError(
                ("json_schema=" if json_schema is not None
                 else "grammar=") +
                ": this engine has no token_strs — pass "
                "LLMEngineConfig(token_strs=[...]) to enable "
                "structured decoding")
        if isinstance(grammar, _gcomp.CompiledGrammar):
            if grammar.vocab != len(self.token_strs):
                raise GrammarError(
                    f"grammar=: CompiledGrammar vocab {grammar.vocab} "
                    f"!= engine vocab {len(self.token_strs)}")
            return grammar
        if eos_token_id is None:
            raise GrammarError(
                ("json_schema=" if json_schema is not None
                 else "grammar=") +
                ": constrained decoding needs eos_token_id= (the "
                "grammar decides WHEN the output is complete by "
                "unmasking eos in accepting states)")
        pattern = (grammar if grammar is not None
                   else schema_to_regex(json_schema))
        ck = (pattern, int(eos_token_id))
        hit = self._grammar_cache.lookup(ck)
        if hit is not None:
            _STRUCT_CACHE_HITS.inc()
            return hit
        # compile OUTSIDE the cache lock (pure host work, possibly
        # slow); a racing duplicate compile is wasted work, not
        # corruption — GrammarCache.insert keeps the first copy
        try:
            cg = _gcomp.compile_regex(
                pattern, self.token_strs, eos_id=int(eos_token_id),
                max_states=self.grammar_arena.capacity)
        except GrammarError:
            self._grammar_cache.reject()
            raise
        return self._grammar_cache.insert(ck, cg)

    def _resolve_constraint(self, grammar, json_schema, eos_token_id,
                            spec_mode):
        """add_request's ingress gate: structural validation (shared
        with every remote ingress), engine-context checks, and the
        grammar compile. Returns the CompiledGrammar or None."""
        from .structured import validate_constraints

        validate_constraints(grammar=grammar, json_schema=json_schema,
                             spec_mode=spec_mode)
        if spec_mode not in (None, "off") and spec_mode != (
                self.spec_mode or "off"):
            raise ValueError(
                f"spec_mode={spec_mode!r}: this engine runs "
                f"spec_mode={self.spec_mode!r} — speculation is an "
                "engine resource; per-request spec_mode can only "
                "opt OUT ('off') or restate the engine's mode")
        if grammar is None and json_schema is None:
            return None
        return self.compile_constraint(grammar=grammar,
                                       json_schema=json_schema,
                                       eos_token_id=eos_token_id)

    def _live_grammar_hashes(self):
        """Hashes of grammars still referenced by queued or running
        requests — what arena compaction must keep."""
        live = set()
        for r in self._slots:
            if r is not None and r.grammar is not None:
                live.add(r.grammar.hash)
        for r in self.sched:
            if r.grammar is not None:
                live.add(r.grammar.hash)
        return live

    def _grammar_args(self, rows):
        """Per-dispatch grammar arguments for the fused/verify
        executables: arena-ABSOLUTE DFA states [num_slots] (0 = the
        mask-identity row unconstrained slots ride) plus the committed
        device tables. Shapes are engine-static — grammar churn swaps
        values, never triggers a retrace. Without token_strs no
        request can EVER be constrained, so all three are None and the
        executables compile the pre-structured graph — engines outside
        the constraint surface pay zero trace or dispatch cost."""
        if self.token_strs is None:
            return None, None, None
        gst = np.zeros((self.num_slots,), np.int32)
        for slot, req in rows:
            if req.grammar is not None:
                gst[slot] = (self.grammar_arena.base_of(req.grammar)
                             + req.gstate)
        gtrans, gmask = self.grammar_arena.device_tables()
        return gst, gtrans, gmask

    # ---- client side ----

    def add_request(self, prompt, max_new_tokens=32, eos_token_id=None,
                    future=None, tenant="default", priority=None,
                    ttft_slo_s=None, temperature=0.0, top_p=1.0,
                    prefill_only=False, kv_import=None, trace=None,
                    deadline_s=None, session_id=None, grammar=None,
                    json_schema=None, spec_mode=None):
        """Enqueue one request. The disaggregated-serving knobs
        (docs/SERVING.md "Disaggregated fleet"):

        prefill_only  run chunked prefill up to the SAMPLING FRONTIER
                      (prompt_len - 1 tokens written) and resolve the
                      future to the exported
                      `fleet_serving.KVPagePayload` — no token is ever
                      sampled, so a prefill replica never steals a
                      decode window. max_new_tokens is ignored.
        kv_import     a KVPagePayload from another replica's
                      `export_kv_pages`: the request admits with its
                      prompt KV written from the payload (skipping that
                      prefill) and decodes from its frontier. Geometry
                      must match this engine's pool exactly — checked
                      loudly HERE, not with corrupt logits at serve
                      time.
        session_id    persistent-chat identity (docs/SERVING.md "KV
                      memory hierarchy"): the finished turn's trie
                      blocks — generated tokens included — stay
                      pinned-then-tiered so the next turn resumes from
                      its frontier instead of re-prefilling the
                      history. Sessions expire by TTL/LRU; brownout
                      L4 sheds pinning before any traffic is
                      refused.

        Structured decoding (docs/SERVING.md "Structured decoding"):

        grammar       a regex string (or pre-compiled
                      structured.CompiledGrammar) constraining the
                      OUTPUT tokens — compiled to a token-level DFA
                      masked inside the decode executables. Requires
                      LLMEngineConfig(token_strs=...) and an
                      eos_token_id; rejected loudly HERE otherwise.
        json_schema   a JSON-schema dict lowered to a grammar
                      (structured.schema_to_regex) — canonical
                      no-whitespace JSON output. Mutually exclusive
                      with grammar=.
        spec_mode     per-request speculation override: None inherits
                      the engine's mode; "off"/the engine's own mode
                      are accepted; asking for a mode the engine
                      doesn't run raises (speculation is an ENGINE
                      resource — a request can't conjure a draft
                      model)."""
        grammar_obj = self._resolve_constraint(grammar, json_schema,
                                               eos_token_id, spec_mode)
        toks = np.asarray(prompt).reshape(-1)
        if toks.size == 0:
            raise ValueError("empty prompt")
        if toks.size > self.max_model_len:
            raise ValueError(
                f"prompt length {toks.size} exceeds max_model_len "
                f"{self.max_model_len}")
        if -(-int(toks.size) // self.page_size) > self.pool.num_pages - 1:
            raise ValueError(
                f"prompt needs more KV pages than the pool holds "
                f"({self.pool.num_pages - 1})")
        req = _Request(toks, max_new_tokens, eos_token_id, future,
                       tenant=tenant, priority=priority,
                       ttft_slo_s=ttft_slo_s, temperature=temperature,
                       top_p=top_p)
        # per-engine sampling stream: stable across preemption replays
        # (assigned once, BEFORE any admission), so a replayed sampled
        # request reproduces its original continuation
        req.sample_stream = next(self._sample_streams)
        req.target = min(req.prompt_len + req.max_new, self.max_model_len)
        if grammar_obj is not None:
            # load into the arena NOW (loud GrammarError at submit,
            # not mid-serve); the device tables refresh lazily at the
            # next window dispatch — a value swap, never a recompile
            req.grammar = grammar_obj
            try:
                self.grammar_arena.load(
                    grammar_obj, live=self._live_grammar_hashes())
            except Exception:
                self._grammar_cache.reject()
                raise
            _STRUCT_REQS.inc()
            self.stats["structured_requests"] += 1
        req.spec_off = spec_mode == "off"
        if session_id is not None and self.prefix_cache is not None:
            req.session_id = str(session_id)
            req._session_seen = self._touch_session(req.session_id)
        _REQS_TOTAL.inc()
        # trace identity: the caller's (router/server — already stamped
        # `queued` at the ingress), else the payload's (a disaggregated
        # hand-off continues the prefill side's trace), else fresh
        if trace is None and kv_import is not None:
            trace = _payload_trace(kv_import)
        req.trace = trace if trace is not None else _reqtrace.new_trace()
        req.trace.stamp("queued")   # no-op when the ingress stamped it
        # overload control plane (docs/SERVING.md "Overload and
        # degradation"): brownout ingress caps + the hard deadline. A
        # shed RESOLVES the future typed (never raises out of here —
        # the server loop and direct drivers share one contract).
        caps = self._brownout
        sp = caps.get("shed_priority")
        if sp is not None and req.priority >= int(sp):
            return self._shed_at_admit(req, "brownout")
        if not prefill_only:
            cap = caps.get("max_new_cap")
            if cap is not None:
                req.target = min(req.target,
                                 req.prompt_len + max(1, int(cap)))
        if deadline_s is not None:
            ds = float(deadline_s)
            if ds <= 0.0:   # expired before admission: reject at submit
                return self._shed_at_admit(req, "deadline")
            req.deadline_t = req.t_submit + ds
            self._deadlines_armed = True
        if kv_import is not None:
            self._check_import(req, kv_import)
            req._kv_import = kv_import
        if prefill_only:
            req.prefill_only = True
            req.target = req.prompt_len
            if req.prompt_len == 1:
                # nothing before the frontier: an empty export (the
                # decode side prefills the single prompt token itself)
                if not req.future.cancelled():
                    req.future.set_result(
                        self._empty_payload(toks, req.trace))
                return req
        elif req.target <= req.prompt_len:
            # zero budget (same contract as generate()): prompt echoes back
            if not req.future.cancelled():
                req.future.set_result(req.result_array())
            return req
        self.sched.enqueue(req)
        _QUEUE_DEPTH.set(len(self.sched))
        return req

    def has_work(self):
        return bool(self.waiting) or any(
            r is not None for r in self._slots)

    @property
    def mean_occupancy(self):
        s = self.stats["steps"]
        return self.stats["occupancy_sum"] / s if s else 0.0

    def compile_stats(self, check_donation=False):
        """Executable count of the decode step (the jit dispatch-cache
        size) — the zero-recompile-after-warmup probe the engine test
        asserts on.

        `check_donation=True` additionally re-lowers the decode step
        through the live compile-cache path and reports whether the
        donated kv pools (and int8 scale planes) actually aliased
        outputs in the executable — donation silently dropping is the
        measured-25%-slower PR-2 serving bug (docs/RESILIENCE.md).
        Adds a `"donation"` key: {"expected", "aliased", "held",
        "dropped"}.

        THREADING: the donation probe re-TRACES the decode step, and
        the trace body temporarily swaps the model's live parameter
        values for tracers — call it from the thread that owns the
        engine (direct-drive callers; or around, never during, an
        `LLMServer` loop tick). The plain `check_donation=False` form
        is read-only and always safe.
        """
        out = {"executables": self._step_fn.cache_size()}
        if self._fused_fn is not None:
            # ONE fused executable per (k, geometry) — window spill and
            # EOS mid-window ride arguments, never a re-trace
            out["fused_executables"] = self._fused_fn.cache_size()
        if self._spec is not None:
            # ONE verify executable per (spec_k, geometry) — narrow
            # windows ride the width/rem arguments, never a re-trace
            out["verify_executables"] = self._spec._verify_fn.cache_size()
        if not check_donation:
            return out
        from .. import analysis

        rep = analysis.analyze_step(self, check_donation=True)
        out["donation"] = rep.donation
        _DONATION_HELD.labels(step="paged_decode").set(
            1.0 if rep.donation["held"] else 0.0)
        if self._fused_fn is not None:
            frep = analysis.analyze_step(self, check_donation=True,
                                         which="fused")
            out["fused"] = {"donation": frep.donation,
                            "host_calls": frep.host_calls}
            _DONATION_HELD.labels(step="fused_decode").set(
                1.0 if frep.donation["held"] else 0.0)
        if self._spec is not None:
            vrep = analysis.analyze_step(self, check_donation=True,
                                         which="verify")
            out["verify"] = {"donation": vrep.donation,
                             "host_calls": vrep.host_calls}
            _DONATION_HELD.labels(step="spec_verify").set(
                1.0 if vrep.donation["held"] else 0.0)
            # BOTH kv pytrees of the speculative contract: the draft
            # propose scan donates the draft pools + shared key too.
            # The n-gram speculator has no propose executable (its
            # proposals are host-mined), so only the verify probe
            # applies there.
            if getattr(self._spec, "_propose_fn", None) is not None:
                prep = analysis.analyze_step(self, check_donation=True,
                                             which="propose")
                out["propose"] = {"donation": prep.donation,
                                  "host_calls": prep.host_calls}
                _DONATION_HELD.labels(step="spec_propose").set(
                    1.0 if prep.donation["held"] else 0.0)
        return out

    def reseed(self, seed):
        """Swap the sampling PRNG key. The key is a step ARGUMENT (not
        a baked constant), so this never recompiles — pinned by the
        recompile probe in tests/test_fused_decode.py."""
        from ..distributed import mesh as mesh_mod

        self._seed = int(seed)
        self._key = jax.device_put(
            jax.random.PRNGKey(self._seed), mesh_mod.named_sharding())

    def pool_bytes(self):
        """Resident KV pool bytes across layers — int8 scale planes
        and the speculative draft pool included (a shared page costs
        big-bytes + draft-bytes; docs/SERVING.md has the sizing)."""
        total = int(sum(int(a.nbytes) for a in self._kv)
                    + sum(int(s.nbytes) for s in self._kv_scales))
        if self._spec is not None:
            total += self._spec.pool_bytes()
        return total

    # ---- disaggregated serving: KV-page export / import ----
    # (fleet_serving.kv_transfer; docs/SERVING.md "Disaggregated
    # fleet"). Both run on the thread that owns the engine — they read/
    # replace the donated pool arrays, so calling them while a step is
    # dispatching from another thread would race the donation.

    def export_kv_pages(self, req):
        """Cut the request's KV pages (every layer pool + scale plane,
        byte-for-byte, the partially-filled frontier page included)
        into a `fleet_serving.KVPagePayload`. The request keeps its
        pages — export is a read.

        The device gather runs at the FIXED `pages_per_seq` width
        (pad index 0 = the trash page, rows sliced off on the host):
        a per-page-count gather shape would compile one executable
        per distinct prompt length — a mid-traffic stall on exactly
        the prefill-storm path the disaggregation exists to protect."""
        from .fleet_serving.kv_transfer import KVPagePayload

        n = len(req.pages)
        kv, scales = self._gather_pages(req.pages)
        self.stats["kv_pages_exported"] = (
            self.stats.get("kv_pages_exported", 0) + n)
        req.trace.stamp("kv_export")
        return KVPagePayload(np.asarray(req.tokens, np.int32),
                             req.n_prefilled, self.page_size,
                             self.kv_dtype, kv, scales,
                             trace=req.trace.to_dict())

    def _gather_pages(self, page_ids):
        """ONE batched D2H gather of `page_ids` rows from every layer
        pool + scale plane, at the FIXED `pages_per_seq` width (pad
        index 0 = the trash page, rows sliced off on the host): the
        shared primitive of request export, trie-node spill, and
        hot-prefix migration — one gather shape, one executable,
        whatever the page count. Returns (kv, scales) owned host
        arrays (the PR-14 snapshot half: safe to hand to a background
        thread while the pool reuses the pages)."""
        n = len(page_ids)
        ids_np = np.zeros((self.pages_per_seq,), np.int32)
        ids_np[:n] = page_ids
        ids = jnp.asarray(ids_np)
        # ONE batched host transfer for all pools + scale planes (a
        # per-pool device_get would serialize 2L+ round trips inside
        # the serve loop, on the prefill-storm path)
        gathered = jax.device_get([p[ids] for p in self._kv]
                                  + [s[ids] for s in self._kv_scales])
        kv = [np.ascontiguousarray(a[:n])
              for a in gathered[:len(self._kv)]]
        scales = [np.ascontiguousarray(a[:n])
                  for a in gathered[len(self._kv):]]
        return kv, scales

    def import_kv_pages(self, payload, **kw):
        """Admit one request whose prompt KV arrives pre-computed (a
        prefill replica's `export_kv_pages`). The payload's tokens are
        the prompt; decoding starts at its frontier, so the first tick
        samples the first generated token without re-running the
        prompt. Accepts the `add_request` keyword surface."""
        return self.add_request(payload.tokens, kv_import=payload, **kw)

    def _empty_payload(self, toks, trace=None):
        from .fleet_serving.kv_transfer import KVPagePayload

        if trace is not None:
            trace.stamp("kv_export")
        return KVPagePayload(
            toks, 0, self.page_size, self.kv_dtype,
            [np.zeros((0,) + p.shape[1:], np.asarray(p[:0]).dtype)
             for p in self._kv],
            [np.zeros((0,) + s.shape[1:], np.float32)
             for s in self._kv_scales],
            trace=trace.to_dict() if trace is not None else None)

    def _check_import(self, req, payload):
        """Loud geometry validation at submit time (an import that
        reinterprets pages under a different page_size / kv_dtype /
        head layout would serve garbage logits, not an error)."""
        if payload.page_size != self.page_size:
            raise ValueError(
                f"kv_import page_size {payload.page_size} != engine "
                f"page_size {self.page_size}")
        if payload.kv_dtype != self.kv_dtype:
            raise ValueError(
                f"kv_import kv_dtype {payload.kv_dtype!r} != engine "
                f"kv_dtype {self.kv_dtype!r} (pools must match "
                "byte-for-byte; re-prefill instead)")
        if len(payload.kv) != len(self._kv):
            raise ValueError(
                f"kv_import carries {len(payload.kv)} pools, engine "
                f"has {len(self._kv)} (different num_layers?)")
        if len(payload.scales) != len(self._kv_scales):
            raise ValueError(
                "kv_import scale planes do not match the engine pool "
                f"({len(payload.scales)} vs {len(self._kv_scales)})")
        # EVERY pool and scale plane, not just kv[0]: a ragged payload
        # (per-layer page counts or a mis-shaped scale plane) must be
        # rejected here — failing later inside _write_imported_pages
        # would abort the whole serve loop (and every co-resident
        # request) for one bad payload. ALL mismatches ride one error:
        # a ragged payload usually disagrees in several pools at once,
        # and the first-mismatch-only message made the operator fix
        # and resubmit once per pool (satellite fix, ISSUE 17)
        n_pages = payload.num_pages
        bad = []
        for i, a in enumerate(payload.kv):
            want = (n_pages,) + tuple(self._kv[i].shape[1:])
            if tuple(a.shape) != want:
                bad.append(f"pool {i} shape {tuple(a.shape)} != {want}")
        for i, a in enumerate(payload.scales):
            want = (n_pages,) + tuple(self._kv_scales[i].shape[1:])
            if tuple(a.shape) != want:
                bad.append(f"scale plane {i} shape {tuple(a.shape)} "
                           f"!= {want}")
        if bad:
            raise ValueError(
                f"kv_import geometry mismatch (engine page geometry "
                f"x {n_pages} pages), {len(bad)} failing arrays: "
                + "; ".join(bad))
        if not 0 <= payload.n_prefilled <= req.prompt_len - 1:
            raise ValueError(
                f"kv_import n_prefilled {payload.n_prefilled} outside "
                f"[0, prompt_len-1] ({req.prompt_len - 1}): the decode "
                "side owns the frontier token")
        need = -(-payload.n_prefilled // self.page_size)
        if payload.num_pages != need:
            raise ValueError(
                f"kv_import ships {payload.num_pages} pages but "
                f"n_prefilled {payload.n_prefilled} needs {need}")

    def _write_imported_pages(self, page_ids, payload):
        """Write the payload's page rows into this engine's pools at
        freshly-allocated page ids — byte-for-byte (no dequant/requant:
        the parity the wire test pins). Replaces the pool arrays;
        re-committed to the pools' sharding so the next compiled-step
        dispatch sees the SAME placement signature (a committed/
        uncommitted flip would cost a second executable). Like the
        export gather, the scatter runs at the FIXED `pages_per_seq`
        width — pad rows land in trash page 0, whose rows are never
        attended — so every import reuses ONE compiled scatter instead
        of one per distinct page count (a mid-traffic compile stall on
        the decode tier's admission path)."""
        if not page_ids:
            return
        from ..distributed import mesh as mesh_mod
        from .fleet_serving.kv_transfer import _KV_PAGES_STREAMED

        sharding = mesh_mod.named_sharding()
        n = len(page_ids)
        ids_np = np.zeros((self.pages_per_seq,), np.int32)
        ids_np[:n] = page_ids
        ids = jnp.asarray(ids_np)

        def pad(rows):
            out = np.zeros((self.pages_per_seq,) + rows.shape[1:],
                           rows.dtype)
            out[:n] = rows
            return jnp.asarray(out)

        updated = [pool.at[ids].set(pad(rows))
                   for pool, rows in zip(self._kv, payload.kv)]
        updated += [plane.at[ids].set(pad(rows))
                    for plane, rows in zip(self._kv_scales,
                                           payload.scales)]
        # one batched placement for the whole pytree (mirrors the
        # export-side batching)
        placed = jax.device_put(updated, sharding)
        self._kv = placed[:len(self._kv)]
        self._kv_scales = placed[len(self._kv):]
        self.stats["kv_pages_imported"] = (
            self.stats.get("kv_pages_imported", 0) + len(page_ids))
        _KV_PAGES_STREAMED.inc(len(page_ids))

    # ---- hierarchical KV memory (fleet_serving.kv_tier, ISSUE 17) ----

    def _spill_node(self, node):
        """RadixPrefixCache spill hook: one dying trie node's pages
        D2H (synchronous snapshot through the SAME fixed-width gather
        export uses — the pages are reused the moment `_drop` frees
        them) and into the tier's spill queue (asynchronous commit —
        pack + index + disk never touch the engine thread). Keyed by
        the node's FULL token prefix; the payload carries only the
        node's own block pages (parents are separate entries).
        Swallows its own failures: eviction is relieving pool
        pressure, a lost spill only re-costs the re-prefill."""
        from .fleet_serving.kv_transfer import KVPagePayload
        from .fleet_serving.kv_tier import _TIER_EVICTIONS, prefix_key

        try:
            blocks = []
            n = node
            while n.block is not None:
                blocks.append(n.block)
                n = n.parent
            blocks.reverse()
            toks = np.asarray([t for blk in blocks for t in blk],
                              np.int32)
            kv, scales = self._gather_pages(node.pages)
            payload = KVPagePayload(toks, int(toks.size),
                                    self.page_size, self.kv_dtype,
                                    kv, scales)
            _TIER_EVICTIONS.labels(tier="hbm").inc(len(node.pages))
            if self.kv_tier.put(prefix_key(toks), payload):
                self._spill_count += 1
                self.stats["kv_pages_spilled"] = (
                    self.stats.get("kv_pages_spilled", 0)
                    + len(node.pages))
        except Exception:   # never block the eviction path
            self.stats["kv_spill_errors"] = (
                self.stats.get("kv_spill_errors", 0) + 1)

    def _prefetch_tier(self, req, cached, pages):
        """Extend a trie match from the spill tiers: for each block
        past the trie frontier whose prefix the tier holds, allocate
        fresh pages, scatter the frame H2D through the SAME fixed-width
        import executable (`_write_imported_pages` — zero recompiles),
        and re-insert the node so the request (and everyone after it)
        maps it as an ordinary trie hit. Stops at the first tier miss,
        a dry pool, or block `pages_per_seq` coverage. Returns the
        extended (cached, pages); `pages` grows by the engine's OWN
        alloc references (released through the ordinary request-page
        path, exactly like match()'s share references)."""
        from .fleet_serving.kv_tier import prefix_key

        bt = self.prefix_cache.block_tokens
        ppb = self.prefix_cache.pages_per_block
        toks = req.tokens
        hit = False
        while (cached + bt <= len(toks)
               and (len(pages) + ppb) <= self.pages_per_seq):
            payload = self.kv_tier.get(prefix_key(toks[:cached + bt]))
            if payload is None:      # tier miss (or a rotten frame)
                break
            new_pages = []
            try:
                for _ in range(ppb):
                    new_pages.append(self._alloc_page())
            except PoolExhausted:
                # prefetch must never starve the request's own prompt
                # pages — give back and serve what we have
                self.pool.free(new_pages)
                break
            self._write_imported_pages(new_pages, payload)
            self.prefix_cache.insert(toks[:cached + bt],
                                     pages + new_pages)
            pages.extend(new_pages)
            cached += bt
            hit = True
            self.stats["kv_pages_prefetched"] = (
                self.stats.get("kv_pages_prefetched", 0) + ppb)
        if hit:
            req.trace.stamp("kv_prefetch")
        return cached, pages

    def export_prefix(self, tokens):
        """Cut the trie's longest cached prefix of `tokens` into a
        `KVPagePayload` — the cross-replica migration source (router
        `_migrate`; docs/SERVING.md "KV memory hierarchy"). The
        payload satisfies the kv_import frontier contract for a
        request with these exact tokens (n_prefilled <= len-1, page
        count exact), so the pulling replica admits it through the
        ordinary import scatter — zero recompiles on either engine —
        and publishes it into ITS trie at the first window boundary.
        Returns None when nothing is cached. Engine-thread only (rides
        the LLMServer control queue)."""
        if self.prefix_cache is None:
            return None
        from .fleet_serving.kv_transfer import KVPagePayload

        toks = np.asarray(tokens).reshape(-1)
        cached, pages = self.prefix_cache.match(toks)
        bt = self.prefix_cache.block_tokens
        # the import contract leaves the frontier token to the decode
        # side: a fully-covered prompt exports one block less
        while pages and cached >= toks.size:
            cached -= self.prefix_cache.cow_split(pages)
        if not pages:
            return None
        kv, scales = self._gather_pages(pages)
        self.pool.free(pages)   # match()'s share refs, returned
        self.stats["kv_pages_migrated_out"] = (
            self.stats.get("kv_pages_migrated_out", 0) + cached // bt
            * self.prefix_cache.pages_per_block)
        return KVPagePayload(toks, cached, self.page_size,
                             self.kv_dtype, kv, scales)

    # ---- persistent chat sessions (ISSUE 17) ----

    def _touch_session(self, sid):
        """Create/refresh one session entry; TTL/LRU-expire the rest.
        Returns True when the session already existed (a RETURNING
        turn — the resume-telemetry precondition). Engine thread (and
        add_request callers driving the engine directly)."""
        from .fleet_serving.kv_tier import _SESSION_ACTIVE

        now = _time.perf_counter()
        seen = sid in self._sessions
        ent = self._sessions.pop(sid, None) or {"turns": 0}
        ent["last_used"] = now
        self._sessions[sid] = ent
        # cheap sweep at the LRU head: expiry only ever drops the
        # TRACKING entry — the session's KV ages out through the
        # ordinary trie-LRU -> tier-LRU path like any other prefix
        while self._sessions:
            head = next(iter(self._sessions))
            if (len(self._sessions) > self.session_max
                    or (now - self._sessions[head]["last_used"]
                        > self.session_ttl_s)):
                del self._sessions[head]
            else:
                break
        _SESSION_ACTIVE.set(len(self._sessions))
        return seen

    def _publish_session(self, req):
        """Pin a finished session turn: insert EVERY full block of the
        final token sequence — generated tokens included, unlike the
        prompt-only `_publish_prefix` — so the next turn (whose prompt
        embeds this turn's history) resumes from the conversation
        frontier. The trie holds the reference after `_release` frees
        the request's own ('pinned'); under pool pressure the blocks
        spill to the tier like any node ('tiered')."""
        if (req.session_id is None or self.prefix_cache is None
                or self._brownout.get("session_pin", True) is False):
            return
        bt = self.hash_block_tokens
        ppb = self.prefix_cache.pages_per_block
        nb = req.n_prefilled // bt     # only KV-written rows publish
        if nb:
            self.prefix_cache.insert(req.tokens[:nb * bt],
                                     req.pages[:nb * ppb])
        ent = self._sessions.get(req.session_id)
        if ent is not None:
            ent["turns"] += 1

    def _finish_prefill(self, slot, req):
        """Retire a prefill-only request AT its frontier: export the
        payload, release the slot/pages, resolve the future to the
        payload (docs/SERVING.md "Disaggregated fleet")."""
        req.trace.stamp("prefill_end")
        payload = self.export_kv_pages(req)
        self._note_timeline(req)
        self._release(slot, req)
        self.stats["finished"] += 1
        self.stats["prefill_exports"] = (
            self.stats.get("prefill_exports", 0) + 1)
        _FINISHED_TOTAL.inc()
        if not req.future.cancelled():
            req.future.set_result(payload)

    def _note_timeline(self, req):
        """Record the request's phase timeline (reqtrace) for the
        `metrics()["recent_requests"]` drill-down. Quiet traces
        (warm-up requests — their prefill segment is an XLA compile
        stall, not serving latency) stay out of the view."""
        if req.trace.quiet:
            return
        self._timelines.append({
            "rid": req.rid, "trace_id": req.trace.trace_id,
            "phases": req.trace.timeline(),
            # unrounded like the timeline's dt_s: the exported
            # invariant is sum(dt_s) == total_s (to float addition
            # error) — rounding one side would break it by up to 5e-7
            "total_s": req.trace.total_s()})

    def kv_fragmentation(self):
        """Internal fragmentation of the live KV pages: unwritten
        slots / (live pages × page_size). High values mean many
        sequences holding mostly-empty tail pages (page_size too big
        for the workload). Counted as per-request tail waste — NOT as
        1 − Σ n_prefilled / capacity, which double-counts shared-prefix
        tokens once per sharer and pins the gauge to 0 exactly when the
        prefix cache is busiest. Unwritten slots live only in a
        request's PRIVATE tail pages (shared and trie pages are full by
        construction), so the sum never double-counts."""
        cap = self.pool.num_live * self.page_size
        if not cap:
            return 0.0
        waste = sum(len(r.pages) * self.page_size - r.n_prefilled
                    for r in self._slots if r is not None)
        return max(0.0, waste / cap)

    def metrics(self):
        """Live engine view + the process-global serving counters from
        the telemetry registry (docs/OBSERVABILITY.md) — what
        `LLMServer.metrics()` and the bench's llm_serve arm report."""
        live = sum(r is not None for r in self._slots)
        return {
            "queue_depth": len(self.waiting),
            "live_slots": live,
            "num_slots": self.num_slots,
            "kv_dtype": self.kv_dtype,
            "kv_pool_bytes": self.pool_bytes(),
            "slot_occupancy": live / self.num_slots,
            "mean_slot_occupancy": self.mean_occupancy,
            "kv_page_occupancy":
                self.pool.num_live / (self.pool.num_pages - 1),
            "kv_fragmentation": self.kv_fragmentation(),
            "kv_pages_shared": self.pool.num_shared,
            "prefix_cache": (self.prefix_cache.snapshot()
                             if self.prefix_cache is not None else None),
            "sched": self.sched.snapshot(),
            "requests": int(_REQS_TOTAL.value),
            "finished": int(_FINISHED_TOTAL.value),
            "preemptions": int(_PREEMPTIONS_TOTAL.value),
            "steps": int(_STEPS_TOTAL.value),
            "aborts": int(_ABORTS_TOTAL.value),
            "prefill_tokens":
                int(_TOKENS_TOTAL.labels(phase="prefill").value),
            "decode_tokens":
                int(_TOKENS_TOTAL.labels(phase="decode").value),
            "decode_k": self.decode_k,
            "spec": self._spec_metrics(),
            "ngram": self._ngram_metrics(),
            "structured": self._structured_metrics(),
            "fused_steps": int(_FUSED_STEPS.value),
            "dispatches": int(_DISPATCHES.value),
            "tokens_per_dispatch": _TOK_PER_DISPATCH.value,
            "admission_p50_s": _ADMIT_SECONDS.quantile(0.5),
            "admission_p99_s": _ADMIT_SECONDS.quantile(0.99),
            "ttft_p50_s": _TTFT_SECONDS.quantile(0.5),
            "ttft_p95_s": _TTFT_SECONDS.quantile(0.95),
            "ttft_p99_s": _TTFT_SECONDS.quantile(0.99),
            "request_tok_per_s_p50": _REQ_TOK_RATE.quantile(0.5),
            # TTFT decomposition (observability.reqtrace): per-phase
            # percentiles + the last requests' full timelines
            "request_phase_seconds": _reqtrace.phase_summary(),
            "recent_requests": list(self._timelines),
            "executables": self._step_fn.cache_size(),
            "kv_tier": self._tier_metrics(),
            "sessions": {"active": len(self._sessions),
                         "resumed": self.stats.get("sessions_resumed",
                                                   0),
                         "shed": self.stats.get("sessions_shed", 0)},
        }

    def _tier_metrics(self):
        """kv_tier block of `metrics()`: None without a tier; else the
        store snapshot, with the hbm rung's gauges published alongside
        (the tier store only sees ram/disk — the device pool IS the
        top rung, so its live-page footprint reports here)."""
        if self.kv_tier is None:
            return None
        from .fleet_serving.kv_tier import _TIER_BYTES, _TIER_PAGES

        live = self.pool.num_live
        per_page = self.pool_bytes() / max(1, self.pool.num_pages)
        _TIER_PAGES.labels(tier="hbm").set(live)
        _TIER_BYTES.labels(tier="hbm").set(int(live * per_page))
        return self.kv_tier.snapshot()

    def _spec_metrics(self):
        """Speculative-decoding block of `metrics()`: None without a
        draft model; else the window/acceptance view (counters are
        PROCESS-cumulative — docs/OBSERVABILITY.md; the per-engine
        window/proposed/accepted splits ride `stats`). The n-gram
        speculator reports under the `ngram` block instead — its
        counters are a different family."""
        if self._spec is None or getattr(self._spec, "mode",
                                         "draft") != "draft":
            return None
        from .speculative import (_SPEC_ACCEPTED, _SPEC_DRAFT_SECONDS,
                                  _SPEC_PROPOSED)

        proposed = _SPEC_PROPOSED.value
        return {
            "spec_k": self._spec.k,
            "windows": self.stats.get("spec_windows", 0),
            "proposed": int(proposed),
            "accepted": int(_SPEC_ACCEPTED.value),
            "acceptance_rate": (
                _SPEC_ACCEPTED.value / proposed if proposed else None),
            "draft_seconds": round(float(_SPEC_DRAFT_SECONDS.value), 4),
            "draft_pool_bytes": self._spec.pool_bytes(),
        }

    def _ngram_metrics(self):
        """n-gram speculation block of `metrics()`: None unless this
        engine runs spec_mode='ngram'."""
        if getattr(self._spec, "mode", None) != "ngram":
            return None
        proposed = self.stats.get("ngram_proposed", 0)
        accepted = self.stats.get("ngram_accepted", 0)
        return {
            "spec_k": self._spec.k,
            "windows": self.stats.get("ngram_windows", 0),
            "proposed": int(proposed),
            "accepted": int(accepted),
            "acceptance_rate": (accepted / proposed if proposed
                                else None),
        }

    def _structured_metrics(self):
        """Structured-decoding block of `metrics()`: None unless the
        engine has token_strs (the constraint surface enabled).
        Engine-local counts — the `pt_structured_*` counters are
        process-cumulative across every engine in the process."""
        if self.token_strs is None:
            return None
        gc = self._grammar_cache.snapshot()
        return {
            "grammars_resident": len(self.grammar_arena._loaded),
            "states_used": self.grammar_arena.states_used,
            "state_budget": self.grammar_arena.n_states,
            "requests": self.stats.get("structured_requests", 0),
            "compiles": gc["compiles"],
            "cache_hits": gc["cache_hits"],
            "rejects": gc["rejects"],
        }

    def abort_all(self, exc):
        """Fail every live and queued request (device-error path),
        release all pages, and re-zero the pools — a step that died
        mid-donation leaves the old kv buffers deleted, so the engine
        must not reuse them."""
        try:
            from ..observability import flight_recorder as _fr

            _fr.dump("engine_abort", error=repr(exc), inflight=[
                {"rid": r.rid, "trace_id": r.trace.trace_id}
                for r in self._slots if r is not None])
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the flight-recorder dump itself)
            pass
        for slot, req in enumerate(self._slots):
            if req is not None:
                self._release(slot, req)
                if not req.future.done():
                    req.future.set_exception(exc)
        for req in self.sched.drain():
            if not req.future.done():
                req.future.set_exception(exc)
        if self.prefix_cache is not None:
            # the re-zeroed pools invalidate every cached KV page — a
            # stale trie mapping would serve zeros as a system prompt
            self.prefix_cache.clear()
        self._kv, self._kv_scales = self._fresh_pools()
        if self._spec is not None:
            # the draft pools ride their own donated pytree through the
            # draft executables — same consumed-buffer hazard
            self._spec.reset_pools()
        # the PRNG key rides the SAME donated pytree as the pools — a
        # consumed key leaf would wedge the recovered engine on its
        # next dispatch ("Array has been deleted")
        self.reseed(self._seed)
        _ABORTS_TOTAL.inc()
        _QUEUE_DEPTH.set(0)
        _LIVE_SLOTS.set(0)
        _SLOT_OCC.set(0.0)

    def abort(self, request_id, reason="client", exc=None,
              counted=False):
        """Evict ONE request (client cancel / deadline expiry) wherever
        it lives. A slot occupant releases through `_release` — pool
        pages decref (shared trie pages keep the trie's own reference;
        the request's pins go), the page-table row zeroes, and its
        draft-pool rows need no touch (keyed by slot, overwritten by
        the next occupant's catch-up). A queued request leaves the
        scheduler with exact class/SLO bookkeeping (`sched.remove`).
        The future resolves with `exc` (default: RequestCancelled)
        unless already done. Returns False when the id is unknown —
        already finished — and touches nothing. Co-resident requests
        are unperturbed: no pool re-zero, no reseed, no executable
        churn (contrast `abort_all`). `counted=True` means the caller
        (the router's `cancel`) already counted this cancellation —
        pt_requests_cancelled_total stays exact, one per request."""
        rid = int(request_id)
        for slot, req in enumerate(self._slots):
            if req is not None and req.rid == rid:
                self._release(slot, req)
                self._resolve_cancel(req, reason, exc, counted=counted)
                live = sum(r is not None for r in self._slots)
                _LIVE_SLOTS.set(live)
                _SLOT_OCC.set(live / self.num_slots if self.num_slots
                              else 0.0)
                return True
        for req in list(self.sched):
            if req.rid == rid:
                if not self.sched.remove(req):
                    return False
                self._resolve_cancel(req, reason, exc, counted=counted)
                _QUEUE_DEPTH.set(len(self.sched))
                return True
        return False

    def _resolve_cancel(self, req, reason, exc=None, counted=False):
        """Shared tail of every cancellation path: count, stamp the
        phase timeline, flight-record (trace_id rides the event), and
        resolve the client future typed."""
        if not counted:
            note_cancelled(reason)
        req.trace.stamp("cancelled")
        self._note_timeline(req)
        try:
            from ..observability import flight_recorder as _fr

            _fr.record_event("request_cancelled", rid=req.rid,
                             trace_id=req.trace.trace_id, reason=reason)
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the trace event itself)
            pass
        if not req.future.done():
            req.future.set_exception(
                exc if exc is not None
                else RequestCancelled(reason=reason,
                                      trace_id=req.trace.trace_id))

    def _shed_at_admit(self, req, reason):
        """Typed admission refusal (add_request): the future RESOLVES
        with RequestShed — no fleet work was consumed, nothing to
        release. Returns the request (add_request's contract)."""
        note_shed(reason)
        try:
            from ..observability import flight_recorder as _fr

            _fr.record_event("request_shed", rid=req.rid,
                             trace_id=req.trace.trace_id, reason=reason)
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the trace event itself)
            pass
        if not req.future.done():
            req.future.set_exception(
                RequestShed(reason, trace_id=req.trace.trace_id))
        return req

    def _expire_deadlines(self):
        """Cancel every live/queued request whose hard deadline passed
        (top of step(), armed only once a deadline request exists)."""
        now = _time.perf_counter()
        hit = False
        for slot, req in enumerate(self._slots):
            if (req is not None and req.deadline_t is not None
                    and now > req.deadline_t):
                self._release(slot, req)
                self._resolve_cancel(req, "deadline")
                hit = True
        stale = [r for r in self.sched
                 if r.deadline_t is not None and now > r.deadline_t]
        for req in stale:
            if self.sched.remove(req):
                self._resolve_cancel(req, "deadline")
                hit = True
        if hit:
            live = sum(r is not None for r in self._slots)
            _LIVE_SLOTS.set(live)
            _SLOT_OCC.set(live / self.num_slots if self.num_slots
                          else 0.0)
            _QUEUE_DEPTH.set(len(self.sched))

    # ---- brownout (fleet_serving.overload) ----

    def apply_brownout(self, caps):
        """Install the fleet's brownout caps (BrownoutController
        apply_fn; {} = full service). Runs on the router monitor
        thread: the dict is replaced WHOLE (GIL-atomic) and read at
        host decision points only (admission caps, window clamps); the
        spec park/restore transition runs on the engine thread at the
        top of step() (`_sync_brownout`) — the draft pytree is only
        ever touched by the thread that dispatches on it."""
        self._brownout = dict(caps)

    def _sync_brownout(self):
        """Engine-thread half of the ladder's L2: park the speculative
        decoder and RELEASE its draft pool (the HBM returns to the
        fleet now, not at the next GC), or restore it — `reset_pools`
        rebuilds zeroed pools and the slots' draft_prefilled reset
        makes the next window's catch-up replay the draft KV."""
        caps = self._brownout
        enabled = caps.get("spec_enabled", True)
        if self._spec is not None and enabled is False:
            self._spec_stash, self._spec = self._spec, None
            self._spec_stash.release_pools()
            _KV_POOL_BYTES.labels(dtype=self.kv_dtype).set(
                self.pool_bytes())
        elif (self._spec is None and self._spec_stash is not None
                and enabled):
            self._spec, self._spec_stash = self._spec_stash, None
            self._spec.reset_pools()
            for r in self._slots:
                if r is not None:
                    r.draft_prefilled = 0   # draft pool is cold: replay
            _KV_POOL_BYTES.labels(dtype=self.kv_dtype).set(
                self.pool_bytes())
        # ladder L4: shed session pinning BEFORE shedding traffic —
        # only the TRACKING entries drop (future turns stop resuming);
        # already-pinned trie blocks age out through ordinary trie LRU
        if caps.get("session_pin", True) is False and self._sessions:
            from .fleet_serving.kv_tier import _SESSION_ACTIVE

            self.stats["sessions_shed"] = (
                self.stats.get("sessions_shed", 0)
                + len(self._sessions))
            self._sessions.clear()
            _SESSION_ACTIVE.set(0)

    def close(self):
        """Retire the engine: drop the prefix trie (its clear()
        publishes the NEGATIVE resident-pages delta, so a process that
        cycles engines doesn't leave pt_prefix_cache_resident_pages
        permanently inflated by gc'd tries). Idempotent; the engine
        stays usable — the trie just starts cold."""
        if self.prefix_cache is not None:
            self.prefix_cache.clear()
        if self.kv_tier is not None:
            self.kv_tier.close()

    # ---- scheduler ----

    def _release(self, slot, req):
        self.pool.free(req.pages)  # shared pages decref; trie keeps its
        req.pages = []             # own reference, private pages free
        req.n_prefilled = 0
        req.draft_prefilled = 0   # preemption replay re-prefills BOTH pools
        req.cached_prefix = 0
        req.published_blocks = 0
        req.slot = None
        self._page_tables[slot, :] = 0
        self._slots[slot] = None
        self._slot_gen += 1  # membership changed: staged arrays stale

    def _finish(self, slot, req):
        # session pinning reads req.pages — must precede the release
        self._publish_session(req)
        self._release(slot, req)
        self.stats["finished"] += 1
        _FINISHED_TOTAL.inc()
        if req.t_first_admit is not None and req.num_generated:
            dt = _time.perf_counter() - req.t_first_admit
            if dt > 0:
                _REQ_TOK_RATE.observe(req.num_generated / dt)
        # a client may have cancel()ed while the request was in flight —
        # set_result would raise InvalidStateError and the server loop
        # would read that as a device error and abort EVERYONE
        if not req.future.cancelled():
            req.future.set_result(req.result_array())

    def _preempt(self, slot, req, reason):
        """Evict-and-requeue one RUNNING sequence (the explicit
        preemption path: pool/slot exhaustion never surfaces as
        `PoolExhausted` while a lower-priority victim exists). The
        already-generated tokens are kept: greedy re-decode of
        prompt+generated reproduces the same continuation, so a
        preempted request stays deterministic — and with the prefix
        cache on, its replayed prefill re-hits the trie."""
        self._release(slot, req)
        req.preemptions += 1
        self.stats["preemptions"] += 1
        _PREEMPTIONS_TOTAL.inc()
        self.sched.note_preemption(reason)
        self.sched.push_front(req)

    def _preempt_one(self, keep_req, worse_than=None, reason="pool",
                     allow_equal=False):
        """Preempt the scheduler's victim pick (lowest priority class,
        then youngest). Returns False when there is no victim (or none
        `worse_than` allows)."""
        pick = self.sched.pick_victim(
            self._slots, keep=keep_req, worse_than=worse_than,
            now=_time.perf_counter(), allow_equal=allow_equal)
        if pick is None:
            return False
        self._preempt(*pick, reason=reason)
        return True

    def _alloc_page(self):
        """Pool alloc with prefix-cache pressure relief: a dry pool
        first reclaims LRU trie-only pages before the caller has to
        preempt anything."""
        try:
            return self.pool.alloc()
        except PoolExhausted:
            if (self.prefix_cache is not None
                    and self.prefix_cache.evict(1) > 0):
                return self.pool.alloc()
            raise

    def _map_prefix(self, req):
        """Match the request's tokens against the radix trie and map
        the shared pages. Returns the mapped page list (the request now
        holds one pool reference per page); `req.cached_prefix` tokens
        of prefill will be SKIPPED. Copy-on-write cap: at least one
        token must run through the model (the frontier logit), and its
        KV write may not land in a shared page — a fully-cached prompt
        splits its tail block back to private recompute."""
        cached, pages = self.prefix_cache.match(req.tokens)
        if self.kv_tier is not None:
            # extend the trie frontier from the spill tiers BEFORE the
            # COW cap: a prefetched block re-enters the trie, so the
            # fully-covered case splits its tail back like any hit
            cached, pages = self._prefetch_tier(req, cached, pages)
        splits = 0
        while pages and cached >= len(req.tokens):
            cached -= self.prefix_cache.cow_split(pages)
            splits += 1
        req.cached_prefix = cached
        req._cow_pending = splits
        return pages

    def _publish_prefix(self, req):
        """Register the request's newly-completed full PROMPT blocks in
        the radix trie (its own mapped blocks are already there —
        insert is idempotent). Generated-token pages stay private:
        the fleet workload shares SYSTEM PROMPTS, and restricting the
        trie to prompt content keeps its size bounded by distinct
        prompts, not distinct continuations."""
        bt = self.hash_block_tokens
        covered = min(req.n_prefilled, req.prompt_len)
        nblocks = covered // bt
        if nblocks > req.published_blocks:
            ppb = self.prefix_cache.pages_per_block
            self.prefix_cache.insert(req.tokens[:nblocks * bt],
                                     req.pages[:nblocks * ppb])
            req.published_blocks = nblocks

    def _try_admit(self, req):
        """Place one popped request into a slot: prefix-cache mapping,
        page-fit check (with trie eviction and lowest-priority
        preemption as pressure valves), page-table setup. Returns False
        — with every transient reference released — when the request
        cannot be placed yet."""
        spills0 = self._spill_count    # kv_spill phase stamp baseline
        # cheap bails FIRST — a blocked head-of-queue request must not
        # pay a full prefix match, a share/free refcount round-trip,
        # and an O(trie) feasibility walk on every engine tick.
        # (a) no free slot AND no legal victim:
        if None not in self._slots:
            now = _time.perf_counter()
            if not any(r is not None
                       and self.sched.less_urgent(r, req, now)
                       for r in self._slots):
                return False
        # speculative k-token reservation (docs/SERVING.md): leave one
        # page of headroom per live frontier slot so a burst of
        # admissions can't drain the pool to where every verify window
        # collapses to 1-token widths — admission waits behind the
        # windows' working set, it never starves (runners finish and
        # the headroom shrinks with them)
        headroom = (self._spec.window_headroom()
                    if self._spec is not None else 0)
        # (b) pool provably short even in the BEST case: the trie can
        # map at most resident_pages into the prompt and reclaim at
        # most resident_pages more, so free + victims + 2·resident <
        # prompt pages is infeasible regardless of what match() finds —
        # O(slots) with no trie walk
        need_all = -(-len(req.tokens) // self.page_size)
        if self.pool.num_free - headroom < need_all:
            now = _time.perf_counter()
            avail = self.pool.num_free - headroom + sum(
                len(r.pages) for r in self._slots if r is not None
                and self.sched.less_urgent(r, req, now))
            resident = (self.prefix_cache.resident_pages
                        if self.prefix_cache is not None else 0)
            if avail + 2 * resident < need_all:
                return False
        # an imported request's prompt KV arrives in its payload — a
        # trie mapping on top would alias pages the import must write
        pages = (self._map_prefix(req)
                 if self.prefix_cache is not None
                 and req._kv_import is None else [])

        def give_up():
            if pages:
                self.pool.free(pages)
            req.cached_prefix = 0
            return False

        # feasibility FIRST: preempting a runner destroys its generated
        # progress, so don't start evicting until a slot AND enough
        # reclaimable pages can possibly exist. `reclaimable` is an
        # upper bound (a page shared by two victims counts twice) — the
        # loops below still give up cleanly when eviction falls short.
        # Skipped entirely on the uncontended fast path (free slot +
        # pool already covers the prompt): the trie walk is O(nodes).
        need = (-(-len(req.tokens) // self.page_size) - len(pages)
                + headroom)
        if None not in self._slots or self.pool.num_free < need:
            now = _time.perf_counter()
            victims = [r for r in self._slots if r is not None
                       and self.sched.less_urgent(r, req, now)]
            if None not in self._slots and not victims:
                return give_up()
            reclaimable = self.pool.num_free + sum(
                len(r.pages) for r in victims)
            if self.prefix_cache is not None:
                reclaimable += self.prefix_cache.reclaimable_pages()
            if reclaimable < need:
                return give_up()
        # a slot: free one, or preempt a strictly-less-urgent runner
        if None not in self._slots:
            if not self._preempt_one(None, worse_than=req,
                                     reason="priority"):
                return give_up()
        # the prompt's remaining pages must fit (head-of-class
        # blocking: a short prompt never jumps its own class's queue)
        while self.pool.num_free < need:
            short = need - self.pool.num_free
            if (self.prefix_cache is not None
                    and self.prefix_cache.evict(short) > 0):
                continue
            if not self._preempt_one(None, worse_than=req,
                                     reason="priority"):
                return give_up()
        slot = self._slots.index(None)
        req.slot = slot
        req.admit_seq = next(self._admit_counter)
        req.pages = list(pages)
        req.n_prefilled = req.cached_prefix
        if req._kv_import is not None:
            # disaggregated hand-off: write the streamed pages and join
            # at the frontier. The payload is CONSUMED — a later
            # preemption replay re-prefills the prompt the ordinary way
            # (greedy replay reproduces the identical continuation).
            imp, req._kv_import = req._kv_import, None
            req.pages = [self._alloc_page()
                         for _ in range(imp.num_pages)]
            self._write_imported_pages(req.pages, imp)
            req.n_prefilled = imp.n_prefilled
            req.trace.stamp("kv_import")
        # mirrored draft pool: a shared page's draft rows were written
        # by the publishing request's own catch-up (same page ids, same
        # tokens, same draft model), so the mapped prefix is draft-valid
        # too. Worst case — a publisher that never ran a spec window —
        # leaves garbage draft rows there: proposals from them get
        # REJECTED by the lossless verify, costing acceptance rate,
        # never correctness.
        req.draft_prefilled = (req.cached_prefix
                               if self._spec is not None else 0)
        req.published_blocks = req.cached_prefix // self.hash_block_tokens
        self._page_tables[slot, :] = 0
        self._page_tables[slot, :len(req.pages)] = req.pages
        self._slots[slot] = req
        self._slot_gen += 1  # membership changed: staged arrays stale
        if self.prefix_cache is not None:
            self.prefix_cache.note_mapped(
                req.cached_prefix, pages,
                cow_splits=getattr(req, "_cow_pending", 0))
        if req.t_first_admit is None:
            req.t_first_admit = _time.perf_counter()
            _ADMIT_SECONDS.observe(req.t_first_admit - req.t_submit)
        # phase stamps (first-wins: a preemption replay re-admits
        # without rewriting the original timeline)
        if self._spill_count > spills0:
            # this admission's pool pressure pushed trie pages to the
            # spill tier (prefix_cache.evict -> _spill_node)
            req.trace.stamp("kv_spill")
        if self.kv_tier is not None and req.cached_prefix > 0:
            from .fleet_serving.kv_tier import _TIER_HITS

            _TIER_HITS.labels(tier="hbm").inc()
        if (req.session_id is not None and req._session_seen
                and req.cached_prefix > 0):
            from .fleet_serving.kv_tier import _SESSION_RESUMED

            req._session_seen = False   # one resume per turn, not replay
            _SESSION_RESUMED.inc()
            self.stats["sessions_resumed"] = (
                self.stats.get("sessions_resumed", 0) + 1)
        if req.n_prefilled < len(req.tokens) - 1:
            req.trace.stamp("prefill_start")
        else:
            # a full import / full trie hit: the frontier is already
            # covered, no prefill ever runs on this engine
            req.trace.stamp("prefill_end")
        if (req.prefill_only
                and req.n_prefilled >= req.prompt_len - 1):
            # an import (or full trie hit) already covers the frontier:
            # nothing left for this replica to compute
            self._finish_prefill(slot, req)
        return True

    def _admit(self):
        now = _time.perf_counter()
        while self.sched:
            req = self.sched.pop_next(now)
            if req is None:
                break
            if not self._try_admit(req):
                self.sched.push_front(req)
                break

    def _active(self):
        """Running sequences in admission order (deterministic plan)."""
        return sorted(
            ((slot, req) for slot, req in enumerate(self._slots)
             if req is not None),
            key=lambda it: it[1].admit_seq)

    def _plan(self, only_slots=None):
        """Allot this step's flat token budget: one frontier token per
        running sequence first, then chunked prefill FIFO. Allocates the
        pages the planned tokens will write; a dry pool preempts the
        youngest sequence and replans. `only_slots` restricts the plan
        to those slots (the ragged-window straggler tick: frontier rows
        already took their window this step); victims of a dry pool are
        still picked from ALL running sequences."""
        while True:
            active = self._active()
            if only_slots is not None:
                active = [(s, r) for s, r in active if s in only_slots]
            if not active:
                return None
            alloc = {}
            budget = self.token_budget - len(active)
            for slot, req in active:
                remaining = len(req.tokens) - req.n_prefilled
                if req.prefill_only:
                    # the frontier token belongs to the DECODE side of
                    # the disaggregated hand-off: stop one short, so no
                    # logit is ever computed (and no token sampled) on
                    # a prefill replica
                    remaining -= 1
                take = 1 + min(remaining - 1, budget)
                budget -= take - 1
                alloc[slot] = take
            ok = True
            for slot, req in active:
                last = req.n_prefilled + alloc[slot] - 1
                try:
                    while last // self.page_size >= len(req.pages):
                        page = self._alloc_page()
                        self._page_tables[slot, len(req.pages)] = page
                        req.pages.append(page)
                except PoolExhausted:
                    # the victim may be no MORE urgent than the growing
                    # sequence: a BATCH job's page growth must never
                    # evict an INTERACTIVE runner (equal urgency keeps
                    # the pre-fleet preempt-youngest baseline)
                    if not self._preempt_one(req, worse_than=req,
                                             allow_equal=True):
                        kept = -(-len(req.tokens) // self.page_size)
                        if (kept <= self.pool.num_pages - 1
                                and any(r is not None and r is not req
                                        for r in self._slots)):
                            # every other runner outranks req: req
                            # itself yields its pages and requeues
                            self._preempt(slot, req, reason="pool")
                        else:
                            # kept tokens outgrew the whole pool:
                            # unservable even alone — requeueing would
                            # spin _try_admit forever
                            self._release(slot, req)
                            if not req.future.done():
                                req.future.set_exception(PoolExhausted(
                                    f"request {req.rid} needs more KV "
                                    f"pages than the pool holds"))
                    ok = False
                    break
            if ok:
                return [(slot, req, alloc[slot]) for slot, req in active]

    def step(self):
        """One scheduler tick: admit (deferred — new and preempted
        sequences only ever join HERE, i.e. at window boundaries) →
        either ONE multi-token decode window (speculative when a draft
        model is configured, else the fused k-scan when decode_k > 1)
        over the rows at their sampling frontier, or one single-tick
        compiled step → evict finished. Returns the list of requests
        finished this tick.

        RAGGED WINDOWS (the PR-8 leftover, fixed): a straggler row
        still chunk-prefilling no longer forces the whole engine onto
        single ticks — the frontier rows take their window and the
        straggler gets a prefill-only single tick in the same
        `step()` call (two dispatches, full progress on both fronts).
        The straggler joins windows at the boundary after its prefill
        completes, and per-request greedy/sampled outputs are
        schedule-invariant, so nothing observable changes per request."""
        self._sync_brownout()
        if self._deadlines_armed:
            self._expire_deadlines()
        self._admit()
        if self._spec is not None or self.decode_k > 1:
            active = self._active()
            frontier = [(s, r) for s, r in active
                        if r.n_prefilled == len(r.tokens) - 1]
            if frontier:
                for _s, r in frontier:
                    if r.num_generated == 0:
                        r.trace.stamp("first_decode_dispatch")
                out = (self._spec.try_window(frontier)
                       if self._spec is not None
                       else self._try_step_fused(frontier))
                if out is not None:
                    stragglers = {s for s, r in active
                                  if r.n_prefilled != len(r.tokens) - 1}
                    if stragglers:
                        out = out + self._step_tick(
                            only_slots=stragglers)
                    return out
        return self._step_tick()

    # ---- fused multi-token decode window ----

    def _ensure_fused(self):
        """The fused k-step executable, built lazily: decode_k and the
        engine geometry are fixed per engine, so this is ONE executable
        per (k, config) — the zero-recompile probe's contract."""
        if self._fused_fn is None:
            self._fused_fn = _CompiledFusedStep(
                self.model, self.decode_k, self.page_size)
        return self._fused_fn

    def _try_step_fused(self, active):
        """One fused decode window over `active` (the caller's frontier
        rows — every one at its sampling frontier), or None when the
        pool cannot cover even a 1-token window (the single-tick path
        takes the tick and owns preemption). Page capacity for the
        window is reserved UP FRONT; when the pool (or a sequence's
        budget) can't cover a full k, the window spills to k' = what
        fits via the `rem` argument — the scan length never changes, so
        spill never recompiles."""
        if not active:
            return None
        ps = self.page_size
        k = self.decode_k

        def pages_needed(w):
            tot = 0
            for _, req in active:
                writes = min(w, req.target - len(req.tokens))
                last = req.n_prefilled + writes - 1
                tot += max(0, last // ps + 1 - len(req.pages))
            return tot

        avail = self.pool.num_free
        if self.prefix_cache is not None:
            avail += self.prefix_cache.reclaimable_pages()
        # brownout window cap: a smaller w rides the `rem` runtime
        # argument of the SAME k-scan executable — degrading the window
        # never recompiles (overload.BrownoutController L3)
        cap = self._brownout.get("decode_k_cap")
        w = k if cap is None else max(1, min(k, int(cap)))
        while w > 1 and pages_needed(w) > avail:
            w -= 1        # spill: the largest window the pool covers
        if pages_needed(w) > avail:
            return None   # not even 1 token/row: single tick preempts

        # reserve the window's pages up front (_alloc_page evicts LRU
        # trie pages under pressure; reclaimable was an upper bound, so
        # a short row spills further instead of failing the window)
        rem_arg = {}
        for slot, req in active:
            want = min(w, req.target - len(req.tokens))
            last = req.n_prefilled + want - 1
            try:
                while last // ps >= len(req.pages):
                    page = self._alloc_page()
                    self._page_tables[slot, len(req.pages)] = page
                    req.pages.append(page)
                writes = want
            except PoolExhausted:
                writes = min(want,
                             len(req.pages) * ps - req.n_prefilled)
                if writes < 1:
                    return None
            rem_arg[slot] = writes

        S = self.num_slots
        tok0 = np.zeros((S,), np.int32)
        pos0 = np.zeros((S,), np.int32)
        rem = np.zeros((S,), np.int32)
        fin0 = np.ones((S,), bool)        # empty slots: finished
        eos = np.full((S,), -1, np.int32)
        temps = np.zeros((S,), np.float32)
        tops = np.ones((S,), np.float32)
        streams = np.zeros((S,), np.int32)
        gen_before = {}
        for slot, req in active:
            tok0[slot] = req.tokens[-1]
            pos0[slot] = req.n_prefilled
            rem[slot] = rem_arg[slot]
            fin0[slot] = False
            if req.eos is not None:
                eos[slot] = int(req.eos)
            temps[slot] = req.temperature
            tops[slot] = req.top_p
            streams[slot] = req.sample_stream
            gen_before[slot] = req.num_generated

        gst, gtrans, gmask = self._grammar_args(active)
        fused = self._ensure_fused()
        t0 = _time.perf_counter()
        try:
            with _trace_span("llm_engine.fused_step", k=k,
                             live=len(active)):
                emits, (self._kv, self._kv_scales, self._key) = fused(
                    tok0, pos0, rem, fin0, eos, temps, tops, streams,
                    gst, gtrans, gmask, self._page_tables,
                    (self._kv, self._kv_scales, self._key))
                emits = np.asarray(emits)   # the once-per-k host sync
        except Exception as e:
            # same contract as the single tick: the donated pytree may
            # already be consumed — fail in-flight work and re-zero
            self.abort_all(e)
            raise
        # k-boundary SLO accounting: tell the scheduler how long a
        # window runs so escalation checks fire a boundary EARLY
        # instead of a boundary late (docs/SERVING.md)
        self.sched.note_boundary(_time.perf_counter() - t0)

        self.stats["steps"] += 1
        self.stats["fused_steps"] += 1
        self.stats["occupancy_sum"] += len(active) / self.num_slots
        _STEPS_TOTAL.inc()
        _FUSED_STEPS.inc()
        _DISPATCHES.inc()

        finished = []
        now = _time.perf_counter()
        total = 0
        for slot, req in active:
            emitted, done = 0, False
            for j in range(int(rem[slot])):
                t = int(emits[j, slot])
                req.tokens.append(t)
                if req.grammar is not None:
                    # host replay of the in-scan DFA advance: gstate
                    # stays a pure function of the emitted tokens
                    req.gstate = req.grammar.advance(req.gstate, t)
                emitted += 1
                if ((req.eos is not None and t == req.eos)
                        or len(req.tokens) >= req.target):
                    done = True   # in-executable masking already
                    break         # padded the rest of the window
            req.n_prefilled += emitted
            total += emitted
            self.stats["generated"] += emitted
            self.sched.note_tokens(req.tenant, emitted)
            if gen_before[slot] == 0 and emitted > 0:
                ttft = now - req.t_submit
                req.t_first_token = now
                req.trace.stamp("first_token")
                self._note_timeline(req)
                _TTFT_SECONDS.observe(ttft)
                self.sched.note_first_token(req, ttft)
            if done:
                self._finish(slot, req)
                finished.append(req)
        self.stats["tokens_in"] += total
        _TOKENS_TOTAL.labels(phase="decode").inc(total)
        _TOK_PER_DISPATCH.set(total)
        _QUEUE_DEPTH.set(len(self.waiting))
        # whole-engine load — `active` is only the window's frontier
        # rows; a chunk-prefilling straggler still occupies its slot
        live = sum(r is not None for r in self._slots)
        _LIVE_SLOTS.set(live)
        _SLOT_OCC.set(live / self.num_slots)
        _PAGE_OCC.set(self.pool.num_live / (self.pool.num_pages - 1))
        _PAGE_FRAG.set(self.kv_fragmentation())
        return finished

    # ---- single-tick step (prefill / mixed / k=1) ----

    def _host_sample_rows(self, lv, reqs):
        """Temperature/top-p (+ greedy rows) for a host tick's frontier
        logits — the SAME `sample_tokens` math the fused scan runs
        in-executable, position-keyed on the SAME engine key, so a
        request's draws are identical whichever path serves the tick
        (that invariance is what makes sampled outputs reproducible
        across decode_k — tests/test_fused_decode.py pins it).

        Padded to num_slots so the jitted sampler traces ONCE per
        engine: the frontier row count varies tick-to-tick with
        arrivals/finishes, and a per-count specialization would stall
        the serving loop on a fresh vocab-sort compile mid-traffic."""
        if self._host_sample is None:
            from ..text.models.gpt import sample_tokens

            self._host_sample = jax.jit(sample_tokens)
        n, S = len(reqs), self.num_slots
        temps = np.zeros((S,), np.float32)   # pad rows: greedy, key 0
        tops = np.ones((S,), np.float32)
        streams = np.zeros((S,), np.int32)
        positions = np.zeros((S,), np.int32)
        for j, r in enumerate(reqs):
            temps[j] = r.temperature
            tops[j] = r.top_p
            streams[j] = r.sample_stream
            positions[j] = len(r.tokens)  # index the new token takes
        lv = jnp.pad(lv, ((0, S - n), (0, 0)))
        return self._host_sample(lv, temps, tops, streams, positions,
                                 self._key)[:n]

    def _step_tick(self, only_slots=None):
        """One single-tick compiled step: plan → dispatch → sample
        frontiers on the host → evict finished. `only_slots` is the
        ragged-window straggler tick (prefill-only rows; see step())."""
        plan = self._plan(only_slots)
        if plan is None:
            return []

        T = self.token_budget
        # pure-decode staging cache: when every planned row is a
        # 1-token sampling frontier AND slot membership is unchanged,
        # sid / sample_idx are IDENTICAL to last tick's — reuse the
        # device-committed copies instead of rebuilding and re-uploading
        # them every tick (keyed on the slot-assignment generation).
        # Never staged for a restricted straggler tick: its row set is
        # a subset the generation counter doesn't describe.
        staged = None
        if only_slots is None and all(
                take == 1 and len(req.tokens) - req.n_prefilled == 1
                for _, req, take in plan):
            staged = self._stage
            if staged is None or staged["gen"] != self._slot_gen:
                from ..distributed import mesh as mesh_mod

                sid_np = np.zeros((T,), np.int32)
                sidx_np = np.zeros((self.num_slots,), np.int32)
                for row, (slot, _, _) in enumerate(plan):
                    sid_np[row] = slot
                    sidx_np[slot] = row
                sharding = mesh_mod.named_sharding()
                staged = self._stage = {
                    "gen": self._slot_gen,
                    "slots": [slot for slot, _, _ in plan],
                    "sid": jax.device_put(sid_np, sharding),
                    "sample_idx": jax.device_put(sidx_np, sharding)}
            else:
                self.stats["stage_hits"] += 1

        tok = np.zeros((T,), np.int32)
        pos = np.zeros((T,), np.int32)
        widx = np.zeros((T,), np.int32)   # 0 → trash page, row 0
        klen = np.zeros((T,), np.int32)   # 0 → padding token
        if staged is not None:
            sid = staged["sid"]
            sample_idx = staged["sample_idx"]
            sample_slots = staged["slots"]
            for row, (slot, req, _) in enumerate(plan):
                p = req.n_prefilled
                tok[row] = req.tokens[p]
                pos[row] = p
                widx[row] = (req.pages[p // self.page_size]
                             * self.page_size + p % self.page_size)
                klen[row] = p + 1
                if req.num_generated == 0:
                    req.trace.stamp("first_decode_dispatch")
            i = len(plan)
        else:
            from ..distributed import mesh as mesh_mod

            sid = np.zeros((T,), np.int32)
            # per-SLOT sampling frontier: the vocab head only runs on
            # these gathered rows (stale slots point at row 0; logits
            # ignored)
            sample_idx = np.zeros((self.num_slots,), np.int32)
            sample_slots = []
            i = 0
            for slot, req, take in plan:
                for k in range(take):
                    p = req.n_prefilled + k
                    tok[i] = req.tokens[p]
                    pos[i] = p
                    sid[i] = slot
                    widx[i] = (req.pages[p // self.page_size]
                               * self.page_size + p % self.page_size)
                    klen[i] = p + 1
                    if p == len(req.tokens) - 1:
                        sample_idx[slot] = i
                        sample_slots.append(slot)
                        if req.num_generated == 0:
                            # this dispatch carries the frontier row:
                            # prefill ends and decode begins HERE (in
                            # that order — the timeline reads left to
                            # right even when one dispatch does both)
                            req.trace.stamp("prefill_end")
                            req.trace.stamp("first_decode_dispatch")
                    i += 1
            # committed like the staged copies: a committed/uncommitted
            # flip at one arg position would cost a second executable
            sharding = mesh_mod.named_sharding()
            sid = jax.device_put(sid, sharding)
            sample_idx = jax.device_put(sample_idx, sharding)

        try:
            with _trace_span("llm_engine.step", tokens=i,
                             live=len(plan)):
                logits, (self._kv, self._kv_scales, self._key) = \
                    self._step_fn(
                        tok, pos, sid, widx, self._page_tables, klen,
                        sample_idx,
                        (self._kv, self._kv_scales, self._key))
        except Exception as e:
            # the donated pools may already be consumed by the failed
            # dispatch — fail the in-flight work and re-zero so a
            # direct-drive caller's engine stays serviceable (the server
            # loop's own abort_all then finds nothing left to do)
            self.abort_all(e)
            raise

        self.stats["steps"] += 1
        self.stats["tokens_in"] += i
        self.stats["occupancy_sum"] += len(plan) / self.num_slots
        _STEPS_TOTAL.inc()
        _DISPATCHES.inc()
        # a ragged-window straggler tick covers only the PREFILL rows —
        # its plan must not overwrite the window's whole-engine load
        # gauges with straggler-only values (7 decoding rows + 1
        # straggler would read as 1/8 occupancy), and the window's
        # tokens-per-dispatch amortization stamp stays unless this
        # tick actually decoded something
        live_now = (len(plan) if only_slots is None
                    else sum(r is not None for r in self._slots))
        if only_slots is None or sample_slots:
            _TOK_PER_DISPATCH.set(len(sample_slots))
        # the flat-budget split: one decode token per sampling frontier,
        # everything else is (chunked or preemption-replay) prefill
        _TOKENS_TOTAL.labels(phase="decode").inc(len(sample_slots))
        _TOKENS_TOTAL.labels(phase="prefill").inc(i - len(sample_slots))
        _QUEUE_DEPTH.set(len(self.waiting))
        _LIVE_SLOTS.set(live_now)
        _SLOT_OCC.set(live_now / self.num_slots)
        _PAGE_OCC.set(self.pool.num_live / (self.pool.num_pages - 1))

        nxt = []
        if sample_slots:
            rows = jnp.asarray(sample_slots, jnp.int32)
            lv = jnp.take(logits[0], rows, axis=0).astype(jnp.float32)
            frontier = [self._slots[s] for s in sample_slots]
            if any(r.grammar is not None for r in frontier):
                # host-path grammar masking: mask the logit VALUES
                # before the (single-trace) jitted sampler / argmax —
                # identical picks to the in-scan mask, zero new traces
                allow = np.ones((len(frontier), lv.shape[1]), bool)
                for jr, r in enumerate(frontier):
                    if r.grammar is not None:
                        allow[jr] = r.grammar.allowed_np(r.gstate)
                lv = jnp.where(jnp.asarray(allow), lv,
                               jnp.float32(-1e30))
            if any(r.do_sample for r in frontier):
                nxt = np.asarray(self._host_sample_rows(lv, frontier))
            else:
                # greedy frontier sampling — same pick as generate()'s
                # default path, so outputs stay token-identical
                nxt = np.asarray(jnp.argmax(lv, axis=-1))

        finished = []
        for slot, req, take in plan:
            req.n_prefilled += take
            if req.n_prefilled >= len(req.tokens) - 1:
                # the sampling frontier is reached: prefill is over
                # (first-wins — steady-state decode ticks are no-ops)
                req.trace.stamp("prefill_end")
            # per-tenant fair-queuing meter: flat tokens actually spent
            self.sched.note_tokens(req.tenant, take)
            if self.prefix_cache is not None:
                self._publish_prefix(req)
            if (req.prefill_only
                    and req.n_prefilled >= len(req.tokens) - 1):
                # disaggregated hand-off: the frontier is reached —
                # export the pages and retire (publish above already
                # registered the full prompt blocks in the trie)
                self._finish_prefill(slot, req)
                finished.append(req)
        _PAGE_FRAG.set(self.kv_fragmentation())
        now = _time.perf_counter()
        for slot, tok_id in zip(sample_slots, nxt):
            req = self._slots[slot]
            t = int(tok_id)
            req.tokens.append(t)
            if req.grammar is not None:
                req.gstate = req.grammar.advance(req.gstate, t)
            self.stats["generated"] += 1
            if req.num_generated == 1:      # replays don't re-count
                ttft = now - req.t_submit
                req.t_first_token = now
                req.trace.stamp("first_token")
                self._note_timeline(req)
                _TTFT_SECONDS.observe(ttft)
                self.sched.note_first_token(req, ttft)
            if ((req.eos is not None and t == req.eos)
                    or len(req.tokens) >= req.target):
                self._finish(slot, req)
                finished.append(req)
        return finished


# the full `LLMServer.submit` kwarg surface — remote ingresses
# (FleetRouter.submit) screen unknown kwargs against this set so a
# typo'd knob raises at submit() time with its name, instead of dying
# as a TypeError inside a replica's serve loop
SUBMIT_KWARGS = frozenset((
    "max_new_tokens", "eos_token_id", "tenant", "priority",
    "ttft_slo_s", "temperature", "top_p", "prefill_only", "kv_import",
    "trace", "deadline_s", "session_id", "grammar", "json_schema",
    "spec_mode"))


class LLMServer(_FutureQueueServer):
    """Continuous-batching text-generation server: the future/queue
    surface of `InferenceServer` over an `LLMEngine` (module docstring
    has the usage). One background thread owns the engine; `submit` is
    thread-safe."""

    _thread_name = "llm-engine"

    def __init__(self, model, config=None):
        super().__init__()
        self._engine = LLMEngine(model, config)
        self.stats = self._engine.stats  # shared view + request counts
        self.stats.setdefault("requests", 0)
        self._http = None

    @property
    def engine(self):
        return self._engine

    def metrics(self):
        """Engine telemetry snapshot (registry-sourced; see
        LLMEngine.metrics). Thread-safe: reads only."""
        return self._engine.metrics()

    def start_metrics_http(self, port=0, host="127.0.0.1"):
        """Optional stdlib-only pull endpoint: GET /metrics serves the
        process registry in Prometheus text format, /metrics.json the
        full snapshot with this engine's view under "extra". port=0
        picks a free port; returns the handle (`.url`, `.port`).
        Stopped automatically with the server."""
        if self._http is None:
            from ..observability import start_http_server

            self._http = start_http_server(port=port, host=host,
                                           extra_json=self.metrics)
        return self._http

    def stop(self):
        super().stop()
        self._engine.close()
        if self._http is not None:
            self._http.stop()
            self._http = None

    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               tenant="default", priority=None, ttft_slo_s=None,
               temperature=0.0, top_p=1.0, prefill_only=False,
               kv_import=None, trace=None, deadline_s=None,
               session_id=None, grammar=None, json_schema=None,
               spec_mode=None):
        """Enqueue one prompt (1-D int token ids). Returns a Future
        resolving to np.int64 [prompt + generated] (eos kept, nothing
        after it) — or, with `prefill_only=True`, to the exported
        `fleet_serving.KVPagePayload` (the disaggregated hand-off;
        `kv_import` is the receiving side — see
        `LLMEngine.add_request`). The engine-side `_Request` is
        attached to the future as `fut.pt_request` once ingested (the
        router's TTFT source; None until the engine thread picks the
        submission up).

        Fleet fields (docs/SERVING.md): `tenant` groups requests for
        token-budget fair queuing, `priority` is a
        `fleet_serving.Priority` class (default STANDARD), and
        `ttft_slo_s` sets this request's TTFT SLO for deadline
        boosting and the attainment gauge. `session_id` marks the
        request as one turn of a persistent chat session (docs/
        SERVING.md "KV memory hierarchy"): its FINAL token sequence —
        generated tokens included — is pinned into the prefix trie on
        finish, so the next turn's prompt (which embeds this turn's
        history) resumes from the conversation frontier instead of
        re-prefilling it; under pool pressure the pinned blocks spill
        to the host/disk tier and prefetch back on resume.

        Sampling: `temperature` 0 (default) decodes greedily,
        token-identical to generate(); > 0 samples the temperature-
        scaled, `top_p`-truncated distribution, seeded from the engine
        PRNG key and keyed on (stream, position) — reproducible for a
        given engine seed whatever decode_k is.

        Structured decoding (docs/SERVING.md "Structured decoding"):
        `grammar=` (regex / CompiledGrammar) or `json_schema=` (dict)
        constrain the output tokens; `spec_mode=` opts a request out
        of ("off") or restates the engine's speculation mode. All
        three validate — and the grammar COMPILES, through the
        engine's hash-keyed cache — on THIS thread, so a malformed
        constraint raises here at submit() with the offending kwarg
        named, never inside the serve loop where it would abort
        co-resident requests (same hardening as `_check_import`)."""
        # loud submit-time gate: structural validation + engine-context
        # checks + grammar compile (GrammarError over the table budget)
        grammar = self._engine._resolve_constraint(
            grammar, json_schema, eos_token_id, spec_mode)
        fut = Future()
        fut.pt_request = None
        # trace identity minted at the INGRESS (this thread), so the
        # `queued` stamp covers the server queue, not just the engine's
        # — unless the payload already carries one (the cross-process
        # decode half: recv_and_decode -> submit_imported must CONTINUE
        # the prefill tier's trace, not start a fresh id)
        if trace is None and kv_import is not None:
            trace = _payload_trace(kv_import)
        if trace is None:
            trace = _reqtrace.new_trace()
        trace.stamp("queued")
        self._enqueue(dict(
            prompt=np.asarray(prompt).reshape(-1),
            max_new_tokens=int(max_new_tokens),
            eos_token_id=eos_token_id, future=fut, tenant=tenant,
            priority=priority, ttft_slo_s=ttft_slo_s,
            temperature=float(temperature), top_p=float(top_p),
            prefill_only=bool(prefill_only), kv_import=kv_import,
            trace=trace, deadline_s=deadline_s,
            session_id=session_id, grammar=grammar,
            json_schema=None, spec_mode=spec_mode))
        return fut

    def export_prefix(self, tokens):
        """Cut the engine trie's longest cached prefix of `tokens`
        into a `KVPagePayload` (or None) — the hot-prefix migration
        source the router's pull path calls on the donor replica. The
        cut runs on the ENGINE thread (a control message on the same
        queue as submissions — the trie and pools are engine-thread
        state); this returns a Future resolving to the payload."""
        fut = Future()
        self._enqueue({"_export_prefix":
                       np.asarray(tokens).reshape(-1),
                       "_export_future": fut})
        return fut

    def generate(self, prompt, max_new_tokens=32, eos_token_id=None):
        return self.submit(prompt, max_new_tokens, eos_token_id).result()

    def abort(self, request_id, reason="client", counted=False):
        """Cancel ONE in-flight request by its engine rid (overload
        control plane; docs/SERVING.md "Overload and degradation").
        The abort rides the SAME queue as submissions, so the engine
        thread applies it between steps — no cross-thread engine
        access. Unknown/finished rids are a no-op on the engine; the
        caller (router `cancel`) owns the client-future resolution
        (and, with `counted=True`, the cancellation count)."""
        self._enqueue({"_abort": int(request_id),
                       "_abort_reason": str(reason),
                       "_abort_counted": bool(counted)})

    def _ingest(self, payload):
        if "_abort" in payload:   # control message, not a submission
            try:
                self._engine.abort(payload["_abort"],
                                   reason=payload.get("_abort_reason",
                                                      "client"),
                                   counted=payload.get("_abort_counted",
                                                       False))
            except Exception:  # ptlint: disable=PTL804 (abort of unknown rid is a no-op; never kill the serve loop)
                pass
            return
        if "_export_prefix" in payload:
            fut = payload["_export_future"]
            try:
                res = self._engine.export_prefix(
                    payload["_export_prefix"])
                if not fut.cancelled():
                    fut.set_result(res)
            except Exception as e:
                if not fut.done():
                    fut.set_exception(e)
            return
        fut = payload.pop("future")
        if fut.cancelled():
            # client cancelled between submit and ingest: the request
            # never reaches the engine (resolving a cancelled future
            # would raise InvalidStateError out of the serve loop)
            return
        try:
            fut.pt_request = self._engine.add_request(future=fut,
                                                      **payload)
            self.stats["requests"] += 1
        except Exception as e:  # bad request must not kill the loop
            if not fut.done():
                fut.set_exception(e)

    def _tick_hook(self):
        """Per-loop-iteration hook (fleet replica runtime: heartbeat +
        chaos kill — fleet_serving.replica overrides). Returning True
        aborts the serve loop DEAD: no drain, no future resolution —
        the process-death shape the router's failover requeues."""
        return False

    def _loop(self):
        eng = self._engine
        while self._running or not self._q.empty() or eng.has_work():
            if self._tick_hook():
                return
            try:
                while True:
                    self._ingest(self._q.get_nowait())
            except queue.Empty:
                pass
            if not eng.has_work():
                # idle: block briefly for the next submission
                try:
                    self._ingest(self._q.get(timeout=0.05))
                except queue.Empty:
                    continue
            try:
                eng.step()
            except Exception as e:  # defensive: never die silently
                eng.abort_all(e)
