"""SLA-aware multi-tenant scheduler — admission policy for `LLMEngine`.

FIFO admission treats a batch tenant's 4k-token backfill job and an
interactive tenant's 20-token chat turn as equals; under load the chat
turn queues behind the backfill and its TTFT SLO dies. This scheduler
replaces arrival order with three composed policies, all host-side (the
compiled decode step never sees any of it):

* **Priority classes** — `Priority.INTERACTIVE < STANDARD < BATCH`
  (lower value = more urgent). Admission always prefers the most urgent
  non-empty class; within a class tenants share; within a tenant, FIFO.

* **Per-tenant token-budget fair queuing** — each tenant accrues the
  flat tokens (prefill + decode) the engine actually spent on it,
  divided by its configured weight. Among same-priority tenants the
  LEAST-served tenant's head request admits next (deficit-style: a
  flooding tenant cannot starve a light one; a tenant that was idle has
  low usage and catches up immediately).

* **TTFT SLO deadline boost** — a request carrying `ttft_slo_s` (or
  inheriting the policy default) whose wait exceeds
  `slo_boost_fraction × slo` escalates above every priority class,
  earliest deadline first. SLO attainment is tracked at first-token
  time and published as the `pt_sched_ttft_slo_attainment` gauge
  (the same stamp feeding the engine's pt_llm_ttft_seconds histogram).

Preemption: on slot or pool exhaustion the engine asks `pick_victim`
for the LOWEST-priority running sequence (tie: youngest admission) —
evict-and-requeue instead of raising `PoolExhausted` — counted by
`pt_sched_preemptions{reason=pool|priority}`. With every request on
default tenant/priority all three policies degrade to exact FIFO plus
preempt-youngest, the pre-fleet engine semantics (pinned by
tests/test_llm_engine.py passing unchanged).
"""
import collections
import itertools

from ...observability import metrics as _obs

__all__ = ["Priority", "SLAPolicy", "SLAScheduler"]

_PREEMPTIONS = _obs.counter(
    "pt_sched_preemptions",
    "scheduler preemptions: evict-and-requeue of a running sequence",
    labelnames=("reason",))
_SLO_FIRST_TOKENS = _obs.counter(
    "pt_sched_slo_first_tokens",
    "first tokens of SLO-carrying requests, by TTFT outcome",
    labelnames=("outcome",))
_SLO_ATTAINMENT = _obs.gauge(
    "pt_sched_ttft_slo_attainment",
    "TTFT SLO attainment: met / (met + missed), process-cumulative")


class Priority:
    """Admission urgency classes (lower value = more urgent)."""
    INTERACTIVE = 0
    STANDARD = 1
    BATCH = 2


class SLAPolicy:
    """Scheduler knobs (docs/SERVING.md has the tuning table).

    default_ttft_slo_s  TTFT SLO applied to requests that don't carry
                        their own (None = no SLO tracking by default)
    slo_boost_fraction  fraction of the SLO a request may wait before
                        it escalates above every priority class
    tenant_weights      {tenant: weight} for fair queuing — a weight-2
                        tenant is entitled to 2x the token share of a
                        weight-1 tenant (missing tenants weigh 1.0)
    """

    def __init__(self, default_ttft_slo_s=None, slo_boost_fraction=0.7,
                 tenant_weights=None):
        self.default_ttft_slo_s = default_ttft_slo_s
        self.slo_boost_fraction = float(slo_boost_fraction)
        if not 0.0 < self.slo_boost_fraction <= 1.0:
            raise ValueError("slo_boost_fraction must be in (0, 1]")
        self.tenant_weights = dict(tenant_weights or {})
        for t, w in self.tenant_weights.items():
            if w <= 0:
                raise ValueError(f"tenant {t!r} weight must be > 0")

    def weight(self, tenant):
        return float(self.tenant_weights.get(tenant, 1.0))

    def slo_for(self, req):
        slo = getattr(req, "ttft_slo_s", None)
        return self.default_ttft_slo_s if slo is None else slo


class SLAScheduler:  # ptlint: thread-shared (scraped by /metrics)
    """Waiting-queue policy for `LLMEngine` (module docstring). One
    deque per (priority, tenant); `pop_next` scans queue HEADS only, so
    a tick costs O(active priority-tenant pairs), not O(waiting)."""

    def __init__(self, policy=None):
        self.policy = policy or SLAPolicy()
        self._q = {}      # (priority, tenant) -> deque of requests
        self._used = collections.defaultdict(float)  # tenant -> tokens/w
        self._arrival = itertools.count()
        self._n = 0
        # count of WAITING requests carrying a per-request TTFT SLO —
        # gates pop_next's deeper-than-head escalation scan (heads-only
        # stays O(active pairs) for the SLO-free default, and a
        # saturated never-empty queue returns to it as soon as the last
        # SLO-carrying request pops)
        self._n_slo = 0
        # fused-decode boundary granularity (docs/SERVING.md): with
        # decode_k > 1 the engine only consults the scheduler once per
        # k-token window, so an escalation deadline crossed MID-window
        # would otherwise be noticed one whole window late. The engine
        # feeds the measured window wall time here and _at_risk
        # escalates when the deadline falls before the NEXT boundary.
        self.boundary_lag_s = 0.0
        self.stats = {"preemptions_pool": 0, "preemptions_priority": 0,
                      "slo_met": 0, "slo_missed": 0,
                      "spec_proposed": 0, "spec_accepted": 0}

    @property
    def _any_slo(self):
        return (self.policy.default_ttft_slo_s is not None
                or self._n_slo > 0)

    @staticmethod
    def _counts_slo(req):
        # only a request that can still ESCALATE (no first token yet —
        # _at_risk's own gate) arms the deep scan; a preempted
        # mid-decode request never re-escalates, so it must not flip
        # every tick to O(waiting). t_first_token is stable while the
        # request waits, so enqueue/pop stay balanced.
        return (getattr(req, "ttft_slo_s", None) is not None
                and getattr(req, "t_first_token", None) is None)

    def __len__(self):
        return self._n

    def __bool__(self):
        return self._n > 0

    def __iter__(self):
        """Waiting requests in plain queue order (metrics/abort use).
        list()/tuple() snapshots: a scrape-thread caller must not race
        the engine thread's queue inserts (dict OR deque resize raises
        RuntimeError mid-iteration)."""
        for dq in list(self._q.values()):
            yield from tuple(dq)

    # ---- enqueue side ----

    def enqueue(self, req):
        if getattr(req, "_arrival", None) is None:
            req._arrival = next(self._arrival)
        if self._counts_slo(req):
            self._n_slo += 1
        self._dq(req).append(req)
        self._n += 1

    def push_front(self, req):
        """Return a popped-but-not-admitted (or preempted) request to
        the head of its class queue — it keeps its original arrival
        stamp, so class-internal order is stable."""
        if self._counts_slo(req):
            self._n_slo += 1
        self._dq(req).appendleft(req)
        self._n += 1

    def _dq(self, req):
        key = (int(req.priority), req.tenant)
        dq = self._q.get(key)
        if dq is None:
            dq = self._q[key] = collections.deque()
        return dq

    def remove(self, req):
        """Remove ONE specific waiting request (single-request abort /
        deadline expiry). Returns False when it is not queued here —
        already admitted to a slot, or already finished."""
        key = (int(req.priority), req.tenant)
        dq = self._q.get(key)
        if dq is None:
            return False
        try:
            dq.remove(req)
        except ValueError:
            return False
        self._n -= 1
        if not dq:
            del self._q[key]        # same key hygiene as pop_next
        if self._counts_slo(req):
            self._n_slo -= 1
        return True

    def drain(self):
        """Pop every waiting request (abort path)."""
        out = []
        for dq in list(self._q.values()):
            out.extend(dq)
        self._q.clear()
        self._n = 0
        self._n_slo = 0
        return out

    # ---- admission order ----

    def _at_risk(self, req, now):
        # TTFT is a FIRST-token target: once a request has produced
        # one, escalation ends (its SLO is already met or missed).
        # Keeping it escalated after that point livelocks: a running
        # low-priority request would be preempted by a standard
        # candidate, re-escalate from the queue, preempt the standard
        # one back, and neither would ever finish.
        if getattr(req, "t_first_token", None) is not None:
            return None
        slo = self.policy.slo_for(req)
        if slo is None:
            return None
        # boundary clamp: escalation checks only run at decode-window
        # boundaries, so look one expected window AHEAD — a request
        # whose boost point falls mid-window escalates at the boundary
        # BEFORE it, not the one after its deadline already slipped
        waited = now - req.t_submit + self.boundary_lag_s
        if waited >= self.policy.slo_boost_fraction * float(slo):
            return req.t_submit + float(slo)  # deadline
        return None

    def _eff_priority(self, req, now):
        """Priority with SLO escalation folded in (-1 = escalated) —
        used for BOTH admission candidates and preemption victims, so
        an at-risk sequence a moment from its first token cannot be
        preempted by the very class it just escalated above."""
        return (-1 if self._at_risk(req, now) is not None
                else int(req.priority))

    def _order_key(self, req, now):
        deadline = self._at_risk(req, now)
        if deadline is not None:
            # escalated above every class: earliest deadline first
            return (-1, deadline, req._arrival)
        # .get, not [] — a defaultdict read would materialize a phantom
        # 0.0 meter for every tenant that merely WAITS (snapshot noise
        # + pressure on the _MAX_TENANT_METERS cap)
        return (int(req.priority), self._used.get(req.tenant, 0.0),
                req._arrival)

    def pop_next(self, now):
        """Most-urgent waiting request, or None: SLO-escalated first
        (earliest deadline), then priority class, then least-served
        tenant, then arrival order. Scans per-class queue HEADS —
        except when TTFT SLOs are in play, where deque members are
        scanned too: a buried request with a tight per-request SLO
        must not wait out its deadline behind an un-escalated head.
        Non-head members compete ONLY once escalated, so within-class
        order stays FIFO."""
        best_key, best_q, best_i = None, None, None
        for key, dq in list(self._q.items()):
            if not dq:
                continue
            candidates = enumerate(dq) if self._any_slo else ((0, dq[0]),)
            for i, r in candidates:
                k = self._order_key(r, now)
                if i and k[0] != -1:
                    continue   # buried + not escalated: FIFO holds
                if best_key is None or k < best_key:
                    best_key, best_q, best_i = k, key, i
        if best_q is None:
            return None
        self._n -= 1
        dq = self._q[best_q]
        req = dq[best_i]
        del dq[best_i]
        if not dq:
            # drop emptied class queues: tenant ids are client-supplied,
            # so keys would otherwise accumulate forever and pop_next
            # would scan every tenant EVER seen each engine tick
            del self._q[best_q]
        if self._counts_slo(req):
            # last SLO-carrying waiter gone: back to the heads-only scan
            # even on a saturated queue that never fully drains (one SLO
            # request long ago must not make every future tick
            # O(waiting))
            self._n_slo -= 1
        return req

    # ---- preemption ----

    def pick_victim(self, slots, keep=None, worse_than=None, now=0.0,
                    allow_equal=False):
        """(slot, request) to evict-and-requeue, or None.

        Victim = lowest-priority running sequence (max priority value),
        tie-broken youngest (max admit_seq) — the request with the
        least sunk cost in its class. `keep` is never picked.
        `worse_than` (an admission candidate, or a running sequence
        that needs to GROW) demands a victim no more urgent than its
        effective urgency: STRICTLY less urgent by default — equal
        priorities never preempt each other, which is what keeps the
        default single-class configuration FIFO-stable — while
        `allow_equal=True` admits equal-urgency victims too (the page-
        growth path's pre-fleet preempt-youngest baseline)."""
        victim, vslot, vkey = None, None, None
        for slot, req in enumerate(slots):
            if req is None or req is keep:
                continue
            key = (self._eff_priority(req, now), req.admit_seq)
            if victim is None or key > vkey:
                victim, vslot, vkey = req, slot, key
        if victim is None:
            return None
        if worse_than is not None:
            cand = self._eff_priority(worse_than, now)
            if vkey[0] < cand or (vkey[0] == cand and not allow_equal):
                return None
        return vslot, victim

    def less_urgent(self, a, b, now=0.0):
        """True when running sequence `a` is STRICTLY less urgent than
        admission candidate `b` — i.e. a legal preemption victim for it
        (the engine's admission-feasibility view of `worse_than`)."""
        return self._eff_priority(a, now) > self._eff_priority(b, now)

    def note_preemption(self, reason):
        self.stats[f"preemptions_{reason}"] += 1
        _PREEMPTIONS.labels(reason=reason).inc()

    def note_spec_window(self, proposed, accepted):
        """Per-window speculative accounting (the engine calls this
        once per verify dispatch): draft tokens proposed vs accepted.
        The scheduler tracks it because the acceptance rate IS the
        boundary-granularity knob — each window emits up to
        accepted+1 tokens per slot before the next admission /
        escalation check, so a high-acceptance engine coarsens TTFT
        observability exactly like a larger decode_k would (the
        boundary_lag_s EMA already absorbs the wall-clock side; the
        page accounting side is the engine's per-window k-token
        reservation + admission headroom over the mirrored draft
        pool — docs/SERVING.md "Speculative decoding")."""
        self.stats["spec_proposed"] += int(proposed)
        self.stats["spec_accepted"] += int(accepted)

    def note_boundary(self, window_s):
        """EMA of the fused decode window's wall time — the engine
        calls this once per window so `_at_risk` can clamp escalation
        checks to boundary granularity (module `boundary_lag_s` note).
        Capped at 1 s: a one-off stall must not permanently escalate
        every SLO request a second early."""
        w = min(float(window_s), 1.0)
        self.boundary_lag_s = (w if self.boundary_lag_s == 0.0
                               else 0.5 * self.boundary_lag_s + 0.5 * w)

    # ---- accounting ----

    # fair-queuing meters kept at most (tenant ids are client-supplied:
    # a per-user tenant scheme must not leak one float per user forever)
    _MAX_TENANT_METERS = 10000

    def note_tokens(self, tenant, n):
        """Charge `n` flat tokens (prefill + decode actually scheduled)
        to the tenant's fair-queuing meter."""
        self._used[tenant] += n / self.policy.weight(tenant)
        if len(self._used) > self._MAX_TENANT_METERS:
            # drop the least-served half: their meters sit nearest the
            # fresh-tenant default of 0, so an evicted tenant returns
            # exactly as entitled as a brand-new one
            keep = sorted(self._used.items(), key=lambda kv: kv[1],
                          reverse=True)[:self._MAX_TENANT_METERS // 2]
            self._used = collections.defaultdict(float, keep)

    def note_first_token(self, req, ttft_s):
        slo = self.policy.slo_for(req)
        if slo is None:
            return
        met = ttft_s <= float(slo)
        self.stats["slo_met" if met else "slo_missed"] += 1
        _SLO_FIRST_TOKENS.labels(outcome="met" if met else "missed").inc()
        # the gauge is PROCESS-cumulative (docs/OBSERVABILITY.md), so
        # derive it from the global counters — several engines in one
        # process must not each overwrite it with their local ratio.
        # Under PT_TELEMETRY=0 the counters are no-ops and both read 0:
        # skip the gauge (also a no-op) instead of dividing by zero.
        n_met = _SLO_FIRST_TOKENS.labels(outcome="met").value
        n_missed = _SLO_FIRST_TOKENS.labels(outcome="missed").value
        if n_met + n_missed:
            _SLO_ATTAINMENT.set(n_met / (n_met + n_missed))

    def snapshot(self):
        """Metrics view for `LLMEngine.metrics()`."""
        # list() copies: the metrics HTTP scrape thread snapshots while
        # the engine thread creates/deletes queues and tenant meters
        depths = {f"{prio}:{tenant}": len(dq)
                  for (prio, tenant), dq in list(self._q.items()) if dq}
        met, missed = self.stats["slo_met"], self.stats["slo_missed"]
        # top consumers only: per-user tenant schemes run the meter
        # table to its 10k cap, and every metrics() call / HTTP scrape
        # would otherwise serialize the whole thing
        top = sorted(list(self._used.items()), key=lambda kv: kv[1],
                     reverse=True)[:32]
        return {
            "waiting": self._n,
            "boundary_lag_s": round(self.boundary_lag_s, 6),
            "queue_depths": depths,
            "tenant_meters": len(self._used),
            "tenant_used_tokens": {t: round(u, 1) for t, u in top},
            "preemptions_pool": self.stats["preemptions_pool"],
            "preemptions_priority": self.stats["preemptions_priority"],
            "spec_proposed": self.stats["spec_proposed"],
            "spec_accepted": self.stats["spec_accepted"],
            "spec_acceptance": (
                self.stats["spec_accepted"] / self.stats["spec_proposed"]
                if self.stats["spec_proposed"] else None),
            "slo_met": met, "slo_missed": missed,
            "slo_attainment": (met / (met + missed)
                               if met + missed else None),
        }
