"""paddle_tpu.inference.fleet_serving — serving at fleet economics.

The continuous-batching `LLMEngine` (inference/llm_engine.py) solved
the single-replica problem: live tokens instead of padded batches, one
compiled decode executable, per-request eviction. This package solves
the FLEET problem — millions-of-users traffic where most requests share
a system prompt and tenants with different latency contracts share one
page pool (ROADMAP item 2; PAPERS.md "Fine-Tuning and Serving Gemma on
Cloud TPU" is the serving-economics reference):

* **Radix prefix cache** (`prefix_cache.py`) — a content-addressed
  token trie over full KV pages. A new request whose prompt prefix is
  already resident maps the shared pages read-only into its page table
  and skips their prefill entirely; system prompts amortize to ~zero.
  Copy-on-write: a write that would land in a shared page first splits
  the mapping. LRU eviction reclaims trie-only pages under pool
  pressure. Greedy outputs stay token-identical to the uncached path.

* **SLA scheduler** (`scheduler.py`) — replaces FIFO admission with
  priority classes, per-tenant token-budget fair queuing, TTFT-SLO
  deadline boosting, and an explicit preemption path (evict-and-requeue
  the lowest-priority running sequence) on slot/pool exhaustion.

Both pieces plug into `LLMEngine` via `LLMEngineConfig(prefix_cache=
True, sla_policy=...)` and change NOTHING about the compiled decode
step: sharing and scheduling are host-side page-table/queue policy, so
the zero-recompile contract (ONE executable) holds with the cache on.

The MULTI-REPLICA tier (ISSUE 13) lives here too — N engines behind
one surface:

* **KV-page transfer** (`kv_transfer.py`) — a request's finished KV
  pages (int8/int4 pools AND fp32 scale planes, byte-for-byte) as a
  self-describing payload over the xproc p2p transport: the
  disaggregated prefill→decode hand-off primitive.

* **Replica runtime** (`replica.py`) — `LLMServer`+engine as a fleet
  member: heartbeat registration into elastic-style membership,
  prefill/serve roles, chaos-injectable kill.

* **Fleet router** (`router.py`) — radix-affinity routing (longest
  cached prefix wins, least-loaded fallback), prefill/decode
  disaggregation, SLO autoscale, chaos-proven failover (a killed
  replica's in-flight requests requeue with token-identical greedy
  outputs), and hot-prefix page migration (pull a hot prefix's pages
  to a less-loaded peer over the KV wire instead of routing around
  the miss).

* **KV tier store** (`kv_tier.py`, ISSUE 17) — the memory hierarchy
  below the device pool: trie-evicted pages spill asynchronously to
  host RAM (stored-byte discipline — no re-encode) and age to an
  mmap-friendly disk tier; a trie hit against a spilled prefix
  prefetches back through the one compiled import scatter.

Docs: docs/SERVING.md. Bench: `python bench.py --worker llm_fleet`
(single engine) / `--worker llm_fleet_multi` (the 2-replica A/B).
"""
from .kv_tier import KVTierStore, prefix_key
from .kv_transfer import (KVPagePayload, pack_kv_payload,
                          recv_kv_payload, send_kv_payload,
                          unpack_kv_payload)
from .overload import (DEFAULT_BROWNOUT_LEVELS, BrownoutController,
                       CircuitBreaker, OverloadPolicy, RequestCancelled,
                       RequestShed, TTFTEstimator, note_cancelled,
                       note_hedge, note_shed)
from .prefix_cache import RadixPrefixCache
from .replica import (LocalReplica, ReplicaRegistry, fork_model,
                      recv_and_decode, stream_prefill)
from .router import AutoscalePolicy, FleetRouter
from .scheduler import Priority, SLAPolicy, SLAScheduler

__all__ = ["RadixPrefixCache", "Priority", "SLAPolicy", "SLAScheduler",
           "KVPagePayload", "pack_kv_payload", "unpack_kv_payload",
           "send_kv_payload", "recv_kv_payload",
           "LocalReplica", "ReplicaRegistry", "fork_model",
           "stream_prefill", "recv_and_decode",
           "AutoscalePolicy", "FleetRouter",
           "OverloadPolicy", "RequestShed", "RequestCancelled",
           "TTFTEstimator", "CircuitBreaker", "BrownoutController",
           "DEFAULT_BROWNOUT_LEVELS", "note_shed", "note_cancelled",
           "note_hedge", "KVTierStore", "prefix_key"]
