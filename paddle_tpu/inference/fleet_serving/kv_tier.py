"""Hierarchical KV memory — host-RAM and disk spill tiers under the
serving fleet (ISSUE 17; docs/SERVING.md "KV memory hierarchy").

The radix prefix cache (prefix_cache.py) is bounded by ONE device
pool: when LRU eviction drops a trie node, its KV is recomputed from
scratch on the next hit — a full re-prefill of a prefix the fleet
already paid for. This module keeps evicted prefixes alive below HBM:

* **Spill is the PR-14 snapshot discipline.** The engine gathers the
  dying node's pages device-to-host SYNCHRONOUSLY (one batched
  `device_get` through the same fixed-width gather the KV export uses
  — the pages are about to be reused, so the snapshot cannot wait) and
  hands the OWNED host arrays to this store; everything slow — packing
  the `KVPagePayload` wire frame, the RAM index insert, any disk write
  — runs on a background commit thread that never touches the step
  path. A failed commit journals (`distributed.resilience`) and drops
  the entry: the only cost of a lost spill is the re-prefill the
  eviction was going to cost anyway.

* **Zero re-encode.** Entries are stored as packed `KVPagePayload`
  frames — int4/int8 codes plus fp32 scale planes byte-for-byte as the
  pool held them (the PR-13 wire format IS the spill format), so a
  spill→prefetch round trip is byte-identical (parity-pinned by
  tests/test_kv_tier.py) and quantized pools spill at quantized bytes.

* **RAM over disk, LRU both.** The RAM tier is an LRU dict under a
  byte budget; overflow demotes the oldest frames to a disk tier
  (when configured) of one payload file per prefix, written
  tmp-then-rename so a SIGKILL mid-write can never leave a half
  frame under a live name. Disk entries are LRU by last hit under
  their own byte budget. On restart the store re-scans its directory:
  `.tmp` leftovers and unparseable frames are GC'd, intact frames are
  re-adopted (a warm tier survives replica death).

* **Prefetch is the import scatter.** A trie hit against a spilled
  prefix re-materializes its pages H2D through the engine's existing
  fixed-width `_write_imported_pages` scatter — ONE compiled
  executable, no per-length recompiles (the probe contract) — and
  re-inserts the node, so the next hit is an ordinary HBM hit.

Keys are the trie's own identity: the np.int32 byte fingerprint of the
full token prefix a node covers — the same fingerprint the router's
affinity map uses, so all three layers (router, trie, tier) agree on
what "the same prefix" means. An entry's payload carries the FULL
prefix tokens with `n_prefilled = len(tokens)` but only the LAST
block's pages (the parent blocks are separate entries): tier frames
are a superset key for the trie, not an importable request payload —
they re-enter the pool through the prefetch scatter, never through
`import_kv_pages`.

Telemetry (docs/OBSERVABILITY.md): the `pt_kv_tier_*{tier}` family
(the `hbm` rows are published by the engine that owns the pool),
`pt_kv_migrations_total` (router page pulls — router.py), and the
`pt_session_*` pair (persistent chat sessions — llm_engine.py).
"""
import collections
import hashlib
import json
import os
import queue
import struct
import threading

import numpy as np

from ...distributed import chaos, resilience
from ...observability import metrics as _obs
from .kv_transfer import _HDR, _MAGIC, _VERSION, KVPagePayload, \
    pack_kv_payload

__all__ = ["KVTierStore", "prefix_key"]

_TIER_BYTES = _obs.gauge(
    "pt_kv_tier_bytes",
    "resident bytes per KV memory tier (hbm = live pool pages, "
    "published by the engine; ram/disk = packed payload frames)",
    labelnames=("tier",))
_TIER_PAGES = _obs.gauge(
    "pt_kv_tier_pages",
    "resident KV pages per memory tier (hbm = live pool pages)",
    labelnames=("tier",))
_TIER_HITS = _obs.counter(
    "pt_kv_tier_hits",
    "prefix lookups served per tier (hbm = trie hits at admission; "
    "ram/disk = spilled frames prefetched back into the pool)",
    labelnames=("tier",))
_TIER_EVICTIONS = _obs.counter(
    "pt_kv_tier_evictions",
    "pages leaving a tier downward (hbm -> spill queue, ram -> disk "
    "or dropped, disk -> dropped), by the tier they left",
    labelnames=("tier",))
_MIGRATIONS = _obs.counter(
    "pt_kv_migrations_total",
    "hot-prefix page migrations pulled to a second replica over the "
    "byte-exact KV wire instead of routing around the miss")
_SESSION_ACTIVE = _obs.gauge(
    "pt_session_active",
    "chat sessions currently tracked (pinned-then-tiered trie "
    "frontiers awaiting their next turn)")
_SESSION_RESUMED = _obs.counter(
    "pt_session_resumed",
    "session turns that resumed from a cached frontier instead of "
    "re-prefilling the conversation history")

_SUFFIX = ".ptkv"


def prefix_key(tokens):
    """Byte fingerprint of a token prefix — content AND position, the
    shared identity of router affinity keys, trie node paths, and tier
    entries."""
    return np.asarray(tokens, np.int32).tobytes()


def _mmap_array(f, path):
    """One npy array as a read-only `np.memmap` over the OS page
    cache: parse the npy header off the stream, map the data span in
    place, and advance `f` past it exactly like `np.load` would — but
    with ZERO eager host copy. The bytes materialize lazily as the
    prefetch scatter touches them; a frame evicted while a mapping is
    live stays readable (POSIX unlink keeps the open mapping valid)."""
    version = np.lib.format.read_magic(f)
    if version == (1, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_1_0(f)
    elif version == (2, 0):
        shape, fortran, dtype = np.lib.format.read_array_header_2_0(f)
    else:
        raise ValueError(f"unsupported npy version {version}: {path}")
    if dtype.hasobject:
        raise ValueError(f"object array in KV frame: {path}")
    offset = f.tell()
    count = int(np.prod(shape, dtype=np.int64))
    arr = np.memmap(path, dtype=dtype, mode="r", offset=offset,
                    shape=shape, order="F" if fortran else "C")
    f.seek(offset + count * dtype.itemsize)
    return arr


def _read_frame(path, use_mmap=False):
    """Parse one on-disk PTKV frame STREAMING from the file handle
    (np.load per array straight off the OS page cache — no whole-frame
    host copy on the read path). With ``use_mmap`` the array payloads
    are `np.memmap` views instead of copies — byte-identical (pinned
    by tests/test_kv_tier.py), just lazier. Raises on any
    truncation/corruption; callers GC the file."""
    with open(path, "rb") as f:
        hdr = f.read(_HDR.size)
        if len(hdr) != _HDR.size:
            raise ValueError(f"truncated frame header: {path}")
        magic, ver, meta_len = _HDR.unpack(hdr)
        if magic != _MAGIC:
            raise ValueError(f"not a KV frame (magic {magic!r}): {path}")
        if ver != _VERSION:
            raise ValueError(f"KV frame version {ver} != {_VERSION}")
        meta = json.loads(f.read(meta_len).decode("utf-8"))
        if use_mmap:
            def rd():
                return _mmap_array(f, path)
        else:
            def rd():
                return np.load(f, allow_pickle=False)
        tokens = rd()
        kv = [rd() for _ in range(meta["n_kv"])]
        scales = [rd() for _ in range(meta["n_scales"])]
    from .kv_transfer import _np_dtype

    kv = [a if a.dtype == _np_dtype(n) else a.view(_np_dtype(n))
          for a, n in zip(kv, meta["pool_dtypes"])]
    return KVPagePayload(tokens, meta["n_prefilled"], meta["page_size"],
                         meta["kv_dtype"], kv, scales,
                         trace=meta.get("trace"))


class KVTierStore:  # ptlint: thread-shared (commit thread + engine serve loop + scrape thread share the index)
    """Host-RAM + disk spill tiers for evicted prefix-cache pages
    (module docstring). One store per engine; `put` is called on the
    engine thread at trie eviction with an already-snapshotted host
    payload, `get` on the engine thread at admission, the commit work
    on this store's own background thread.

    ram_bytes    RAM-tier byte budget for packed frames
    disk_dir     directory for the cold tier (None: RAM only —
                 RAM overflow is simply dropped)
    disk_bytes   disk-tier byte budget (LRU by last hit)
    max_pending  spill-queue bound: a saturated commit thread REJECTS
                 new spills (counted, journal-free) instead of ever
                 blocking the engine thread
    mmap         disk-tier read path: True maps frames with np.memmap
                 (lazy, zero eager copy — default), False streams with
                 np.load. None reads env PT_KV_TIER_MMAP ("0" opts
                 out). Byte-identity either way.
    """

    def __init__(self, ram_bytes=256 << 20, disk_dir=None,
                 disk_bytes=1 << 30, max_pending=64, mmap=None):
        self.ram_bytes = int(ram_bytes)
        self.disk_dir = disk_dir
        self.disk_bytes = int(disk_bytes) if disk_dir else 0
        if mmap is None:
            mmap = os.environ.get(
                "PT_KV_TIER_MMAP", "1").lower() not in ("0", "false")
        self.use_mmap = bool(mmap)
        self._lock = threading.Lock()
        self._ram = collections.OrderedDict()   # key -> (frame, pages)
        self._ram_used = 0
        self._disk = collections.OrderedDict()  # key -> (path, nbytes,
        self._disk_used = 0                     #         pages)
        # delta-published gauges (several engines' stores SUM into the
        # process-global cells instead of last-writer-wins)
        self._published = {("bytes", "ram"): 0, ("bytes", "disk"): 0,
                           ("pages", "ram"): 0, ("pages", "disk"): 0}
        self.stats = {"spills": 0, "spill_pages": 0, "spill_failed": 0,
                      "spill_rejected": 0, "ram_hits": 0,
                      "disk_hits": 0, "misses": 0, "demotions": 0,
                      "ram_dropped": 0, "disk_dropped": 0,
                      "gc_files": 0, "adopted": 0, "mmap_reads": 0}
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
            self._restart_scan()
        self._jobs = queue.Queue(maxsize=int(max_pending))
        self._running = True
        self._thread = threading.Thread(
            target=self._commit_loop, name="kv-tier-commit", daemon=True)
        self._thread.start()

    # ---- restart hygiene (chaos: SIGKILL with a warm tier) ----

    def _restart_scan(self):
        """Adopt intact frames left by a previous process; GC `.tmp`
        leftovers (a rename that never happened) and frames that fail
        to parse (a torn write can only exist as a .tmp, but a corrupt
        disk is cheap to defend against while we're here)."""
        for name in sorted(os.listdir(self.disk_dir)):
            path = os.path.join(self.disk_dir, name)
            if name.endswith(".tmp"):
                self._gc_file(path)
                continue
            if not name.endswith(_SUFFIX):
                continue
            try:
                payload = _read_frame(path)
                key = prefix_key(payload.tokens)
            except Exception as e:
                self._gc_file(path, error=repr(e))
                continue
            with self._lock:
                self._disk[key] = (path, os.path.getsize(path),
                                   payload.num_pages)
                self._disk_used += os.path.getsize(path)
                self.stats["adopted"] += 1
        with self._lock:
            self._publish_locked()

    def _gc_file(self, path, error=None):
        # never called with the lock held (init scan / post-lock drop)
        try:
            os.remove(path)
            with self._lock:
                self.stats["gc_files"] += 1
            resilience.record("kv_tier_gc", path=os.path.basename(path),
                              error=error)
        except OSError:
            pass

    # ---- spill (engine thread enqueues; commit thread owns the work) ----

    def put(self, key, payload):
        """Queue one evicted prefix for tiering. `payload` must already
        be host-resident owned arrays (the engine's synchronous D2H
        snapshot); this call is O(1) and NEVER blocks — a full queue
        rejects the spill (the entry is simply lost, like any other
        eviction) rather than stall the serve loop. Returns True when
        queued."""
        if not self._running:
            return False
        try:
            self._jobs.put_nowait((key, payload))
        except queue.Full:
            with self._lock:
                self.stats["spill_rejected"] += 1
            return False
        return True

    def _commit_loop(self):
        while True:
            job = self._jobs.get()
            try:
                if job is None:
                    return
                key, payload = job
                try:
                    chaos.fire("kv_tier.spill")
                    frame = pack_kv_payload(payload)
                    self._insert_ram(key, frame, payload.num_pages)
                    with self._lock:
                        self.stats["spills"] += 1
                        self.stats["spill_pages"] += payload.num_pages
                except Exception as e:
                    # journal + drop: a failed commit costs exactly the
                    # re-prefill the eviction already cost — serving
                    # correctness never depends on the tier
                    with self._lock:
                        self.stats["spill_failed"] += 1
                    try:
                        resilience.record("kv_tier_spill_failed",
                                          error=repr(e),
                                          pages=payload.num_pages)
                    except Exception:  # ptlint: disable=PTL804 (the guard wraps the journal call itself)
                        pass
            finally:
                self._jobs.task_done()

    def _insert_ram(self, key, frame, pages):
        """RAM-tier insert + LRU demotion cascade. Victim frames are
        collected under the lock but written to disk OUTSIDE it, so a
        concurrent `get` on the engine thread never waits on disk I/O
        (worst case it misses a frame mid-demotion and re-prefills)."""
        demote = []
        with self._lock:
            if key in self._ram:
                self._ram.move_to_end(key)
                return
            self._ram[key] = (frame, pages)
            self._ram_used += len(frame)
            while self._ram_used > self.ram_bytes and self._ram:
                vk, (vframe, vpages) = self._ram.popitem(last=False)
                self._ram_used -= len(vframe)
                demote.append((vk, vframe, vpages))
            self._publish_locked()
        for vk, vframe, vpages in demote:
            _TIER_EVICTIONS.labels(tier="ram").inc(vpages)
            if self.disk_dir:
                self._demote_disk(vk, vframe, vpages)
            else:
                with self._lock:
                    self.stats["ram_dropped"] += 1

    def _demote_disk(self, key, frame, pages):
        """One frame RAM -> disk: tmp-write + rename (the PR-14
        visibility rule — a reader, or a restart scan, only ever sees
        whole frames), then LRU-trim the disk tier to budget."""
        path = os.path.join(
            self.disk_dir, hashlib.sha1(key).hexdigest() + _SUFFIX)
        try:
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(frame)
            os.replace(tmp, path)
        except OSError as e:
            try:
                resilience.record("kv_tier_disk_failed", error=repr(e))
            except Exception:  # ptlint: disable=PTL804 (the guard wraps the journal call itself)
                pass
            return
        drop = []
        with self._lock:
            old = self._disk.pop(key, None)
            if old is not None:
                self._disk_used -= old[1]
            self._disk[key] = (path, len(frame), pages)
            self._disk_used += len(frame)
            self.stats["demotions"] += 1
            while self._disk_used > self.disk_bytes and self._disk:
                _, (vpath, vbytes, vpages) = self._disk.popitem(
                    last=False)
                self._disk_used -= vbytes
                self.stats["disk_dropped"] += 1
                drop.append((vpath, vpages))
            self._publish_locked()
        for vpath, vpages in drop:
            _TIER_EVICTIONS.labels(tier="disk").inc(vpages)
            try:
                os.remove(vpath)
            except OSError:
                pass

    # ---- prefetch lookups (engine thread) ----

    def get(self, key):
        """The tier lookup behind a trie miss: RAM frame, else disk
        frame (LRU-touched), else None. Returns the unpacked
        `KVPagePayload` — byte-identical arrays to what was spilled."""
        from .kv_transfer import unpack_kv_payload

        with self._lock:
            ent = self._ram.get(key)
            if ent is not None:
                self._ram.move_to_end(key)
                self.stats["ram_hits"] += 1
            else:
                dent = self._disk.get(key)
                if dent is not None:
                    self._disk.move_to_end(key)   # LRU by last HIT
                    self.stats["disk_hits"] += 1
                else:
                    self.stats["misses"] += 1
                    return None
        if ent is not None:
            _TIER_HITS.labels(tier="ram").inc()
            return unpack_kv_payload(ent[0])
        _TIER_HITS.labels(tier="disk").inc()
        try:
            payload = _read_frame(dent[0], use_mmap=self.use_mmap)
            if self.use_mmap:
                with self._lock:
                    self.stats["mmap_reads"] += 1
            return payload
        except Exception as e:
            # a frame that rots on disk is dropped like a failed spill
            with self._lock:
                old = self._disk.pop(key, None)
                if old is not None:
                    self._disk_used -= old[1]
                self._publish_locked()
            self._gc_file(dent[0], error=repr(e))
            return None

    def __contains__(self, key):
        with self._lock:
            return key in self._ram or key in self._disk

    # ---- lifecycle / introspection ----

    def flush(self):
        """Drain the spill queue (tests and the bench's deterministic
        A/B phases — production never waits on the tier)."""
        self._jobs.join()

    def close(self):
        if not self._running:
            return
        self._running = False
        self._jobs.put(None)
        self._thread.join(timeout=10)

    def _publish_locked(self):
        ram_pages = sum(p for _, p in list(self._ram.values()))
        disk_pages = sum(p for _, _, p in list(self._disk.values()))
        cur = {("bytes", "ram"): self._ram_used,
               ("bytes", "disk"): self._disk_used,
               ("pages", "ram"): ram_pages,
               ("pages", "disk"): disk_pages}
        for (what, tier), val in cur.items():
            gauge = _TIER_BYTES if what == "bytes" else _TIER_PAGES
            gauge.labels(tier=tier).inc(val - self._published[
                (what, tier)])
            self._published[(what, tier)] = val

    def snapshot(self):
        with self._lock:
            out = dict(self.stats)
            out.update({
                "ram_bytes": self._ram_used,
                "ram_entries": len(self._ram),
                "disk_bytes": self._disk_used,
                "disk_entries": len(self._disk),
                "pending": self._jobs.qsize(),
            })
        return out
