"""Overload control plane — shedding, cancellation, brownout, breaker.

PRs 13–15 made the fleet survive replica death and made every request
traceable; this module makes it survive its own CLIENTS. Without it a
saturated fleet has exactly one behaviour: every request's TTFT slides
together until nothing meets any deadline — the classic congestion-
collapse shape. The control plane here turns overload into a first-
class, *typed* outcome instead:

* **Typed rejections.** :class:`RequestShed` (admission refused — the
  request never consumed fleet work; carries a ``retry_after_s`` hint)
  and :class:`RequestCancelled` (the request was admitted and then
  aborted — client ``cancel()`` or deadline expiry). A client future
  ALWAYS resolves with a result or one of these; never a hang.

* **Deadline admission** (:class:`TTFTEstimator`). Requests may carry
  a hard ``deadline_s``. The estimator tracks the FASTEST fleet token
  rate ever observed (peak of per-monitor-tick deltas of the engines'
  ``tokens_in`` counters) plus an EMA of prompt length, giving an
  optimistic lower bound on TTFT behind the current queue. A deadline
  below that bound is *provably* unmeetable — even a fleet running at
  its best-ever rate could not serve it in time — so the router sheds
  at ingress, the cheapest byte never moved. No observed rate → no
  proof → admit (the estimator never guesses against the client).

* **Brownout ladder** (:class:`BrownoutController`). Under sustained
  pressure (queue depth per alive replica ≥ ``brownout_high`` for
  ``brownout_step_ticks`` monitor ticks) the fleet steps DOWN through
  journaled levels — shrink spec_k → disable speculation and release
  the draft pool → shrink the fused decode window and cap
  max_new_tokens → shed the best-effort class at ingress — and steps
  back UP with hysteresis (pressure ≤ ``brownout_low`` for
  ``brownout_recover_ticks`` ticks). Every cap is a host-side clamp on
  a RUNTIME argument of the compiled step (widths/remainders ride as
  arguments; scan lengths stay baked), so the ladder never triggers a
  recompile. Each transition journals, stamps a flight-recorder
  ``brownout_transition`` event, and moves ``pt_fleet_brownout_level``.

* **Circuit breaker** (:class:`CircuitBreaker`). Failover (PR 13) only
  reacts to a *dead* prefill tier; the breaker reacts to a *sick* one.
  A windowed failure(/latency) rate at/above ``breaker_failure_rate``
  opens the breaker and the router falls back to whole-request serving
  on the decode tier; after ``breaker_reset_s`` one half-open probe
  decides re-close vs re-open. States surface as
  ``pt_prefill_breaker_state`` (0 closed · 0.5 half-open · 1 open).

Defaults are deliberately inert where behaviour would change: brownout
and hedging are opt-in (``brownout_high=None`` / ``hedge_after_s=
None``), the parking bound is generous, and the breaker counts
failures only (``breaker_latency_s=None``) so a slow CI host never
flips it. docs/SERVING.md "Overload and degradation" is the contract.
"""
import collections
import threading
import time

from ...observability import flight_recorder as _flight
from ...observability import metrics as _obs

__all__ = ["RequestShed", "RequestCancelled", "OverloadPolicy",
           "TTFTEstimator", "CircuitBreaker", "BrownoutController",
           "DEFAULT_BROWNOUT_LEVELS", "note_shed", "note_cancelled",
           "note_hedge"]

_SHED_TOTAL = _obs.counter(
    "pt_requests_shed_total",
    "requests refused at admission with a typed RequestShed, by reason "
    "(deadline | deadline_unmeetable | brownout | capacity | "
    "no_capacity) — a shed request consumed no fleet work",
    labelnames=("reason",))
_CANCELLED_TOTAL = _obs.counter(
    "pt_requests_cancelled_total",
    "admitted requests aborted mid-flight, by reason (client | "
    "deadline) — slots, pool pages and trie pins are freed and the "
    "client future resolves with RequestCancelled",
    labelnames=("reason",))
_BROWNOUT_LEVEL = _obs.gauge(
    "pt_fleet_brownout_level",
    "current brownout degradation level (0 = full service; each step "
    "applies the cumulative caps of docs/SERVING.md's ladder)")
_BREAKER_STATE = _obs.gauge(
    "pt_prefill_breaker_state",
    "prefill hand-off circuit breaker state: 0 closed, 0.5 half-open "
    "(single probe outstanding), 1 open (whole-request fallback)")
_BREAKER_OPENS = _obs.counter(
    "pt_prefill_breaker_opens_total",
    "times the prefill circuit breaker opened (windowed failure/"
    "latency rate crossed the threshold, or a half-open probe failed)")
_HEDGES = _obs.counter(
    "pt_router_hedges_total",
    "hedged re-dispatches: an in-flight request re-sent to a healthy "
    "replica because its current one stopped ticking (first completion "
    "wins; the duplicate attempt's outcome is suppressed)")


def note_shed(reason):
    """Count one shed (reason labels: module docstring)."""
    _SHED_TOTAL.labels(reason=reason).inc()


def note_cancelled(reason):
    """Count one mid-flight cancellation."""
    _CANCELLED_TOTAL.labels(reason=reason).inc()


def note_hedge():
    """Count one hedged re-dispatch."""
    _HEDGES.inc()


class RequestShed(RuntimeError):
    """Admission refused — the request consumed no fleet work.

    ``reason``        why (docs/SERVING.md table)
    ``retry_after_s`` optimistic seconds until a retry could be
                      admitted (None: retry timing is not the issue,
                      e.g. the deadline already expired at submit)
    ``trace_id``      the request's trace identity, when it got far
                      enough to have one
    """

    def __init__(self, reason, retry_after_s=None, trace_id=None):
        self.reason = reason
        self.retry_after_s = retry_after_s
        self.trace_id = trace_id
        hint = ("" if retry_after_s is None
                else f" (retry after ~{retry_after_s:.3f}s)")
        super().__init__(f"request shed: {reason}{hint}")


class RequestCancelled(RuntimeError):
    """An ADMITTED request was aborted mid-flight (client cancel or
    deadline expiry); its slots/pages/pins were freed."""

    def __init__(self, reason="client", trace_id=None):
        self.reason = reason
        self.trace_id = trace_id
        super().__init__(f"request cancelled: {reason}")


class OverloadPolicy:
    """Control-plane knobs (docs/SERVING.md has the tuning table).

    max_parked          bound on the all-replicas-dead parking queue;
                        beyond it the worst parked request sheds
    max_inflight        bound on total router-tracked requests (None:
                        unbounded); beyond it the worst parked request
                        — or the newcomer — sheds
    brownout_high       queue-depth-per-alive-replica at/above which a
                        monitor tick counts HOT (None: brownout off)
    brownout_low        pressure at/below which a tick counts COOL
                        (None: half of brownout_high)
    brownout_step_ticks     consecutive hot ticks per step DOWN
    brownout_recover_ticks  consecutive cool ticks per step UP
    brownout_levels     override ladder (tuple of caps dicts; None:
                        DEFAULT_BROWNOUT_LEVELS)
    breaker_window      sliding event window for the prefill breaker
    breaker_failure_rate    bad fraction at/above which it opens
    breaker_latency_s   prefill hand-off latency counted as bad (None:
                        failures only — the CI-safe default)
    breaker_min_events  minimum window occupancy before evaluating
    breaker_reset_s     open -> half-open probe delay
    hedge_after_s       request age before it is hedge-eligible (None:
                        hedging off)
    hedge_stale_s       replica tick staleness that marks it wedged
                        (None: a quarter of the heartbeat timeout — a
                        hedge must fire BEFORE failover would)
    """

    def __init__(self, max_parked=256, max_inflight=None,
                 brownout_high=None, brownout_low=None,
                 brownout_step_ticks=3, brownout_recover_ticks=10,
                 brownout_levels=None,
                 breaker_window=16, breaker_failure_rate=0.5,
                 breaker_latency_s=None, breaker_min_events=4,
                 breaker_reset_s=2.0,
                 hedge_after_s=None, hedge_stale_s=None):
        self.max_parked = int(max_parked)
        self.max_inflight = (None if max_inflight is None
                             else int(max_inflight))
        self.brownout_high = (None if brownout_high is None
                              else float(brownout_high))
        self.brownout_low = (None if brownout_low is None
                             else float(brownout_low))
        self.brownout_step_ticks = int(brownout_step_ticks)
        self.brownout_recover_ticks = int(brownout_recover_ticks)
        self.brownout_levels = brownout_levels
        self.breaker_window = int(breaker_window)
        self.breaker_failure_rate = float(breaker_failure_rate)
        self.breaker_latency_s = (None if breaker_latency_s is None
                                  else float(breaker_latency_s))
        self.breaker_min_events = int(breaker_min_events)
        self.breaker_reset_s = float(breaker_reset_s)
        self.hedge_after_s = (None if hedge_after_s is None
                              else float(hedge_after_s))
        self.hedge_stale_s = (None if hedge_stale_s is None
                              else float(hedge_stale_s))


class TTFTEstimator:  # ptlint: thread-shared (router submit + monitor tick write; submit reads)
    """Optimistic TTFT lower bound from live fleet telemetry.

    ``note_progress`` feeds cumulative fleet ``tokens_in`` samples from
    the router monitor; the PEAK observed rate between samples is kept
    (negative deltas — a replica died or re-warmed and its counter left
    the sum — are discarded). ``note_prompt`` keeps an EMA of prompt
    length so queue depth converts to queued *tokens*. The bound
    ``lower_bound_ttft = queued_tokens / peak_rate`` is optimistic by
    construction — the real fleet never beats its best-ever rate — so
    shedding on it is provable, and NO observed rate yields bound 0.0
    (admit: the estimator never guesses against the client)."""

    def __init__(self, prompt_ema=0.2):
        self._lock = threading.Lock()
        self._alpha = float(prompt_ema)
        self._avg_prompt = 0.0
        self._peak_rate = 0.0      # tokens/s, best ever observed
        self._last = None          # (cum_tokens, t_monotonic)

    def note_prompt(self, n_tokens):
        with self._lock:
            if self._avg_prompt <= 0.0:
                self._avg_prompt = float(n_tokens)
            else:
                self._avg_prompt += self._alpha * (float(n_tokens)
                                                   - self._avg_prompt)

    def note_progress(self, cum_tokens, t=None):
        t = time.monotonic() if t is None else float(t)
        with self._lock:
            last, self._last = self._last, (float(cum_tokens), t)
            if last is None:
                return
            dtok, dt = cum_tokens - last[0], t - last[1]
            if dtok <= 0.0 or dt <= 0.0:
                return
            self._peak_rate = max(self._peak_rate, dtok / dt)

    def avg_prompt_tokens(self):
        with self._lock:
            return self._avg_prompt

    def peak_rate(self):
        with self._lock:
            return self._peak_rate

    def lower_bound_ttft(self, queued_tokens):
        """Optimistic seconds before a request behind `queued_tokens`
        of work sees its first token; 0.0 while no rate is known."""
        with self._lock:
            if self._peak_rate <= 0.0:
                return 0.0
            return float(queued_tokens) / self._peak_rate

    def snapshot(self):
        with self._lock:
            return {"peak_rate_tok_s": round(self._peak_rate, 3),
                    "avg_prompt_tokens": round(self._avg_prompt, 2)}


class CircuitBreaker:  # ptlint: thread-shared (dispatch allow() + prefill-callback records)
    """Windowed failure(/latency) breaker for the prefill hand-off.

    closed -> open when the sliding window (>= min_events deep) holds a
    bad fraction >= failure_rate; bad = a failed hand-off, or — with
    latency_s set — one slower than latency_s. open -> half_open after
    reset_s; half_open admits EXACTLY one probe: a clean success closes
    (and forgets the window), anything else re-opens and restarts the
    timer."""

    def __init__(self, window=16, failure_rate=0.5, latency_s=None,
                 min_events=4, reset_s=2.0):
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=int(window))
        self.failure_rate = float(failure_rate)
        self.latency_s = None if latency_s is None else float(latency_s)
        self.min_events = int(min_events)
        self.reset_s = float(reset_s)
        self.state = "closed"
        self.opens = 0
        self._opened_t = 0.0
        self._probe_out = False
        self._probe_t = 0.0
        _BREAKER_STATE.set(0.0)

    def allow(self, now=None):
        """May a prefill hand-off be attempted right now?"""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if now - self._opened_t < self.reset_s:
                    return False
                self.state = "half_open"
                self._probe_out = False
                _BREAKER_STATE.set(0.5)
                _flight.record_event("breaker_half_open")
            if self._probe_out:
                # an abandoned probe (its replica died; the future was
                # superseded and never reports) must not wedge the
                # breaker half-open forever — age it out
                if now - self._probe_t < max(self.reset_s, 1.0):
                    return False
            self._probe_out = True
            self._probe_t = now
            return True

    def record_success(self, latency_s=0.0, now=None):
        with self._lock:
            slow = (self.latency_s is not None
                    and float(latency_s) > self.latency_s)
            self._events.append(not slow)
            if self.state == "half_open":
                if slow:
                    self._open(now)
                    return
                self.state = "closed"
                self._events.clear()
                _BREAKER_STATE.set(0.0)
                _flight.record_event("breaker_closed")
                return
            if self.state == "closed":
                self._evaluate(now)

    def record_failure(self, now=None):
        with self._lock:
            self._events.append(False)
            if self.state == "half_open":
                self._open(now)
            elif self.state == "closed":
                self._evaluate(now)

    # both called with the lock held
    def _evaluate(self, now):
        n = len(self._events)
        if n < self.min_events:
            return
        bad = n - sum(self._events)
        if bad / n >= self.failure_rate:
            self._open(now)

    def _open(self, now):
        self.state = "open"
        self._opened_t = time.monotonic() if now is None else float(now)
        self._probe_out = False
        # _open runs with self._lock held (record_* / _evaluate)
        self.opens += 1  # ptlint: disable=PTL702
        _BREAKER_OPENS.inc()
        _BREAKER_STATE.set(1.0)
        _flight.record_event("breaker_open", opens=self.opens,
                             window=list(self._events))

    def snapshot(self):
        with self._lock:
            return {"state": self.state, "opens": self.opens,
                    "window": list(self._events)}


# cumulative caps per level; every cap is a host-side clamp on a
# RUNTIME argument (widths/remainders/targets) — never a retrace
DEFAULT_BROWNOUT_LEVELS = (
    {},                                           # L0: full service
    {"spec_k_cap": 2},                            # L1: shrink spec_k
    {"spec_enabled": False},                      # L2: spec off, draft
                                                  #     pool released
    {"spec_enabled": False, "decode_k_cap": 2,    # L3: + window/output
     "max_new_cap": 32},                          #     caps
    {"spec_enabled": False, "decode_k_cap": 2,    # L4: + stop pinning
     "max_new_cap": 32, "session_pin": False},    #     session KV: the
                                                  #     engine sheds
                                                  #     convenience
                                                  #     state (multi-
                                                  #     turn frontiers
                                                  #     re-prefill)
                                                  #     BEFORE any
                                                  #     traffic is
                                                  #     refused
    {"spec_enabled": False, "decode_k_cap": 2,    # L5: + shed the
     "max_new_cap": 32, "session_pin": False,     #     best-effort
     "shed_priority": 2},                         #     (BATCH) class
)


class BrownoutController:  # ptlint: thread-shared (monitor tick writes; submit/ingress read)
    """Journaled, hysteretic degradation ladder (module docstring).

    ``note_pressure`` is called once per router monitor tick with the
    fleet pressure (queue depth per alive replica); a step only fires
    after ``step_ticks``/``recover_ticks`` CONSECUTIVE hot/cool ticks,
    and a mid-band tick resets both streaks — the ladder cannot
    oscillate on a noisy boundary. Inert when ``brownout_high`` is
    None."""

    def __init__(self, policy, apply_fn=None):
        self.policy = policy
        self.apply_fn = apply_fn     # fn(level, caps) on transition
        self.levels = tuple(dict(lv) for lv in
                            (policy.brownout_levels
                             or DEFAULT_BROWNOUT_LEVELS))
        self._lock = threading.Lock()
        self.level = 0
        self._hot = 0
        self._cool = 0
        self._entered_t = None
        self.journal = []            # [{t, from, to, pressure}], bounded
        self.dwell_s = [0.0] * len(self.levels)

    @property
    def enabled(self):
        return self.policy.brownout_high is not None

    def shed_priority(self):
        """Priority value at/above which ingress sheds (None: no class
        is being shed at the current level)."""
        return self.levels[self.level].get("shed_priority")

    def caps(self):
        return dict(self.levels[self.level])

    def note_pressure(self, pressure, now=None):
        pol = self.policy
        if pol.brownout_high is None:
            return self.level
        now = time.monotonic() if now is None else float(now)
        low = (pol.brownout_low if pol.brownout_low is not None
               else 0.5 * pol.brownout_high)
        with self._lock:
            if self._entered_t is None:
                self._entered_t = now
            target = None
            if pressure >= pol.brownout_high:
                self._hot, self._cool = self._hot + 1, 0
                if (self._hot >= pol.brownout_step_ticks
                        and self.level < len(self.levels) - 1):
                    target, self._hot = self.level + 1, 0
            elif pressure <= low:
                self._cool, self._hot = self._cool + 1, 0
                if (self._cool >= pol.brownout_recover_ticks
                        and self.level > 0):
                    target, self._cool = self.level - 1, 0
            else:
                self._hot = self._cool = 0
            if target is None:
                return self.level
            prev, self.level = self.level, target
            self.dwell_s[prev] += now - self._entered_t
            self._entered_t = now
            self.journal.append({"t": now, "from": prev, "to": target,
                                 "pressure": round(float(pressure), 3)})
            del self.journal[:-256]
            caps = dict(self.levels[target])
            fn = self.apply_fn
        _BROWNOUT_LEVEL.set(float(target))
        _flight.record_event("brownout_transition", level_from=prev,
                             level_to=target,
                             pressure=round(float(pressure), 3),
                             caps=caps)
        if fn is not None:
            try:
                fn(target, caps)
            except Exception as e:
                # the ladder still advanced — but a failing apply hook
                # means the fleet did NOT degrade; leave a trace
                _flight.record_event("brownout_apply_failed",
                                     level=target, error=repr(e))
        return target

    def dwell(self, now=None):
        """Seconds spent at each level so far (current level's open
        interval included) — the bench's brownout-dwell stamp."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            out = list(self.dwell_s)
            if self._entered_t is not None:
                out[self.level] += now - self._entered_t
            return out

    def snapshot(self):
        with self._lock:
            return {"level": self.level,
                    "enabled": self.enabled,
                    "caps": dict(self.levels[self.level]),
                    "transitions": len(self.journal),
                    "journal_tail": self.journal[-8:]}
