"""Radix prefix cache — content-addressed COW sharing of KV pages.

A fleet of requests carrying the same system prompt re-prefills and
re-stores identical KV over and over: with a 128-token system prompt
and 32-token user suffixes, ~80% of every prefill is redundant compute
AND redundant HBM. This cache de-duplicates both.

Design (the vLLM/SGLang radix-cache shape, page-pool native):

* **Token trie at block granularity.** Prompts are split into blocks of
  `block_tokens` (a multiple of the pool's `page_size`; default equal).
  Each trie node is keyed by its block's exact token tuple — python's
  hash gives the content addressing, tuple equality makes collisions
  impossible — and owns the physical page ids whose KV holds exactly
  those tokens at those positions. A node's identity is its PATH from
  the root, so equal blocks under different prefixes are distinct
  (positions differ, so their KV differs — RoPE).

* **Sharing is page-table aliasing + refcounts.** `match()` walks the
  trie and maps each hit page into the caller's page table after
  `PagePool.share()` (refcount + 1). The engine then starts the
  request's prefill AFTER the cached tokens: shared pages are read by
  paged attention but never written. KV rows depend only on (token,
  position) prefix — identical prefix, identical rows — so greedy
  outputs are token-identical to the uncached path (pinned by
  tests/test_fleet_serving.py).

* **Copy-on-write split.** A request may only write pages it owns
  exclusively. When its first divergent write would land INSIDE a
  shared page (e.g. the prompt is an exact block multiple and fully
  cached, so the frontier token's KV row lands in the last shared
  page), the engine splits: the shared mapping is dropped
  (`release()`, refcount − 1) and the block's rows are recomputed into
  a freshly-allocated private page. The "copy" is a replayed prefill of
  ≤ block_tokens tokens through the SAME decode executable — no page-
  copy kernel, no second executable, and bit-identical page contents.

* **LRU eviction under pool pressure.** Trie nodes whose pages nobody
  maps (pool refcount 1 — the trie's own reference) are evictable; the
  engine calls `evict()` before preempting a running sequence when the
  pool runs dry. Leaves evict first (a node's children extend its
  prefix, so parents are only reclaimable once their subtree is gone),
  least-recently-matched first.

Telemetry (docs/OBSERVABILITY.md): pt_prefix_cache_hits,
pt_prefix_cache_pages_shared, pt_prefix_cache_prefill_tokens_saved,
pt_prefix_cache_cow_splits, pt_prefix_cache_evicted_pages, and the
pt_prefix_cache_resident_pages gauge.
"""
import heapq
import itertools

from ...observability import metrics as _obs

__all__ = ["RadixPrefixCache"]

_HITS = _obs.counter(
    "pt_prefix_cache_hits",
    "requests admitted with a non-empty shared-prefix mapping")
_PAGES_SHARED = _obs.counter(
    "pt_prefix_cache_pages_shared",
    "KV pages mapped read-only into an admitted request's page table")
_TOKENS_SAVED = _obs.counter(
    "pt_prefix_cache_prefill_tokens_saved",
    "prompt tokens whose prefill was skipped via a cache hit")
_COW_SPLITS = _obs.counter(
    "pt_prefix_cache_cow_splits",
    "shared-page mappings split copy-on-write (divergent write)")
_EVICTED = _obs.counter(
    "pt_prefix_cache_evicted_pages",
    "trie-held pages reclaimed by LRU eviction under pool pressure")
_RESIDENT = _obs.gauge(
    "pt_prefix_cache_resident_pages",
    "pages currently pinned by the prefix trie (refcount holders)")


class _TrieNode:
    __slots__ = ("block", "pages", "parent", "children", "last_used")

    def __init__(self, block, pages, parent):
        self.block = block      # tuple of block_tokens token ids
        self.pages = pages      # tuple of physical page ids (aligned)
        self.parent = parent
        self.children = {}      # block tuple -> _TrieNode
        self.last_used = 0


class RadixPrefixCache:  # ptlint: thread-shared (scraped by /metrics)
    """Token-trie index over a `PagePool`'s resident KV pages (module
    docstring has the design). The cache owns one pool reference per
    indexed page; `match()` hands the caller one more per mapped page
    (released through the ordinary `pool.free` path when the request's
    pages are released)."""

    def __init__(self, pool, page_size, block_tokens=None):
        self.pool = pool
        self.page_size = int(page_size)
        self.block_tokens = int(block_tokens or page_size)
        if (self.block_tokens < self.page_size
                or self.block_tokens % self.page_size):
            raise ValueError(
                f"block_tokens {self.block_tokens} must be a positive "
                f"multiple of page_size {self.page_size}: the trie maps "
                "whole pages, so a hash block must cover an exact page "
                "count")
        self.pages_per_block = self.block_tokens // self.page_size
        self._root = _TrieNode(None, (), None)
        self._clock = itertools.count(1)
        self._nodes = 0
        self._resident_published = 0
        # KV tier hook (fleet_serving.kv_tier, docs/SERVING.md "KV
        # memory hierarchy"): called with the dying node BEFORE its
        # pages are freed, so the engine can snapshot them D2H and
        # spill to the host-RAM tier. None = eviction simply drops.
        # clear() does NOT spill — a cleared trie means the pool's
        # bytes are invalid (abort path) or the engine is retiring.
        self.spill_fn = None
        # local mirror of the registry counters (per-cache attribution:
        # the registry is process-global across engines)
        self.stats = {"hits": 0, "misses": 0, "pages_shared": 0,
                      "tokens_saved": 0, "cow_splits": 0,
                      "evicted_pages": 0, "inserted_blocks": 0}

    # ---- introspection ----

    @property
    def num_nodes(self):
        return self._nodes

    @property
    def resident_pages(self):
        return self._nodes * self.pages_per_block

    def _touch(self, node):
        node.last_used = next(self._clock)

    def _publish_resident(self):
        # the gauge is process-global: publish the DELTA so several
        # engines' caches SUM into it instead of last-writer-wins
        cur = self.resident_pages
        _RESIDENT.inc(cur - self._resident_published)
        self._resident_published = cur

    # ---- lookup ----

    def match(self, tokens):
        """Longest cached prefix of `tokens` at block granularity.

        Returns (cached_tokens, page_ids): the caller now HOLDS one
        pool reference per returned page (``pool.share`` applied) and
        must release them through ``pool.free`` — either when the
        request's pages are released or immediately on an abandoned
        admission attempt."""
        bt = self.block_tokens
        node = self._root
        pages = []
        cached = 0
        while cached + bt <= len(tokens):
            blk = tuple(int(t) for t in tokens[cached:cached + bt])
            child = node.children.get(blk)
            if child is None:
                break
            node = child
            self._touch(node)
            for p in node.pages:
                self.pool.share(p)
            pages.extend(node.pages)
            cached += bt
        return cached, pages

    def note_mapped(self, cached_tokens, pages, cow_splits=0):
        """Telemetry for a mapping that actually ADMITTED (called by
        the engine once per successful admission — match() and
        cow_split() run on every admission ATTEMPT, including ones
        pushed back for a slot, and must not inflate hit/split rates):
        prefill tokens skipped + pages aliased + COW splits taken."""
        if cow_splits:
            self.stats["cow_splits"] += cow_splits
            _COW_SPLITS.inc(cow_splits)
        if cached_tokens:
            self.stats["hits"] += 1
            self.stats["tokens_saved"] += int(cached_tokens)
            self.stats["pages_shared"] += len(pages)
            _HITS.inc()
            _TOKENS_SAVED.inc(int(cached_tokens))
            _PAGES_SHARED.inc(len(pages))
        else:
            self.stats["misses"] += 1

    def cow_split(self, pages):
        """Drop the tail block's shared mapping so its rows can be
        recomputed into private pages (module docstring: COW-by-
        recompute). `pages` is the FULL mapped list; the last block's
        pages are released in place. Returns the tokens un-cached.
        NOT counted here — the engine reports splits through
        `note_mapped` on successful admission only, so a request
        re-splitting across pushed-back admission attempts counts
        once."""
        tail = pages[-self.pages_per_block:]
        del pages[-self.pages_per_block:]
        self.pool.free(tail)
        return self.block_tokens

    # ---- registration ----

    def insert(self, tokens, pages):
        """Index fully-written pages under their token blocks. `tokens`
        and `pages` must be block-aligned views of one request's
        prefilled prompt (positions 0..len(tokens)); only full blocks
        register. Idempotent: blocks already present (including ones
        this request itself mapped from the trie) are left untouched —
        no re-share, no replacement, so two requests racing the same
        new prefix keep the first registration and the loser simply
        stays private. Returns the number of NEW nodes."""
        bt, ppb = self.block_tokens, self.pages_per_block
        node = self._root
        new = 0
        for b in range(len(tokens) // bt):
            blk = tuple(int(t) for t in tokens[b * bt:(b + 1) * bt])
            child = node.children.get(blk)
            if child is None:
                pg = tuple(int(p) for p in pages[b * ppb:(b + 1) * ppb])
                for p in pg:
                    self.pool.share(p)
                child = _TrieNode(blk, pg, node)
                node.children[blk] = child
                self._nodes += 1
                new += 1
            self._touch(child)
            node = child
        if new:
            self.stats["inserted_blocks"] += new
            self._publish_resident()
        return new

    # ---- reclamation ----

    def _evictable_leaves(self):
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif all(self.pool.refcount(p) == 1 for p in n.pages):
                out.append(n)
        return out

    def _drop(self, node):
        del node.parent.children[node.block]
        if self.spill_fn is not None:
            # snapshot-before-free: after pool.free these page ids are
            # reusable and the bytes can be overwritten any tick. The
            # hook owns its own error handling (a failed spill must
            # never block the eviction that is relieving pool pressure).
            self.spill_fn(node)
        self.pool.free(node.pages)
        self._nodes -= 1
        return len(node.pages)

    def reclaimable_pages(self):
        """Pages a full eviction cascade could free: every node whose
        subtree pins NO live-mapped (refcount > 1) page is ultimately
        evictable (leaves first, then their newly-leaf parents). The
        engine's admission feasibility check reads this BEFORE
        preempting runners, so running sequences never lose their KV
        for an admission that cannot succeed anyway. Iterative like
        every other trie traversal here: a long-context prompt chains
        one node per block, deeper than python's recursion limit."""
        order = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        free = 0
        pinned = {}   # id(node) -> subtree pins a live-mapped page
        for n in reversed(order):   # preorder reversed: children first
            pin = (any(self.pool.refcount(p) > 1 for p in n.pages)
                   or any(pinned[id(c)] for c in n.children.values()))
            pinned[id(n)] = pin
            if not pin:
                free += len(n.pages)
        return free

    def evict(self, num_pages):
        """Reclaim >= `num_pages` pages from trie-only nodes (pool
        refcount 1), least-recently-used leaves first. Returns pages
        actually freed (0 when every resident page is still mapped by a
        live request). ONE tree scan seeds an LRU heap and a dropped
        victim's parent enters it as its subtree drains — an eviction
        cascade is O(nodes log nodes), not O(nodes²) of rescans on the
        admission path."""
        freed = 0
        heap = [(n.last_used, id(n), n)
                for n in self._evictable_leaves()]
        heapq.heapify(heap)
        while freed < num_pages and heap:
            _, _, victim = heapq.heappop(heap)
            freed += self._drop(victim)
            parent = victim.parent
            if (parent is not self._root and not parent.children
                    and all(self.pool.refcount(p) == 1
                            for p in parent.pages)):
                heapq.heappush(heap,
                               (parent.last_used, id(parent), parent))
        if freed:
            self.stats["evicted_pages"] += freed
            _EVICTED.inc(freed)
            self._publish_resident()
        return freed

    def clear(self):
        """Drop every node and release the trie's pool references —
        the engine's abort path (re-zeroed pools invalidate all cached
        KV) and teardown."""
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            stack.extend(n.children.values())
            self.pool.free(n.pages)
        self._root = _TrieNode(None, (), None)
        self._nodes = 0
        self._publish_resident()

    def snapshot(self):
        """Metrics view for `LLMEngine.metrics()` (per-cache counters,
        unlike the process-global registry)."""
        out = dict(self.stats)
        out["nodes"] = self._nodes
        out["resident_pages"] = self.resident_pages
        out["block_tokens"] = self.block_tokens
        return out
