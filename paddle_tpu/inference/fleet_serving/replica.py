"""Replica runtime — one serving engine as a fleet member.

One `LLMServer` owns one engine; the millions-of-users tier runs N of
them behind a router (docs/SERVING.md "Disaggregated fleet"). This
module is the MEMBER side of that tier:

* **LocalReplica** wraps model + `LLMServer` (its own engine thread)
  and registers into a `ReplicaRegistry` with per-tick heartbeats —
  the `fleet/elastic` membership shape: the registry mirrors beats
  into the launcher's `hb_<rank>` file protocol when given a
  directory (`distributed.fleet.elastic.touch_heartbeat`), so an
  `ElasticManager` pointed at the same dir observes the serving fleet
  exactly as it observes a training pod.

* **Roles.** A `role="prefill"` replica only ever runs prefill-only
  requests (`submit_prefill` → `KVPagePayload`); a `role="serve"`
  replica decodes — from scratch or from an imported payload
  (`submit_imported`). The split is policy, not mechanism: every
  replica's engine can do both, the router just routes by role.

* **Chaos kill.** Each serve-loop tick fires the `replica.kill` and
  `replica.kill.<name>` chaos scopes (distributed/chaos.py); an
  injector there stops the loop DEAD — no drain, no future
  resolution, heartbeats cease — the process-death shape. `kill()`
  does the same programmatically. The router's failover requeues the
  replica's in-flight work and greedy replay keeps outputs
  token-identical (tests/test_fleet_router.py pins it).

* **Cross-process streams.** `stream_prefill` / `recv_and_decode` are
  the xproc-transport halves of the disaggregated hand-off used by
  multi-host fleets (and the 2-proc chaos launch test): finished KV
  pages ride `kv_transfer.send_kv_payload` over the p2p socket path —
  RetryPolicy reconnect/resend and the `sock.send`/`sock.recv` chaos
  scopes included.

SHARED-MODEL CAVEAT: two replicas may share one model object only if
both are WARM before concurrent traffic (tracing a compiled step swaps
the model's parameter values for tracers — `fork_model` gives each
replica its own copy and is what the router's autoscale factory should
use; `LocalReplica(warm=True)` (default) warms in the constructor's
thread, so replicas built sequentially over one model are also safe).
"""
import itertools
import os
import threading
import time

import numpy as np

from ...distributed import chaos
from ...observability import metrics as _obs
from ...observability import tracing as _tracing

__all__ = ["ReplicaRegistry", "LocalReplica", "fork_model",
           "stream_prefill", "recv_and_decode"]

_REPLICA_LIVE = _obs.gauge(
    "pt_router_replica_live",
    "replicas currently alive in the registry (heartbeat fresh, loop "
    "running) — the fleet-capacity gauge the autoscaler moves")
_REPLICA_QUEUE = _obs.gauge(
    "pt_replica_queue_depth",
    "per-replica waiting requests (engine queue + server inbox), "
    "refreshed every serve-loop tick — the fleet-wide /metrics view's "
    "per-member load signal (series removed on retirement/death)",
    labelnames=("replica",))
_REPLICA_OCC = _obs.gauge(
    "pt_replica_slot_occupancy",
    "per-replica live slots / num_slots, refreshed every serve-loop "
    "tick (series removed on retirement/death)",
    labelnames=("replica",))

_replica_ids = itertools.count()


def fork_model(model):
    """A private copy of `model` (same config, copied weights) for a
    new replica. `set_state_dict` COPIES at ingest (the PR-11 aliasing
    fix), so the fork shares no mutable state with the source — the
    only safe shape for scale-up while other replicas are serving
    (module docstring caveat)."""
    m = type(model)(model.config)
    m.set_state_dict(model.state_dict())
    m.eval()
    return m


class ReplicaRegistry:  # ptlint: thread-shared (router monitor + replica threads)
    """Heartbeat membership for a replica fleet (elastic-style: the
    registry IS the `hb_<rank>` view, held in-process with an optional
    file mirror for cross-process observers)."""

    def __init__(self, hb_dir=None, timeout_s=2.0):
        self._lock = threading.Lock()
        self._members = {}   # name -> {"replica", "rid", "beat"}
        self.hb_dir = hb_dir
        self.timeout_s = float(timeout_s)

    def register(self, replica):
        with self._lock:
            self._members[replica.name] = {
                "replica": replica, "rid": replica.rid,
                "beat": time.monotonic()}
        self._mirror(replica.rid)
        self._publish()

    def deregister(self, name):
        with self._lock:
            entry = self._members.pop(name, None)
        if entry is not None and self.hb_dir:
            from ...distributed.fleet.elastic import remove_heartbeat

            remove_heartbeat(self.hb_dir, entry["rid"])
        self._publish()

    def beat(self, name):
        with self._lock:
            entry = self._members.get(name)
            if entry is None:
                return
            entry["beat"] = time.monotonic()
            rid = entry["rid"]
        self._mirror(rid)

    def _mirror(self, rid):
        if self.hb_dir:
            from ...distributed.fleet.elastic import touch_heartbeat

            touch_heartbeat(self.hb_dir, rid)

    def ages(self):
        """name -> seconds since the last beat (scrape-safe snapshot)."""
        now = time.monotonic()
        with self._lock:
            return {name: now - e["beat"]
                    for name, e in list(self._members.items())}

    def alive(self, name):
        """Alive = loop running AND heartbeat inside the timeout — a
        wedged loop (hang injector) goes dead by staleness even though
        its thread still exists."""
        with self._lock:
            entry = self._members.get(name)
            if entry is None:
                return False
            fresh = time.monotonic() - entry["beat"] <= self.timeout_s
            return fresh and entry["replica"].running

    def live(self):
        """Names of alive replicas (snapshot)."""
        return [name for name in list(self._members) if self.alive(name)]

    def _publish(self):
        _REPLICA_LIVE.set(len(self.live()))


def _make_server_class():
    """The replica's `LLMServer` subclass (per-tick heartbeat + chaos
    kill hook), built lazily: fleet_serving loads BEFORE
    inference.llm_engine, so the base class cannot be imported at
    module level."""
    from ..llm_engine import LLMServer

    class _Server(LLMServer):
        _thread_name = "fleet-replica"

        def __init__(self, model, config, replica):
            super().__init__(model, config)
            self._replica = replica

        def _loop(self):
            # every span this serve thread emits carries the replica
            # name — the merged timeline's per-replica lanes
            _tracing.set_replica(self._replica.name)
            try:
                super()._loop()
            finally:
                _tracing.set_replica(None)

        def _tick_hook(self):
            rep = self._replica
            if not rep._killed:
                rep.last_tick = time.monotonic()
                rep._registry.beat(rep.name)
                eng = self._engine
                _REPLICA_QUEUE.labels(replica=rep.name).set(
                    len(eng.waiting) + self._q.qsize())
                _REPLICA_OCC.labels(replica=rep.name).set(
                    sum(r is not None for r in list(eng._slots))
                    / eng.num_slots)
                # the kill scopes count BUSY ticks only: an idle loop
                # polls on a wall-clock cadence, so a seeded call
                # index would name a moment, not a serving state —
                # counting work ticks makes "kill at tick N" mean
                # "mid-stream after N scheduling rounds" on every run
                if eng.has_work() or not self._q.empty():
                    try:
                        chaos.fire("replica.kill")
                        chaos.fire(f"replica.kill.{rep.name}")
                    except chaos.InjectedFault:
                        rep._killed = True
                        # postmortem at the moment of death, from the
                        # dying thread: the ring still holds the
                        # victim requests' phase/span trail
                        rep._flight_dump("chaos_replica_kill")
            # True aborts the loop dead: in-flight futures stay
            # unresolved and heartbeats stop — the router requeues
            return rep._killed

    return _Server


class LocalReplica:  # ptlint: thread-shared (router monitor reads; engine thread writes)
    """One fleet member: model + threaded `LLMServer` + registry
    heartbeat (module docstring). The submit surface returns the
    server's futures unchanged; `metrics()`/`queue_depth()` are the
    router's load signals."""

    def __init__(self, model, name=None, config=None, registry=None,
                 role="serve", warm=True):
        self.rid = next(_replica_ids)
        self.name = name or f"replica{self.rid}"
        self.role = str(role)
        self._registry = registry if registry is not None \
            else ReplicaRegistry()
        self._killed = False
        # monotonic stamp of the last serve-loop tick, kept on the
        # REPLICA (the registry drops a deregistered member's beats):
        # the router's failover recovery needs progress evidence that
        # survives expulsion — a hung thread is `running` but does not
        # tick, so `last_tick` is what distinguishes a cleared wedge
        # from an ongoing one
        self.last_tick = 0.0
        cls = _make_server_class()
        self._server = cls(model, config, self)
        if warm:
            self._warm()
        self._server.start()
        self._registry.register(self)

    @property
    def engine(self):
        return self._server.engine

    @property
    def server(self):
        return self._server

    def _warm(self):
        """Compile the decode executables in THIS thread before the
        serve loop starts (the shared-model tracing caveat; also keeps
        first-request latency off the serving path). A short request
        long enough to cross one fused window warms both the
        single-tick and the fused/spec paths."""
        from ...observability import reqtrace as _reqtrace

        eng = self.engine
        k = max(eng.decode_k,
                eng._spec.k + 1 if eng._spec is not None else 1)
        # quiet traces: the warm requests' prefill segments ARE the
        # executable compiles — they must not enter the TTFT phase
        # distribution or the recent-requests view
        req = eng.add_request(np.zeros((2,), np.int32),
                              max_new_tokens=k + 1,
                              trace=_reqtrace.quiet_trace())
        while eng.has_work():
            eng.step()
        req.future.result(timeout=0)
        # warm the disaggregation pair too: export gather + import
        # scatter are fixed-shape (pages_per_seq-padded), so one tiny
        # round trip compiles the executables every later hand-off
        # reuses — the first streamed payload must not pay a compile
        # stall on the decode tier's admission path
        pr = eng.add_request(np.zeros((2,), np.int32),
                             prefill_only=True,
                             trace=_reqtrace.quiet_trace())
        while eng.has_work():
            eng.step()
        ir = eng.import_kv_pages(pr.future.result(timeout=0),
                                 max_new_tokens=1,
                                 trace=_reqtrace.quiet_trace())
        while eng.has_work():
            eng.step()
        ir.future.result(timeout=0)
        eng.stats.update({"steps": 0, "tokens_in": 0, "generated": 0,
                          "finished": 0, "occupancy_sum": 0.0,
                          "fused_steps": 0, "kv_pages_exported": 0,
                          "kv_pages_imported": 0, "prefill_exports": 0})

    # ---- submit surface (thread-safe: LLMServer queue) ----

    def submit(self, prompt, **kw):
        return self._server.submit(prompt, **kw)

    def submit_prefill(self, prompt, **kw):
        """Future -> KVPagePayload (the disaggregated prefill half)."""
        kw.pop("max_new_tokens", None)  # ignored by prefill-only
        return self._server.submit(prompt, prefill_only=True, **kw)

    def submit_imported(self, payload, **kw):
        """Future -> tokens, decoding from an imported payload's
        frontier (the disaggregated decode half)."""
        return self._server.submit(payload.tokens, kv_import=payload,
                                   **kw)

    def export_prefix(self, tokens):
        """Future -> KVPagePayload (or None): cut this replica's trie
        prefix of `tokens` for a hot-prefix pull (router migration —
        docs/SERVING.md "KV memory hierarchy"). Engine-thread work,
        queued behind in-flight submissions like any control op."""
        return self._server.export_prefix(tokens)

    def abort(self, request_id, reason="client", counted=False):
        """Cancel one in-flight request on this replica's engine
        (cancellation propagation — the overload control plane's
        router `cancel` lands here). Rides the server queue; a
        stopped/killed replica swallows it: the request dies with the
        replica anyway and the router owns the client future."""
        try:
            self._server.abort(request_id, reason=reason, counted=counted)
        except RuntimeError:
            pass   # server not started / already stopped

    # ---- liveness / load ----

    @property
    def running(self):
        t = self._server._thread
        return (not self._killed and self._server._running
                and t is not None and t.is_alive())

    @property
    def alive(self):
        return self._registry.alive(self.name)

    def queue_depth(self):
        eng = self.engine
        return len(eng.waiting) + self._server._q.qsize()

    def load(self):
        """(queue_depth, live-slot occupancy): the least-loaded order
        the router's fallback uses — the PR-3 queue/TTFT gauges'
        per-replica view."""
        eng = self.engine
        live = sum(r is not None for r in list(eng._slots))
        return (self.queue_depth(), live / eng.num_slots)

    def metrics(self):
        out = self.engine.metrics()
        out["replica"] = {"name": self.name, "rid": self.rid,
                          "role": self.role, "alive": self.alive,
                          "queue_depth": self.queue_depth()}
        return out

    # ---- lifecycle ----

    def _flight_dump(self, reason):
        """Postmortem into the flight recorder (best-effort): the dead
        member's name plus the requests it was holding, with their
        trace ids — what the failover's requeue is about to replay."""
        try:
            from ...observability import flight_recorder as _fr

            eng = self.engine
            inflight = [{"rid": r.rid, "trace_id": r.trace.trace_id}
                        for r in list(eng._slots) if r is not None]
            _fr.dump(reason, replica=self.name, role=self.role,
                     inflight=inflight, queued=len(eng.waiting))
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the flight-recorder dump itself)
            pass

    def _drop_gauges(self):
        """Remove this replica's labeled gauge series — a dead/retired
        member must not export frozen last-tick values forever."""
        _REPLICA_QUEUE.remove(replica=self.name)
        _REPLICA_OCC.remove(replica=self.name)

    def export_telemetry(self, directory=None):
        """Per-replica telemetry file (`metrics.rank<r>.<name>.json`).
        Threaded replicas share one rank — rank-only naming made them
        overwrite each other's at-exit export; naming by replica keeps
        every member's final view (observability.export_replica)."""
        from ...observability import export_replica

        return export_replica(self.name, self.metrics, directory)

    def kill(self):
        """Die like a lost process: the serve loop exits at its next
        tick without resolving anything, heartbeats stop. (The chaos
        `replica.kill` injector lands here too.)"""
        self._killed = True
        self._flight_dump("replica_kill")

    def stop(self):
        """Graceful retirement (scale-down): drain the queue, stop the
        loop, deregister — and export this member's telemetry view in
        full mode (per-replica file naming: see export_telemetry)."""
        self._server.stop()
        self._registry.deregister(self.name)
        self._drop_gauges()
        try:
            from ...observability import full_enabled

            if full_enabled():
                self.export_telemetry()
        except Exception:  # ptlint: disable=PTL804 (best-effort telemetry export at stop)
            pass


# ---- cross-process disaggregation (xproc transport) -----------------

def stream_prefill(replica, prompt, dst, tag=None, timeout_ms=600_000,
                   **kw):
    """Prefill `prompt` on `replica` and stream the finished KV pages
    to rank `dst` over the p2p socket path (kv_transfer module
    docstring: byte-for-byte, RetryPolicy + chaos-injectable). Returns
    the payload's page count."""
    from .kv_transfer import KV_STREAM_TAG, send_kv_payload

    payload = replica.submit_prefill(prompt, **kw).result()
    send_kv_payload(payload, dst,
                    tag=KV_STREAM_TAG if tag is None else tag,
                    timeout_ms=timeout_ms)
    return payload.num_pages


def recv_and_decode(replica, src, tag=None, timeout_ms=600_000, **kw):
    """Receive one streamed payload from rank `src` and admit it on
    `replica` at its frontier. Returns the decode future."""
    from .kv_transfer import KV_STREAM_TAG, recv_kv_payload

    payload = recv_kv_payload(src,
                              tag=KV_STREAM_TAG if tag is None else tag,
                              timeout_ms=timeout_ms)
    return replica.submit_imported(payload, **kw)
