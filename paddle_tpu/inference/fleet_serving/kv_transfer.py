"""KV-page transfer — the disaggregated-serving wire format.

Prefill/decode disaggregation (docs/SERVING.md "Disaggregated fleet")
moves a request's FINISHED KV pages from the replica that computed them
to the replica that will decode from them. The transport primitive is
cheap exactly because of the byte discipline PRs 4/12 already bought:
an int8/int4 pool's pages plus their fp32 scale planes ARE the
quantized wire format — the pool is stored pre-quantized, so streaming
it byte-for-byte ships ~4x (int8) / ~7x (int4) fewer bytes than an
fp32 KV re-materialization would, with zero re-encode work and zero
additional quantization error (the decode replica attends over the
IDENTICAL bytes the prefill replica wrote — greedy outputs cannot
diverge; tests/test_fleet_router.py pins byte identity for
fp32/int8/int4 including a mid-page frontier page).

The payload is self-describing (`KVPagePayload`): the request's tokens,
how many of them have KV written (`n_prefilled` — the frontier), the
pool geometry it was cut from, and one page-array per layer pool (+ one
scale-plane array per pool when quantized). `pack`/`unpack` give the
byte form; `send_kv_payload`/`recv_kv_payload` move it over the xproc
p2p transport — the same socket path (RetryPolicy reconnect/resend,
chaos `sock.send`/`sock.recv` injection points) every other
cross-process byte in this repo rides, so the KV stream inherits the
PR-1 fault tolerance for free (the 2-proc chaos test injects faults on
exactly this path).

Engine surface: `LLMEngine.export_kv_pages(req)` cuts a payload,
`LLMEngine.import_kv_pages(payload, ...)` admits it at its frontier
(inference/llm_engine.py).
"""
import io
import json
import struct

import numpy as np

from ...observability import metrics as _obs

__all__ = ["KVPagePayload", "pack_kv_payload", "unpack_kv_payload",
           "send_kv_payload", "recv_kv_payload", "KV_STREAM_TAG"]

# default p2p tag for the disaggregated KV stream (one logical channel;
# routers multiplex per-request streams by sequencing on one tag — the
# xproc inbox already orders frames per (src, tag, seq))
KV_STREAM_TAG = 0x4B56  # "KV"

_KV_PAGES_STREAMED = _obs.counter(
    "pt_disagg_kv_pages_streamed",
    "KV pages imported into a decode replica's pool from a prefill "
    "replica's export (disaggregated serving, docs/SERVING.md "
    "\"Disaggregated fleet\")")

# frame: magic, version, meta-json length; then the meta json, then one
# np.save blob per pool array (kv pools first, then scale planes).
# np.save is byte-exact for every pool dtype this repo ships (fp32 /
# bf16 via uint16 view is not needed — jnp bf16 pools export as their
# numpy dtype), and self-describing, so unpack needs no shape math.
_MAGIC = b"PTKV"
_VERSION = 1
_HDR = struct.Struct("<4sBI")


class KVPagePayload:
    """One request's exported KV pages (module docstring). Fields:

    tokens       np.int32 [n] — the request's tokens (prompt so far)
    n_prefilled  tokens whose KV rows the pages hold (the frontier —
                 the last page may be PARTIALLY filled; rows past the
                 frontier are whatever bytes the pool held and are
                 masked by kv_len on the decode side, exactly as they
                 are on the exporting engine)
    page_size    tokens per page of the source pool
    kv_dtype     source pool dtype label ("float32"/"bfloat16"/"int8"/
                 "int4" — import requires an exact match: a cross-dtype
                 import would silently reinterpret quantized codes)
    kv           one np array [num_pages, page_size, H, D'] per layer
                 pool (2 x num_layers: k then v interleaved in pool
                 order), byte-for-byte as stored
    scales       the fp32 scale planes [num_pages, page_size, H] per
                 pool for quantized kv_dtypes, else []
    trace        the request's TraceContext wire dict (observability.
                 reqtrace: trace_id + phase stamps so far) or None —
                 rides the frame header, so the importing replica's
                 spans/phases join the SAME trace the router minted
    """

    def __init__(self, tokens, n_prefilled, page_size, kv_dtype, kv,
                 scales, trace=None):
        # np.array: the payload outlives the call (it rides the wire
        # encoder later) — an aliased token buffer the scheduler then
        # extends in place would ship the wrong prefix (PTL501)
        self.tokens = np.array(tokens, np.int32).reshape(-1)
        self.n_prefilled = int(n_prefilled)
        self.page_size = int(page_size)
        self.kv_dtype = str(kv_dtype)
        self.kv = list(kv)
        self.scales = list(scales)
        self.trace = trace          # wire dict (json-able)
        self.trace_ctx = None       # restored TraceContext (recv side)

    @property
    def num_pages(self):
        return int(self.kv[0].shape[0]) if self.kv else 0

    def nbytes(self):
        return int(sum(a.nbytes for a in self.kv)
                   + sum(a.nbytes for a in self.scales))


def _np_dtype(name):
    """np.dtype by name, extension float types (bfloat16) included —
    np.load round-trips their BYTES but reads the dtype back as a
    void type, so the frame records names and unpack restores them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def pack_kv_payload(payload):
    """KVPagePayload -> bytes (module docstring has the frame)."""
    meta = json.dumps({
        "n_prefilled": payload.n_prefilled,
        "page_size": payload.page_size,
        "kv_dtype": payload.kv_dtype,
        "n_kv": len(payload.kv),
        "n_scales": len(payload.scales),
        "pool_dtypes": [str(a.dtype) for a in payload.kv],
        "trace": payload.trace,
    }).encode("utf-8")
    buf = io.BytesIO()
    buf.write(_HDR.pack(_MAGIC, _VERSION, len(meta)))
    buf.write(meta)
    np.save(buf, payload.tokens, allow_pickle=False)
    for a in payload.kv:
        np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    for a in payload.scales:
        np.save(buf, np.ascontiguousarray(a), allow_pickle=False)
    return buf.getvalue()


def unpack_kv_payload(raw):
    """bytes -> KVPagePayload; byte-identical arrays (parity-pinned)."""
    magic, ver, meta_len = _HDR.unpack_from(raw, 0)
    if magic != _MAGIC:
        raise ValueError(
            f"not a KV-page frame (magic {magic!r}): the KV stream and "
            "other p2p traffic must not share a tag")
    if ver != _VERSION:
        raise ValueError(f"KV-page frame version {ver} != {_VERSION}")
    meta = json.loads(raw[_HDR.size:_HDR.size + meta_len].decode("utf-8"))
    buf = io.BytesIO(raw)
    buf.seek(_HDR.size + meta_len)
    tokens = np.load(buf, allow_pickle=False)
    kv = []
    for name in meta["pool_dtypes"]:
        a = np.load(buf, allow_pickle=False)
        want = _np_dtype(name)
        kv.append(a if a.dtype == want else a.view(want))
    scales = [np.load(buf, allow_pickle=False)
              for _ in range(meta["n_scales"])]
    return KVPagePayload(tokens, meta["n_prefilled"], meta["page_size"],
                         meta["kv_dtype"], kv, scales,
                         trace=meta.get("trace"))


def send_kv_payload(payload, dst, tag=KV_STREAM_TAG, timeout_ms=600_000):
    """Stream one payload to rank `dst` over the xproc p2p transport.
    Byte-for-byte: the frame is already pool-quantized, so it must NOT
    ride the PTQ8 float re-encoder (`send_bytes`, not `send_np`) —
    re-quantizing quantized codes would corrupt them. The payload's
    trace rides the frame header AND the `xproc.send` span (ambient),
    so the transfer leg shows under the request's trace_id on both
    sides of the merged timeline."""
    from ...distributed import xproc
    from ...observability import reqtrace, tracing

    ctx = (reqtrace.TraceContext.from_dict(payload.trace)
           if payload.trace else None)
    with tracing.ambient_trace(ctx):
        xproc.send_bytes(pack_kv_payload(payload), dst, tag=tag,
                         timeout_ms=timeout_ms)


def recv_kv_payload(src, tag=KV_STREAM_TAG, timeout_ms=600_000):
    from ...distributed import xproc
    from ...observability import reqtrace

    payload = unpack_kv_payload(xproc.recv_bytes(src, tag=tag,
                                                 timeout_ms=timeout_ms))
    if payload.trace:
        # restore the exporter's trace and stamp the transfer's END on
        # it — the kv_export -> kv_transfer segment lands on THIS rank
        # (wall clocks align the cross-process chain, like span `ts`)
        ctx = reqtrace.TraceContext.from_dict(payload.trace)
        ctx.stamp("kv_transfer")
        payload.trace = ctx.to_dict()
        payload.trace_ctx = ctx
    return payload
