"""Fleet router — radix-affinity routing, SLO autoscale, failover.

The tier above everything PRs 2–12 built (ROADMAP item 1): N replica
engines behind ONE submit surface. Three policies, all host-side:

* **Radix-affinity routing.** The prompt's leading blocks are
  fingerprinted at `hash_block_tokens` granularity — the SAME
  content-at-position identity the per-engine `RadixPrefixCache` tries
  key on — and the request routes to the replica whose (router-side)
  prefix view holds the LONGEST match, so a fleet sharing system
  prompts concentrates each prefix's KV on one replica instead of
  re-prefilling it everywhere: the PR-7 cache becomes a fleet-wide
  asset. No match (or a tie at zero) falls back to LEAST-LOADED by the
  per-replica queue-depth/occupancy gauges (the PR-3 load signals).

* **Prefill/decode disaggregation.** With prefill-role replicas
  attached, prompts at/above `prefill_min_tokens` chunk-prefill on a
  prefill replica and their finished KV pages stream to the affinity-
  chosen decode replica (`kv_transfer` byte discipline), which admits
  the request AT ITS FRONTIER — long-prompt admission stops stealing
  the decode replicas' fused/speculative windows, which is the
  decode-side TTFT p99 win the `llm_fleet_multi` bench arm measures.

* **SLO autoscale + failover.** A monitor thread watches heartbeats
  and queue depth: sustained pressure above `queue_high` grows the
  fleet through the replica factory (up to `max_replicas`), an idle
  fleet shrinks gracefully (drained replicas retire), and a DEAD
  replica (chaos kill, wedge, crash) has its in-flight requests
  REQUEUED — prompts replay through the prefix/KV machinery on a
  surviving replica, and greedy decode makes the replayed outputs
  token-identical to the unkilled run (the chaos acceptance;
  client futures never observe the death).

Failover guarantee (docs/SERVING.md "Disaggregated fleet"): at-least-
once execution with deterministic outputs — a request may run twice
(the killed replica's partial work is discarded), never zero times,
and the client-visible tokens are identical either way. Requests are
NOT persisted: losing the router process loses its queue (the router
is one process supervising in-process replicas; cross-process fleets
put the durable queue in front).

Metrics: pt_router_requests / pt_router_affinity_hits /
pt_router_replica_live / pt_router_requeues (+ the kv_transfer stream
counter). docs/OBSERVABILITY.md has the catalogue rows.
"""
import itertools
import threading
import time

import numpy as np

from ...observability import flight_recorder as _flight
from ...observability import metrics as _obs
from ...observability import reqtrace as _reqtrace
from .overload import (BrownoutController, CircuitBreaker, OverloadPolicy,
                       RequestCancelled, RequestShed, TTFTEstimator,
                       note_cancelled, note_hedge, note_shed)
from .replica import LocalReplica, ReplicaRegistry
from .scheduler import Priority

__all__ = ["AutoscalePolicy", "FleetRouter"]

_ROUTER_REQS = _obs.counter(
    "pt_router_requests",
    "requests routed by the fleet router (process-global)")
_AFFINITY_HITS = _obs.counter(
    "pt_router_affinity_hits",
    "routed requests whose chosen replica held a non-empty radix "
    "prefix match (the fleet-wide cache-locality rate)")
_REQUEUES = _obs.counter(
    "pt_router_requeues",
    "in-flight requests requeued off a dead replica (failover — "
    "greedy outputs stay token-identical under replay)")
_MONITOR_ERRORS = _obs.counter(
    "pt_router_monitor_errors",
    "exceptions swallowed by the router monitor's failover/autoscale "
    "ticks (supervision survives a bad tick, but a persistently "
    "failing one — e.g. a factory that cannot build replicas — must "
    "be visible, not a silent poll-rate retry loop)")
_SPILL_SCALEUPS = _obs.counter(
    "pt_router_spill_scale_ups",
    "scale-ups triggered by sustained fleet KV spill pressure rather "
    "than queue depth (the memory-bound growth signal: queues look "
    "healthy while the tier sheds pages, so TTFT regresses via cold "
    "recompute instead of visible backlog)")
_ROUTER_TTFT = _obs.histogram(
    "pt_router_ttft_seconds",
    "client-observed TTFT at the ROUTER ingress (submit -> the serving "
    "replica's first-token stamp) — the fleet-wide latency the "
    "per-engine pt_llm_ttft_seconds cannot see across a hand-off")


class AutoscalePolicy:
    """Autoscale/monitor knobs (docs/SERVING.md has the tuning table).

    min_replicas / max_replicas  fleet size bounds
    queue_high       mean waiting-per-replica that triggers scale-UP
                     (sustained: two consecutive monitor ticks)
    spill_high       fleet KV spill_pressure (fraction of spill
                     attempts the host-RAM/disk tier rejected or aged
                     out — kv_tier block in metrics()) at/above which
                     the fleet grows even with healthy queues; memory-
                     bound traffic sheds pages long before it queues.
                     Shares queue_high's two-tick hysteresis; an
                     over-pressure fleet never retires replicas
    queue_low        fleet-wide waiting total at/below which an IDLE
                     replica (no queue, no in-flight) may retire
    cooldown_s       minimum seconds between scaling actions
    heartbeat_timeout_s  staleness after which a replica counts dead
    poll_s           monitor loop period
    """

    def __init__(self, min_replicas=1, max_replicas=4, queue_high=8,
                 queue_low=0, cooldown_s=1.0, heartbeat_timeout_s=2.0,
                 poll_s=0.02, spill_high=0.5):
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.queue_high = float(queue_high)
        self.queue_low = float(queue_low)
        self.spill_high = float(spill_high)
        self.cooldown_s = float(cooldown_s)
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.poll_s = float(poll_s)
        if not 1 <= self.min_replicas <= self.max_replicas:
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")


class _RoutedRequest:
    _ids = itertools.count()

    def __init__(self, prompt, kwargs, future, trace=None):
        self.rid = next(_RoutedRequest._ids)
        self.prompt = prompt
        self.kwargs = kwargs       # submit kwargs (eos, sampling, SLA)
        self.future = future       # client-facing
        # fleet-wide identity: every engine request, span, and KV
        # payload this request touches — on ANY replica — carries this
        # trace (observability.reqtrace). Requeue/replay attempts share
        # it; first-wins stamps keep the first attempt's timeline.
        self.trace = trace if trace is not None else _reqtrace.new_trace()
        self.trace.stamp("queued")
        self.replica = None        # name currently serving it
        self.internal = None       # the replica-side Future
        self.stage = None          # "prefill" | "decode"
        self.payload = None        # streamed KV (between stages)
        self.no_disagg = False     # prefill fallback taken
        self.requeues = 0
        self.affinity_hit = False
        self.resolved = False      # exactly-one-outcome gate (lock-held)
        self.t_submit = time.perf_counter()
        # overload control plane (fleet_serving.overload)
        self.deadline_t = None     # absolute perf_counter hard deadline
        self.hedges = 0            # hedged re-dispatches taken
        self._prefill_t0 = None    # hand-off latency (breaker window)


class FleetRouter:  # ptlint: thread-shared (client submits + monitor + replica callbacks)
    """N replicas behind one `submit` (module docstring).

        router = FleetRouter(factory=make_replica, policy=...)
        with router:
            fut = router.submit(prompt_ids, max_new_tokens=64)
            tokens = fut.result()

    `factory(name) -> LocalReplica` builds members (give each its OWN
    model copy — `replica.fork_model`); pre-built replicas can be
    passed instead/in addition via `replicas=[...]`. Prefill-role
    replicas (`prefill_replicas` or factory-built with
    `prefill_factory`) enable the disaggregated hand-off for prompts
    >= `prefill_min_tokens`."""

    def __init__(self, replicas=None, factory=None, policy=None,
                 hash_block_tokens=16, max_affinity_blocks=8,
                 prefill_replicas=None, prefill_min_tokens=None,
                 registry=None, overload=None, migrate_hot_hits=None,
                 migrate_interval_s=5.0, migrate_budget=2):
        self.policy = policy or AutoscalePolicy()
        # overload control plane (fleet_serving.overload; docs/SERVING
        # "Overload and degradation") — defaults are inert where
        # behaviour would change: brownout/hedging opt-in, generous
        # parking bound, failure-count-only breaker
        self.overload = overload or OverloadPolicy()
        self.registry = registry if registry is not None else \
            ReplicaRegistry(timeout_s=self.policy.heartbeat_timeout_s)
        self._factory = factory
        self.hash_block_tokens = int(hash_block_tokens)
        self.max_affinity_blocks = int(max_affinity_blocks)
        self.prefill_min_tokens = (None if prefill_min_tokens is None
                                   else int(prefill_min_tokens))
        # hot-prefix page migration (docs/SERVING.md "KV memory
        # hierarchy"): when a prefix's affinity holder is busier than
        # a peer, PULL its cached pages to the peer over the byte-
        # exact KV wire instead of routing around the miss. Off by
        # default (migrate_hot_hits=None); `migrate_hot_hits` routed
        # hits on one leading block within `migrate_interval_s` make
        # the prefix hot, and at most `migrate_budget` pulls run per
        # interval (a migration costs a D2H gather on the donor).
        self.migrate_hot_hits = (None if migrate_hot_hits is None
                                 else int(migrate_hot_hits))
        self.migrate_interval_s = float(migrate_interval_s)
        self.migrate_budget = int(migrate_budget)
        self._hot = {}             # first-block key -> hits this window
        self._hot_t0 = time.monotonic()
        self._migrations_left = self.migrate_budget
        self._lock = threading.Lock()
        self._replicas = {}        # name -> LocalReplica (decode/serve)
        self._prefill = {}         # name -> LocalReplica (prefill role)
        self._expelled = {}        # name -> replica removed by failover
        self._affinity = {}        # name -> {prefix-key: last-use clock}
        self._clock = itertools.count()
        self._inflight = {}        # rid -> _RoutedRequest
        # per-ROUTER TTFT distribution (unregistered Histogram: the
        # registry's pt_router_ttft_seconds is process-global — two
        # routers in one process must not blur each other's view)
        self._ttft_hist = _obs.Histogram("router_ttft_local")
        self._monitor = None
        self._http = None
        self._running = False
        self._last_scale = 0.0
        self._pressure_ticks = 0
        self.stats = {"requests": 0, "affinity_hits": 0, "requeues": 0,
                      "scale_ups": 0, "scale_downs": 0,
                      "spill_scale_ups": 0,
                      "disagg_handoffs": 0, "replicas_lost": 0,
                      "shed": 0, "cancelled": 0, "hedges": 0,
                      "brownout_level": 0, "migrations": 0,
                      "migration_failures": 0}
        pol = self.overload
        self._estimator = TTFTEstimator()
        self._breaker = CircuitBreaker(
            window=pol.breaker_window,
            failure_rate=pol.breaker_failure_rate,
            latency_s=pol.breaker_latency_s,
            min_events=pol.breaker_min_events,
            reset_s=pol.breaker_reset_s)
        self._brownout_ctl = BrownoutController(
            pol, apply_fn=self._apply_brownout)
        for r in (replicas or ()):
            self._adopt(r)
        for r in (prefill_replicas or ()):
            self._adopt(r)

    def _adopt(self, replica):
        with self._lock:
            if replica.role == "prefill":
                self._prefill[replica.name] = replica
            else:
                self._replicas[replica.name] = replica
                self._affinity.setdefault(replica.name, {})
        if self._brownout_ctl.level:
            # a member joining mid-brownout (scale-up, recovery) must
            # degrade like the rest of the fleet
            try:
                replica.engine.apply_brownout(self._brownout_ctl.caps())
            except Exception:
                # an engine without brownout support degrades later —
                # but the miss must be visible, not silent (PTL804)
                _MONITOR_ERRORS.inc()
        if replica._registry is not self.registry:
            # one membership view: the router's failover watches ITS
            # registry, so members must beat into it
            replica._registry = self.registry
            self.registry.register(replica)

    # ---- lifecycle ----

    def start(self):
        if self._running:
            return self
        while (len(self._replicas) < self.policy.min_replicas
               and self._factory is not None):
            self._scale_up()
        if not self._replicas:
            raise RuntimeError(
                "FleetRouter needs at least one serve-role replica "
                "(pass replicas=[...] or a factory)")
        self._running = True
        # dump-time state: every postmortem carries this router's full
        # fleet view (unique key — tests run several routers)
        self._fr_key = f"router:{id(self):x}"
        _flight.add_state_provider(self._fr_key, self.metrics)
        self._monitor = threading.Thread(target=self._monitor_loop,
                                         name="fleet-router",
                                         daemon=True)
        self._monitor.start()
        return self

    def stop(self):
        self._running = False
        _flight.remove_state_provider(getattr(self, "_fr_key", ""))
        if self._http is not None:
            self._http.stop()
            self._http = None
        if self._monitor is not None:
            self._monitor.join(timeout=30)
            self._monitor = None
        for r in (list(self._replicas.values())
                  + list(self._prefill.values())
                  + [rep for rep, _ in list(self._expelled.values())]):
            # expelled members included: a wedged-then-recovered-too-
            # late replica still owns a live serve thread
            r.stop()
        # anything still unresolved after the graceful drain is lost
        for rr in self._drain_inflight():
            if not rr.future.done():
                rr.future.set_exception(
                    RuntimeError("router stopped with request in flight"))

    def _drain_inflight(self):
        with self._lock:
            out = list(self._inflight.values())
            self._inflight.clear()
        return out

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- client surface ----

    def submit(self, prompt, max_new_tokens=32, eos_token_id=None,
               **kw):
        """Route one prompt; returns the client Future (tokens). The
        kwargs surface is `LLMServer.submit`'s, plus the overload
        knobs: `deadline_s` is a HARD deadline — a request whose
        deadline is provably unmeetable sheds at submit with a typed
        `RequestShed` (retry_after_s hint attached), one that expires
        mid-flight cancels with `RequestCancelled`. The returned
        future carries `pt_rid`, the handle `cancel(pt_rid)` takes."""
        from concurrent.futures import Future

        if not self._running:
            raise RuntimeError("router not started (use `with router:`)")
        # loud ingress hardening (same pattern as the engine's
        # _check_import): an unknown kwarg or a malformed structured-
        # decoding constraint raises HERE, at submit(), with the
        # offending name — not as a serve-loop error on whichever
        # replica the request lands, where it would abort co-resident
        # requests. Grammar COMPILATION still happens replica-side
        # (it needs the engine's token_strs); this gate is structural.
        from ..llm_engine import SUBMIT_KWARGS
        from ..structured import validate_constraints

        unknown = set(kw) - SUBMIT_KWARGS
        if unknown:
            raise TypeError(
                f"submit() got unknown kwarg(s) {sorted(unknown)} — "
                f"the surface is {sorted(SUBMIT_KWARGS)}")
        validate_constraints(grammar=kw.get("grammar"),
                             json_schema=kw.get("json_schema"),
                             spec_mode=kw.get("spec_mode"))
        prompt = np.asarray(prompt).reshape(-1)
        # a caller-minted trace (a gateway in front of this router)
        # must not collide with the per-replica submit's own trace kwarg
        trace = kw.pop("trace", None)
        deadline_s = kw.pop("deadline_s", None)
        rr = _RoutedRequest(
            prompt, dict(max_new_tokens=int(max_new_tokens),
                         eos_token_id=eos_token_id, **kw), Future(),
            trace=trace)
        rr.future.pt_rid = rr.rid     # the cancel() handle
        if deadline_s is not None:
            rr.deadline_t = rr.t_submit + float(deadline_s)
        with self._lock:
            self._inflight[rr.rid] = rr
            self.stats["requests"] += 1
        _ROUTER_REQS.inc()
        self._estimator.note_prompt(len(prompt))
        self._admission_control(rr, deadline_s)
        if not rr.future.done():
            self._dispatch(rr)
        return rr.future

    def generate(self, prompt, max_new_tokens=32, eos_token_id=None):
        return self.submit(prompt, max_new_tokens, eos_token_id).result()

    def cancel(self, request_id, reason="client"):
        """Cancel one in-flight request (`fut.pt_rid` is the handle).
        The abort propagates across every tier the request currently
        touches — router bookkeeping, the replica engine serving it
        (slot, pool pages, trie pins), and any KV payload parked
        between the prefill and decode stages — and the client future
        resolves with `RequestCancelled`. Returns False when the id is
        unknown or already resolved (result delivery won the race)."""
        with self._lock:
            rr = self._inflight.get(int(request_id))
            if rr is None or rr.resolved:
                return False
            rr.resolved = True       # exactly-one-outcome gate
            self._inflight.pop(rr.rid, None)
            self.stats["cancelled"] += 1
            rep = (self._replicas.get(rr.replica)
                   or self._prefill.get(rr.replica))
        note_cancelled(reason)
        rr.trace.stamp("cancelled")
        _flight.record_event("request_cancelled", rid=rr.rid,
                             trace_id=rr.trace.trace_id, reason=reason,
                             stage=rr.stage, replica=rr.replica)
        rr.payload = None            # KV parked between stages: dropped
        internal = rr.internal
        if internal is not None:
            req = getattr(internal, "pt_request", None)
            if req is not None and rep is not None:
                # already ingested by the replica engine: evict there
                # (counted here — the engine must not double-count)
                rep.abort(req.rid, reason=reason, counted=True)
            else:
                # still in the server queue: the ingest loop skips
                # cancelled futures without touching the engine
                internal.cancel()
        if not rr.future.done():
            rr.future.set_exception(RequestCancelled(
                reason=reason, trace_id=rr.trace.trace_id))
        return True

    # ---- admission control (fleet_serving.overload) ----

    def _shed_key(self, rr):
        """Shed order under pressure: LOWEST priority class first, then
        LATEST deadline (no deadline = infinitely patient = first to
        go), then newest. max() of this key picks the victim."""
        pri = rr.kwargs.get("priority")
        pri = int(Priority.STANDARD if pri is None else pri)
        dl = (float("inf") if rr.deadline_t is None
              else rr.deadline_t)
        return (pri, dl, rr.rid)

    def _shed(self, rr, reason, retry_after_s=None):
        """Typed admission refusal: pop from inflight, count, flight-
        record, resolve the client future with RequestShed. Respects
        the exactly-one-outcome gate; returns False when rr already
        resolved."""
        with self._lock:
            if rr.resolved:
                return False
            rr.resolved = True
            self._inflight.pop(rr.rid, None)
            self.stats["shed"] += 1
        note_shed(reason)
        _flight.record_event("request_shed", rid=rr.rid,
                             trace_id=rr.trace.trace_id, reason=reason,
                             retry_after_s=retry_after_s)
        if not rr.future.done():
            rr.future.set_exception(RequestShed(
                reason, retry_after_s=retry_after_s,
                trace_id=rr.trace.trace_id))
        return True

    def _queued_tokens(self):
        """Work ahead of a new arrival, in tokens (queue depths ×
        the EMA prompt length) — the TTFT lower bound's numerator."""
        depth = sum(r.queue_depth() for r in self._alive_replicas())
        with self._lock:
            depth += sum(rr.stage == "parked"
                         for rr in self._inflight.values())
        return depth * self._estimator.avg_prompt_tokens()

    def _admission_control(self, rr, deadline_s):
        """Reject-early checks at submit (docs/SERVING.md "Overload
        and degradation"): expired deadline, provably-unmeetable
        deadline (optimistic TTFT lower bound from live telemetry vs
        the deadline), brownout best-effort-class shed, and the
        max_inflight capacity bound (worst parked victim — or the
        newcomer — sheds)."""
        pol = self.overload
        if deadline_s is not None:
            ds = float(deadline_s)
            if ds <= 0.0:
                self._shed(rr, "deadline")
                return
            lb = self._estimator.lower_bound_ttft(
                self._queued_tokens() + len(rr.prompt))
            if lb > ds:
                # provable: even at the best service rate ever
                # observed the first token lands after the deadline
                self._shed(rr, "deadline_unmeetable",
                           retry_after_s=round(lb - ds, 3))
                return
        sp = self._brownout_ctl.shed_priority()
        if sp is not None:
            pri = rr.kwargs.get("priority")
            pri = int(Priority.STANDARD if pri is None else pri)
            if pri >= int(sp):
                self._shed(rr, "brownout", retry_after_s=max(
                    0.05, round(self._estimator.lower_bound_ttft(
                        self._queued_tokens()), 3)))
                return
        if pol.max_inflight is not None:
            with self._lock:
                over = len(self._inflight) > pol.max_inflight
                cands = ([x for x in self._inflight.values()
                          if x.stage == "parked"] + [rr]) if over else ()
            if over:
                victim = max(cands, key=self._shed_key)
                self._shed(victim, "capacity", retry_after_s=max(
                    0.05, round(self._estimator.lower_bound_ttft(
                        self._queued_tokens()), 3)))

    # ---- routing ----

    def _block_keys(self, tokens):
        """Leading-block fingerprints: key i covers tokens[:(i+1)*bt] —
        content AND position, the RadixPrefixCache node identity, so
        router affinity and the engine trie agree on what 'the same
        prefix' means."""
        bt = self.hash_block_tokens
        n = min(len(tokens) // bt, self.max_affinity_blocks)
        return [np.asarray(tokens[:(i + 1) * bt], np.int32).tobytes()
                for i in range(n)]

    def _alive_replicas(self, exclude=()):
        with self._lock:
            reps = list(self._replicas.values())
        return [r for r in reps
                if r.name not in exclude and r.alive]

    def _pick(self, tokens, exclude=()):
        """(replica, matched_blocks): longest router-side prefix match,
        least-loaded fallback. Registers the prompt's blocks on the
        winner so the NEXT same-prefix request lands there too."""
        alive = self._alive_replicas(exclude)
        if not alive:
            return None, 0
        keys = self._block_keys(tokens)
        best, best_len = None, 0
        for r in alive:
            store = self._affinity.get(r.name, {})
            ln = 0
            for k in keys:
                if k not in store:
                    break
                ln += 1
            if ln > best_len:
                best, best_len = r, ln
        if best is None:
            best = min(alive, key=lambda r: r.load())
        if keys:
            with self._lock:
                store = self._affinity.setdefault(best.name, {})
                for k in keys:
                    store[k] = next(self._clock)
                cap = 4096 * self.max_affinity_blocks
                if len(store) > cap:
                    # LRU cap: affinity is a ROUTING HINT, not state —
                    # dropping old keys only costs a fallback route.
                    # Trim to HALF the cap (not a flat floor): the hit
                    # rate degrades smoothly instead of collapsing to
                    # ~nothing on every trim
                    keep = sorted(store.items(), key=lambda kv: kv[1],
                                  reverse=True)[:cap // 2]
                    self._affinity[best.name] = dict(keep)
        return best, best_len

    def _pick_prefill(self, exclude=()):
        with self._lock:
            reps = list(self._prefill.values())
        alive = [r for r in reps if r.name not in exclude and r.alive]
        if not alive:
            return None
        return min(alive, key=lambda r: r.load())

    def _deadlined(self, kwargs, rr):
        """Per-dispatch submit kwargs: the REMAINING deadline rides to
        the replica engine (which expires it mid-flight) — remaining,
        not absolute, so a requeued attempt keeps the original
        contract. kwargs is copied; rr.kwargs stays pristine for
        re-dispatch."""
        kw = dict(kwargs)
        if rr.deadline_t is not None:
            kw["deadline_s"] = rr.deadline_t - time.perf_counter()
        return kw

    def _dispatch(self, rr, exclude=()):
        """Place `rr` on a replica (possibly via the prefill stage).
        Called at submit, at stage hand-off, at failover requeue, and
        at hedged re-dispatch — a superseded internal future's outcome
        is suppressed by the stale-attempt checks."""
        if rr.future.done():
            return
        disagg = (self.prefill_min_tokens is not None
                  and not rr.no_disagg and rr.payload is None
                  and len(rr.prompt) >= self.prefill_min_tokens)
        if disagg:
            pre = self._pick_prefill(exclude)
            if pre is None:
                rr.no_disagg = True  # no live prefill: serve whole
            elif self._breaker.allow() and self._dispatch_prefill(rr, pre):
                # breaker open ≠ no_disagg: the tier is SICK, not
                # absent — a later (requeued) dispatch may retry it
                # once the breaker half-opens
                return
        rep, matched = self._pick(rr.prompt, exclude)
        if rep is None:
            # no live replica AT ALL: park it — the monitor requeues
            # once the factory (or a recovering heartbeat) restores
            # one. The parking queue is BOUNDED: past max_parked the
            # worst-placed request (shed order) gets a typed shed
            # instead of unbounded growth.
            with self._lock:
                parked = [x for x in self._inflight.values()
                          if x.stage == "parked" and x is not rr]
            if len(parked) >= self.overload.max_parked:
                victim = max(parked + [rr], key=self._shed_key)
                self._shed(victim, "no_capacity")
                if victim is rr:
                    return
            rr.stage, rr.replica, rr.internal = "parked", None, None
            return
        if matched and rr.requeues == 0 and rr.payload is None:
            rr.affinity_hit = True
            with self._lock:
                self.stats["affinity_hits"] += 1
            _AFFINITY_HITS.inc()
            target = self._migrate_check(rr, rep)
            if target is not None and self._start_migration(
                    rr, rep, target):
                return
        rr.stage = "decode"
        rr.replica = rep.name
        rr.trace.stamp("routed")
        if rr.payload is not None:
            with self._lock:
                self.stats["disagg_handoffs"] += 1
            payload, rr.payload = rr.payload, None  # consumed
            rr.internal = rep.submit_imported(
                payload, trace=rr.trace, **self._deadlined(rr.kwargs, rr))
        else:
            rr.internal = rep.submit(
                rr.prompt, trace=rr.trace,
                **self._deadlined(rr.kwargs, rr))
        rr.internal.add_done_callback(
            lambda f, rr=rr: self._on_decode_done(rr, f))

    def _dispatch_prefill(self, rr, pre):
        """Bind rr to the prefill tier; False when the submit itself
        fails (a stopping replica) — counted against the breaker, and
        the caller falls through to whole-request serving."""
        rr.stage, rr.replica = "prefill", pre.name
        rr.trace.stamp("routed")
        rr._prefill_t0 = time.monotonic()
        try:
            rr.internal = pre.submit_prefill(
                rr.prompt, trace=rr.trace,
                **self._deadlined(
                    {k: rr.kwargs[k] for k in
                     ("tenant", "priority", "ttft_slo_s")
                     if k in rr.kwargs}, rr))
        except Exception:
            self._breaker.record_failure()
            rr.stage, rr.replica = None, None
            return False
        rr.internal.add_done_callback(
            lambda f, rr=rr: self._on_prefill_done(rr, f))
        return True

    def _on_prefill_done(self, rr, fut):
        if rr.future.done() or fut is not rr.internal:
            # stale attempt: the request was already requeued onto
            # another replica — the live attempt owns the hand-off
            return
        err = fut.exception()
        if isinstance(err, (RequestCancelled, RequestShed)):
            # the ENGINE cancelled/shed this very request (deadline
            # expiry, brownout class): that is the request's typed
            # outcome, not tier sickness — propagate, don't fall back
            # and don't count against the breaker
            with self._lock:
                if rr.resolved:
                    return
                rr.resolved = True
                self._inflight.pop(rr.rid, None)
            if not rr.future.done():
                rr.future.set_exception(err)
            return
        if err is not None:
            # prefill failed (bad request / replica abort): fall back
            # to serving the whole request on a decode replica — only a
            # request the DECODE side also rejects errors the client
            self._breaker.record_failure()
            rr.no_disagg = True
            self._dispatch(rr)
            return
        self._breaker.record_success(
            0.0 if rr._prefill_t0 is None
            else time.monotonic() - rr._prefill_t0)
        rr.payload = fut.result()
        rr.trace.stamp("kv_transfer")   # the in-process hand-off moment
        self._dispatch(rr)

    def _on_decode_done(self, rr, fut):
        if rr.future.done():
            return
        err = fut.exception()
        if err is not None and fut is not rr.internal:
            # a SUPERSEDED attempt failing late (the replica it ran
            # on died/aborted after the requeue) must not poison the
            # client while the live retry is still running — that
            # would be the very death the failover guarantee hides.
            # (A stale SUCCESS is kept: greedy outputs are
            # deterministic, so first-wins is correct.)
            return
        # exactly-one-outcome gate: a stale and a live attempt can
        # complete near-simultaneously on two replica threads — only
        # the winner may resolve, attach pt_request, and record TTFT
        # (the loser would otherwise clobber pt_request and append a
        # second, wedge-inflated TTFT sample)
        with self._lock:
            if rr.resolved:
                return
            rr.resolved = True
        if err is not None:
            if not rr.future.done():
                rr.future.set_exception(err)
        else:
            req = getattr(fut, "pt_request", None)
            # mirror the LLMServer.submit contract on the CLIENT
            # future (set BEFORE the result so a completed future
            # always carries it): the serving replica's _Request is
            # where per-request TTFT stamps live
            rr.future.pt_request = req
            if not rr.future.done():
                rr.future.set_result(fut.result())
            if req is not None and req.t_first_token is not None:
                ttft = req.t_first_token - rr.t_submit
                self._ttft_hist.observe(ttft)
                _ROUTER_TTFT.observe(ttft)
        with self._lock:
            self._inflight.pop(rr.rid, None)

    # ---- hot-prefix page migration (docs/SERVING.md) ----

    def _migrate_check(self, rr, holder):
        """Pull-vs-route decision for an affinity-hit request: returns
        the replica to pull the prefix's pages TO, or None to route to
        the holder as usual. A prefix is hot once its leading block
        takes `migrate_hot_hits` routed hits inside the current
        `migrate_interval_s` window; the pull fires only while the
        window's `migrate_budget` lasts and only toward a STRICTLY
        less-loaded alive peer (the point is relieving the holder, not
        shuffling pages between equally-busy members)."""
        if self.migrate_hot_hits is None or rr.requeues:
            return None
        keys = self._block_keys(rr.prompt)
        if not keys:
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._hot_t0 >= self.migrate_interval_s:
                self._hot_t0 = now
                self._hot.clear()
                self._migrations_left = self.migrate_budget
            hits = self._hot.get(keys[0], 0) + 1
            self._hot[keys[0]] = hits
            if hits < self.migrate_hot_hits or not self._migrations_left:
                return None
        peers = [r for r in self._alive_replicas()
                 if r.name != holder.name and r.load() < holder.load()]
        if not peers:
            return None
        with self._lock:
            self._migrations_left -= 1
            self._hot[keys[0]] = 0    # re-arm: next pull needs fresh heat
        return min(peers, key=lambda r: r.load())

    def _start_migration(self, rr, src, target):
        """Kick the donor's engine-thread prefix cut; rr parks in stage
        'migrate' (the failover orphan sweep covers it) until the
        payload lands. False when the donor refuses the export (a
        stopping replica) — the caller routes normally."""
        rr.stage, rr.replica = "migrate", src.name
        try:
            fut = src.export_prefix(rr.prompt)
        except Exception:
            rr.stage, rr.replica = None, None
            return False
        rr.internal = fut
        fut.add_done_callback(
            lambda f, rr=rr, t=target: self._on_migrate_export(rr, t, f))
        return True

    def _on_migrate_export(self, rr, target, fut):
        if rr.future.done() or fut is not rr.internal:
            return     # stale attempt: failover already requeued rr
        from .kv_tier import _MIGRATIONS
        from .kv_transfer import pack_kv_payload, unpack_kv_payload

        payload = None if fut.exception() is not None else fut.result()
        if payload is None or not target.alive:
            # donor trie went cold (evicted under us) or the export
            # died or the target died meanwhile: route normally, once
            with self._lock:
                self.stats["migration_failures"] += 1
            rr.internal = None
            self._dispatch(rr)
            return
        # the byte-exact xproc wire discipline: the payload crosses
        # pack -> unpack exactly as a cross-process pull would, so the
        # imported bytes are PROVABLY the donor's stored bytes (no
        # re-encode — int4/int8 codes + scale planes ride verbatim)
        payload = unpack_kv_payload(pack_kv_payload(payload))
        nb = payload.n_prefilled // self.hash_block_tokens
        with self._lock:
            self.stats["migrations"] += 1
            store = self._affinity.setdefault(target.name, {})
            for k in self._block_keys(rr.prompt)[:nb]:
                store[k] = next(self._clock)
        _MIGRATIONS.inc()
        _flight.record_event("kv_migration", rid=rr.rid,
                             trace_id=rr.trace.trace_id,
                             to=target.name, was_on=rr.replica,
                             pages=payload.num_pages)
        rr.stage, rr.replica = "decode", target.name
        rr.internal = target.submit_imported(
            payload, trace=rr.trace, **self._deadlined(rr.kwargs, rr))
        rr.internal.add_done_callback(
            lambda f, rr=rr: self._on_decode_done(rr, f))

    # ---- monitor: failover + autoscale ----

    def _monitor_loop(self):
        # the monitor must outlive any single bad tick (a failover
        # racing a graceful stop() retries next poll rather than
        # ending supervision) — but every swallowed error is COUNTED
        # and kept in the snapshot, and the ticks fail independently
        # (an autoscale error must not mask the failover scan)
        last_state = 0.0
        while self._running:
            time.sleep(self.policy.poll_s)
            try:
                self._failover_tick()
            except Exception as e:
                self._note_monitor_error(e)
            try:
                self._autoscale_tick()
            except Exception as e:
                self._note_monitor_error(e)
            try:
                self._overload_tick()
            except Exception as e:
                self._note_monitor_error(e)
            now = time.monotonic()
            if now - last_state >= 0.5:
                # throttled fleet-state capture into the flight ring:
                # a postmortem shows the minutes BEFORE the failure,
                # not just its instant
                last_state = now
                try:
                    with self._lock:
                        reps = list(self._replicas.values()) + list(
                            self._prefill.values())
                        inflight = len(self._inflight)
                    _flight.record_event(
                        "router_state", inflight=inflight,
                        requeues=self.stats["requeues"],
                        replicas={r.name: {"alive": r.alive,
                                           "queue": r.queue_depth()}
                                  for r in reps})
                except Exception as e:
                    self._note_monitor_error(e)

    def _note_monitor_error(self, exc):
        _MONITOR_ERRORS.inc()
        with self._lock:
            self.stats["monitor_errors"] = (
                self.stats.get("monitor_errors", 0) + 1)
            self.stats["last_monitor_error"] = repr(exc)

    def _failover_tick(self):
        # recovery scan FIRST: an expelled member that TICKED after
        # its expulsion was only transiently stale (a wedge that
        # cleared, a slow step) — re-register (fresh beat) and
        # re-adopt it, so a stall never permanently shrinks the fleet
        # (its requeued work may have run twice: at-least-once,
        # outputs deterministic). Progress evidence is `last_tick`,
        # NOT thread aliveness: a STILL-hung loop is `running` too,
        # and re-adopting it would flap expel→re-adopt every
        # heartbeat timeout, stranding fresh dispatches on a wedge.
        # A killed/dead member never ticks again and stays expelled
        # until stop().
        with self._lock:
            expelled = list(self._expelled.items())
        for name, (rep, t_expelled) in expelled:
            if rep.running and rep.last_tick > t_expelled:
                with self._lock:
                    self._expelled.pop(name, None)
                    self.stats["replicas_recovered"] = (
                        self.stats.get("replicas_recovered", 0) + 1)
                self.registry.register(rep)
                self._adopt(rep)
        with self._lock:
            serve = list(self._replicas.items())
            pre = list(self._prefill.items())
        for name, rep in serve + pre:
            # DEAD = not alive: loop stopped OR heartbeat stale. A
            # WEDGED loop (hang injector, stuck dispatch) keeps its
            # thread — gating on `running` too would strand its
            # in-flight work forever
            if rep.alive:
                continue
            self._handle_death(name, rep)
        # orphan sweep: a dispatch that raced a death can bind a
        # request to a member _handle_death already removed (its
        # victims snapshot predates the bind) — requeue anything
        # pointing at a name that is no longer registered
        with self._lock:
            members = set(self._replicas) | set(self._prefill)
            orphans = [rr for rr in self._inflight.values()
                       if rr.stage in ("prefill", "decode", "migrate")
                       and rr.replica is not None
                       and rr.replica not in members
                       and not rr.future.done()]
        orphan_info = [{"rid": rr.rid, "trace_id": rr.trace.trace_id,
                        "was_on": rr.replica} for rr in orphans]
        for rr in orphans:
            self._requeue(rr, exclude={rr.replica})
        if orphans:
            # a requeue with NO death this tick (the dispatch-vs-death
            # TOCTOU): still a failover event worth a postmortem
            _flight.dump("failover_requeue", requeued=orphan_info)
        self.registry._publish()

    def _requeue(self, rr, exclude):
        rr.requeues += 1
        rr.internal = None
        rr.payload = None        # streamed KV lived in the dead pool
        with self._lock:
            self.stats["requeues"] += 1
        _REQUEUES.inc()
        _flight.record_event("failover_requeue", rid=rr.rid,
                             trace_id=rr.trace.trace_id,
                             exclude=sorted(exclude),
                             attempt=rr.requeues)
        self._dispatch(rr, exclude=exclude)

    def _handle_death(self, name, rep):
        """Remove a dead member and requeue everything it was serving.
        The replay path IS the ordinary dispatch path: prompts re-route
        (minus the dead replica) through prefix-cache/KV machinery, and
        greedy decode reproduces the identical tokens."""
        with self._lock:
            self._replicas.pop(name, None)
            self._prefill.pop(name, None)
            self._affinity.pop(name, None)  # its cached KV died with it
            # recovery scan / stop() track it; the stamp is the bar a
            # future tick must clear to prove the wedge ended
            self._expelled[name] = (rep, time.monotonic())
            victims = [rr for rr in self._inflight.values()
                       if rr.replica == name and not rr.future.done()]
            self.stats["replicas_lost"] += 1
        self.registry.deregister(name)
        rep._drop_gauges()   # a dead member must not export frozen load
        for rr in victims:
            self._requeue(rr, exclude={name})
        # postmortem: the dead member, everything it was serving (with
        # trace ids — the merged timeline's keys), and the ring that
        # holds the last seconds of spans/phases/journal leading in
        _flight.dump(
            "replica_death", replica=name, role=rep.role,
            last_tick_age_s=round(time.monotonic() - rep.last_tick, 3),
            requeued=[{"rid": rr.rid, "trace_id": rr.trace.trace_id,
                       "stage": rr.stage, "requeues": rr.requeues}
                      for rr in victims],
            stats=dict(self.stats))

    def _autoscale_tick(self):
        pol = self.policy
        now = time.monotonic()
        alive = self._alive_replicas()
        # parked requests (a no-replica window) re-dispatch as soon as
        # capacity exists
        if alive:
            with self._lock:
                parked = [rr for rr in self._inflight.values()
                          if rr.stage == "parked"]
            for rr in parked:
                self._dispatch(rr)
        if self._factory is None:
            return
        if now - self._last_scale < pol.cooldown_s:
            return
        with self._lock:
            waiting = sum(rr.stage == "parked"
                          for rr in self._inflight.values())
        depth = sum(r.queue_depth() for r in alive) + waiting
        if len(alive) < pol.min_replicas:
            self._scale_up()
            return
        queue_hot = bool(alive) and depth / len(alive) >= pol.queue_high
        # memory-bound growth signal: the KV tier shedding pages is
        # pressure the queue never shows (lookups still succeed — they
        # just recompute cold prefixes, so TTFT regresses silently).
        # Only scraped when the queue is NOT already hot: one signal
        # firing is enough, and the scrape costs a metrics() call per
        # replica.
        spill_hot = False
        if not queue_hot and alive:
            sp = self._fleet_spill_pressure(alive)
            spill_hot = sp is not None and sp >= pol.spill_high
        if (queue_hot or spill_hot) and len(alive) < pol.max_replicas:
            # sustained pressure only: one hot tick must not double the
            # fleet. Queue and spill pressure SHARE the tick counter —
            # both are "the fleet is too small", and alternating
            # signals should not reset each other's evidence.
            with self._lock:
                self._pressure_ticks += 1
                fire = self._pressure_ticks >= 2
                if fire:
                    self._pressure_ticks = 0
            if fire:
                self._scale_up()
                if spill_hot and not queue_hot:
                    with self._lock:
                        self.stats["spill_scale_ups"] += 1
                    _SPILL_SCALEUPS.inc()
            return
        self._pressure_ticks = 0
        # an over-pressure tier also vetoes retirement: killing a
        # replica while the fleet sheds pages trades the idle slot for
        # MORE cold recompute
        if (depth <= pol.queue_low and len(alive) > pol.min_replicas
                and not spill_hot):
            idle = [r for r in alive if r.load() == (0, 0.0)
                    and not self._has_inflight(r.name)]
            if idle:
                self._scale_down(idle[-1])

    def _has_inflight(self, name):
        with self._lock:
            return any(rr.replica == name
                       for rr in self._inflight.values())

    @staticmethod
    def _tier_block(tiers):
        """Fold per-replica kv_tier snapshots into ONE fleet block
        with hit/spill-pressure rates. Shared by `metrics()` (the
        scrape view) and `_autoscale_tick` (the growth signal) so the
        number an operator reads is the number the autoscaler acts
        on. `tiers` yields kv_tier dicts (None/empty skipped)."""
        tier_totals, tier_n = {}, 0
        for t in tiers:
            if not t:
                continue
            tier_n += 1
            for k, v in t.items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    tier_totals[k] = tier_totals.get(k, 0) + v
        if not tier_n:
            return None
        g = tier_totals.get
        lookups = g("ram_hits", 0) + g("disk_hits", 0) + g("misses", 0)
        attempts = (g("spills", 0) + g("spill_failed", 0)
                    + g("spill_rejected", 0))
        dropped = (g("spill_rejected", 0) + g("ram_dropped", 0)
                   + g("disk_dropped", 0))
        kv_tier = dict(tier_totals)
        kv_tier.update({
            "replicas_with_tier": tier_n,
            # spilled-prefix lookups served below HBM / all lookups
            "hit_rate": ((g("ram_hits", 0) + g("disk_hits", 0))
                         / lookups) if lookups else None,
            # fraction of spill attempts the tier had to reject or
            # age out — rising pressure means the fleet's cold
            # capacity is saturating (scale out, or grow the tier)
            "spill_pressure": (dropped / (attempts + dropped)
                               if attempts + dropped else None),
        })
        return kv_tier

    def _fleet_spill_pressure(self, alive):
        """Fleet-wide KV spill_pressure from the alive replicas'
        engine views, or None when no replica runs a tier (tierless
        fleets autoscale on queue depth alone). Per-replica scrape
        failures are skipped — a dying member must not stall the
        autoscale decision for the rest."""
        tiers = []
        for r in alive:
            try:
                tiers.append(r.engine.metrics().get("kv_tier"))
            except Exception:   # ptlint: disable=PTL804 (scrape failure of one replica; the failover scan owns its death)
                pass
        block = self._tier_block(tiers)
        return block["spill_pressure"] if block else None

    # ---- overload tick (fleet_serving.overload) ----

    def _apply_brownout(self, level, caps):
        """BrownoutController apply_fn: push the level's caps to every
        member engine (serve AND prefill tier — the prefill engines
        honour shed_priority/deadline the same way)."""
        with self._lock:
            reps = (list(self._replicas.values())
                    + list(self._prefill.values()))
            self.stats["brownout_level"] = level
        for r in reps:
            try:
                r.engine.apply_brownout(caps)
            except Exception as e:
                self._note_monitor_error(e)

    def _overload_tick(self):
        """One monitor pass of the overload control plane: feed the
        admission estimator (fleet service rate), feed the brownout
        controller (pressure = queue depth per alive replica), expire
        deadlines the engines cannot see (parked / between-stages
        requests — plus a grace-lagged sweep behind a wedged engine),
        and hedge requests stuck behind a replica that stopped ticking
        (BEFORE failover's heartbeat timeout would fire)."""
        pol = self.overload
        now_m = time.monotonic()
        alive = self._alive_replicas()
        with self._lock:
            pre_alive = [p for p in self._prefill.values() if p.alive]
        # service-rate sample: cumulative tokens_in across the fleet —
        # the estimator keeps the PEAK inter-tick rate and discards
        # negative deltas (a member died/re-warmed out of the sum)
        try:
            tokens = sum(int(r.engine.stats.get("tokens_in", 0))
                         for r in alive + pre_alive)
            self._estimator.note_progress(tokens, now_m)
        except Exception:
            # a malformed stats dict skips ONE rate sample — count it
            # (a persistently failing sample starves the estimator)
            _MONITOR_ERRORS.inc()
        # brownout pressure
        if alive:
            with self._lock:
                parked = sum(rr.stage == "parked"
                             for rr in self._inflight.values())
            depth = sum(r.queue_depth() for r in alive) + parked
            self._brownout_ctl.note_pressure(depth / len(alive), now_m)
        # deadline sweep: the engines expire their own requests, but a
        # PARKED request has no engine, and a request on a WEDGED
        # engine never reaches the expiry scan — sweep those here
        # (grace-lagged for dispatched stages so a healthy engine's
        # own cancel, with its fuller timeline, wins the race)
        now_p = time.perf_counter()
        with self._lock:
            expired = [rr for rr in self._inflight.values()
                       if rr.deadline_t is not None and not rr.resolved
                       and now_p > rr.deadline_t
                       + (0.0 if rr.stage == "parked" else 0.25)]
        for rr in expired:
            self.cancel(rr.rid, reason="deadline")
        # hedged re-dispatch
        if pol.hedge_after_s is None:
            return
        stale_s = (pol.hedge_stale_s if pol.hedge_stale_s is not None
                   else 0.25 * self.policy.heartbeat_timeout_s)
        with self._lock:
            cands = [rr for rr in self._inflight.values()
                     if rr.stage in ("prefill", "decode")
                     and not rr.resolved and rr.hedges == 0
                     and rr.internal is not None
                     and not rr.internal.done()
                     and now_p - rr.t_submit >= pol.hedge_after_s]
            reps = dict(self._replicas)
            reps.update(self._prefill)
        for rr in cands:
            rep = reps.get(rr.replica)
            if rep is None or not rep.running:
                continue    # dead member: failover owns the requeue
            if now_m - rep.last_tick < stale_s:
                continue    # still making progress: not wedged
            if not self._alive_replicas(exclude={rr.replica}):
                continue    # nowhere to hedge to
            rr.hedges += 1
            with self._lock:
                self.stats["hedges"] += 1
            note_hedge()
            _flight.record_event(
                "request_hedged", rid=rr.rid,
                trace_id=rr.trace.trace_id, was_on=rr.replica,
                tick_age_s=round(now_m - rep.last_tick, 3))
            rr.payload = None   # a stale stage hand-off is not reusable
            self._dispatch(rr, exclude={rr.replica})

    def _scale_up(self):
        name = f"replica{next(_scale_names)}"
        rep = self._factory(name)
        self._adopt(rep)
        with self._lock:
            self.stats["scale_ups"] += 1
        self._last_scale = time.monotonic()
        self.registry._publish()
        return rep

    def _scale_down(self, rep):
        with self._lock:
            self._replicas.pop(rep.name, None)
            self._affinity.pop(rep.name, None)
            self.stats["scale_downs"] += 1
        rep.stop()   # graceful: queue is empty by the idle check
        self._last_scale = time.monotonic()
        self.registry._publish()

    # ---- observability ----

    def num_replicas(self):
        with self._lock:
            return len(self._replicas)

    def ttft_quantile(self, q):
        """Router-ingress TTFT percentile (the histogram replaces the
        old hand-kept sample list — satellite: percentiles come from
        the metrics substrate, not per-caller np.percentile)."""
        if self._ttft_hist.count == 0:
            return None
        return self._ttft_hist.quantile(q)

    def metrics(self):
        """ONE fleet-wide snapshot (scrape-safe): router policy state,
        per-replica engine views keyed by replica name (the labels the
        per-process islands lacked), the fleet TTFT distribution, the
        process-wide TTFT phase decomposition, and the last requests'
        merged timelines. `start_metrics_http` serves this under
        /metrics.json "extra"; the Prometheus text side carries the
        same per-replica identity via pt_replica_*{replica} series."""
        with self._lock:
            reqs = self.stats["requests"]
            hits = self.stats["affinity_hits"]
            snap = dict(self.stats)
            inflight = len(self._inflight)
            reps = list(self._replicas.values()) + list(
                self._prefill.values())
        replicas = {}
        recent = []
        for r in reps:
            info = {"role": r.role, "alive": r.alive,
                    "queue_depth": r.queue_depth(),
                    "mean_slot_occupancy": r.engine.mean_occupancy}
            try:
                eng = r.engine.metrics()
                recent += eng.pop("recent_requests", [])
                info["engine"] = eng
            except Exception as e:   # a dying member must not kill the
                info["engine_error"] = repr(e)   # whole fleet scrape
            replicas[r.name] = info
        # one fleet-wide timeline list: requests interleave across
        # replicas; order by their first stamp. A disaggregated request
        # appears on BOTH tiers (the prefill engine notes it at export,
        # the decode engine at first token) — same trace, snapshotted
        # at two moments — keep the fuller one. NOTE the per-engine
        # deques are bounded (64 each) — under sustained traffic this
        # is the TAIL, not history.
        by_trace = {}
        for tl in recent:
            cur = by_trace.get(tl["trace_id"])
            if cur is None or len(tl.get("phases", ())) >= len(
                    cur.get("phases", ())):
                by_trace[tl["trace_id"]] = tl
        recent = sorted(by_trace.values(),
                        key=lambda tl: tl["phases"][0]["t"]
                        if tl.get("phases") else 0.0)
        # tier-aware autoscale signals (ROADMAP item 2 follow-on): fold
        # the per-replica kv_tier snapshots (the pt_kv_tier_* family,
        # fleet_serving/kv_tier.py) into ONE fleet block with hit and
        # spill-pressure RATES, so the autoscale monitor sees memory
        # pressure building without scraping every engine view
        # (`_tier_block` — the same fold `_autoscale_tick` reads)
        kv_tier = self._tier_block(
            (info.get("engine") or {}).get("kv_tier")
            for info in replicas.values())
        snap.update({
            "inflight": inflight,
            "kv_tier": kv_tier,
            "affinity_hit_rate": hits / reqs if reqs else None,
            "ttft_p50_s": self.ttft_quantile(0.5),
            "ttft_p95_s": self.ttft_quantile(0.95),
            "ttft_p99_s": self.ttft_quantile(0.99),
            "request_phase_seconds": _reqtrace.phase_summary(),
            "recent_requests": recent[-128:],
            "replica_ages": self.registry.ages(),
            "replicas": replicas,
            "overload": {
                "breaker": self._breaker.snapshot(),
                "brownout": self._brownout_ctl.snapshot(),
                "estimator": self._estimator.snapshot(),
            },
        })
        return snap

    def start_metrics_http(self, port=0, host="127.0.0.1"):
        """Fleet-wide pull endpoint: GET /metrics is the process
        registry (per-replica pt_replica_* series included) in
        Prometheus text, /metrics.json adds this router's `metrics()`
        under "extra" — ONE scrape for the whole in-process fleet
        instead of per-replica islands. Stopped with the router."""
        if self._http is None:
            from ...observability import start_http_server

            self._http = start_http_server(port=port, host=host,
                                           extra_json=self.metrics)
        return self._http


_scale_names = itertools.count(1000)   # factory-built replica names
