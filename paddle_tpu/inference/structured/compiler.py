"""Grammar compiler — regex → token-level DFA for constrained decoding.

The structured-decoding subsystem (docs/SERVING.md "Structured
decoding") needs, per grammar, two dense host tables it can thread
into the compiled decode executables as plain arrays:

* ``trans``  int32 ``[n_states, vocab]`` — grammar-LOCAL next state for
  emitting token ``t`` in state ``q``, ``-1`` where the token is
  disallowed;
* ``accept`` bool ``[n_states]`` — states where the output so far is a
  complete match (the ONLY states where the request's eos token is
  unmasked).

The pipeline is entirely host-side and dependency-free: a restricted
regex (literals, escapes, ``.``, ``[...]`` classes, groups,
alternation, ``* + ? {m,n}`` — a subset that python's ``re`` also
accepts, so tests can cross-check validity) is parsed to an AST,
compiled to a Thompson NFA, determinized by subset construction over
the CHARACTER alphabet the tokenizer actually uses, and finally closed
over the token vocabulary: token ``t`` is allowed in state ``q`` iff
running its string through the char DFA from ``q`` never dies, and the
token-level transition is the char path's end state. Multi-character
tokens therefore constrain exactly like their character expansion —
the mask is per TOKEN, the semantics per CHARACTER.

Budget discipline: a grammar whose DFA exceeds ``max_states`` raises
``GrammarError`` DURING construction (the subset walk aborts early),
never an OOM after minutes — the loud-reject contract the engine's
grammar arena relies on. Tables are tiny (states × vocab int32) and
cached by content hash upstream, so a hot schema compiles once per
replica.
"""
import hashlib

import numpy as np

from ...observability import metrics as _obs

__all__ = ["CompiledGrammar", "GrammarError", "compile_regex"]

# structured-decoding telemetry (docs/OBSERVABILITY.md). Counters are
# process-global, same contract as the pt_spec_* family.
_STRUCT_REQS = _obs.counter(
    "pt_structured_requests_total",
    "requests admitted with a grammar/json_schema constraint attached")
_STRUCT_COMPILES = _obs.counter(
    "pt_structured_compiles_total",
    "grammar compilations (regex -> token DFA) actually performed — "
    "cache hits don't count")
_STRUCT_CACHE_HITS = _obs.counter(
    "pt_structured_cache_hits",
    "compiled-grammar cache hits (a hot schema compiles once per "
    "replica; every later request reuses the table)")
_STRUCT_REJECTS = _obs.counter(
    "pt_structured_rejects_total",
    "grammars rejected loudly (DFA over the state budget, grammar "
    "arena full, unsatisfiable pattern)")
_STRUCT_STATES = _obs.gauge(
    "pt_structured_states",
    "grammar-arena DFA states currently resident (row 0 is the "
    "mask-identity row unconstrained requests ride)")


class GrammarError(ValueError):
    """A constraint the engine refuses loudly at submit/compile time:
    unsupported syntax, a DFA over the state budget, an unsatisfiable
    pattern, or a full grammar arena."""


# ---- regex AST ----
# nodes: ("chars", frozenset) | ("cat", [n..]) | ("alt", [n..]) |
#        ("star", n) | ("plus", n) | ("opt", n) | ("rep", n, lo, hi)

_SPECIALS = set("\\.[](){}*+?|^$")
_ESC_CLASSES = {
    "d": frozenset("0123456789"),
    "w": frozenset("abcdefghijklmnopqrstuvwxyz"
                   "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"),
    "s": frozenset(" \t\n\r\f\v"),
}
_ESC_LITERALS = {"n": "\n", "t": "\t", "r": "\r", "f": "\f", "v": "\v"}


class _Parser:
    """Recursive-descent parser for the supported regex subset. The
    alphabet is the TOKENIZER's character set: classes are materialized
    against it, so ``.`` and negated classes stay finite."""

    def __init__(self, pattern, alphabet):
        self.p = pattern
        self.i = 0
        self.alphabet = alphabet

    def error(self, msg):
        raise GrammarError(
            f"grammar=: {msg} at position {self.i} in {self.p!r}")

    def peek(self):
        return self.p[self.i] if self.i < len(self.p) else None

    def parse(self):
        node = self._alt()
        if self.i != len(self.p):
            self.error(f"unexpected {self.p[self.i]!r}")
        return node

    def _alt(self):
        branches = [self._cat()]
        while self.peek() == "|":
            self.i += 1
            branches.append(self._cat())
        return branches[0] if len(branches) == 1 else ("alt", branches)

    def _cat(self):
        parts = []
        while self.peek() not in (None, "|", ")"):
            parts.append(self._rep())
        if not parts:
            return ("cat", [])      # empty branch: matches ""
        return parts[0] if len(parts) == 1 else ("cat", parts)

    def _rep(self):
        node = self._atom()
        ch = self.peek()
        if ch == "*":
            self.i += 1
            return ("star", node)
        if ch == "+":
            self.i += 1
            return ("plus", node)
        if ch == "?":
            self.i += 1
            return ("opt", node)
        if ch == "{":
            return self._bounds(node)
        return node

    def _bounds(self, node):
        j = self.p.find("}", self.i)
        if j < 0:
            self.error("unterminated {m,n} quantifier")
        body = self.p[self.i + 1:j]
        self.i = j + 1
        parts = body.split(",")
        try:
            lo = int(parts[0])
            if len(parts) == 1:
                hi = lo
            elif parts[1] == "":
                hi = None           # {m,} — unbounded tail
            else:
                hi = int(parts[1])
        except ValueError:
            self.error(f"malformed quantifier {{{body}}}")
        if lo < 0 or (hi is not None and hi < lo):
            self.error(f"malformed quantifier {{{body}}}")
        return ("rep", node, lo, hi)

    def _atom(self):
        ch = self.peek()
        if ch is None:
            self.error("dangling quantifier or empty atom")
        if ch == "(":
            self.i += 1
            if self.p[self.i:self.i + 2] == "?:":
                self.i += 2         # non-capturing groups: same thing
            node = self._alt()
            if self.peek() != ")":
                self.error("unterminated group")
            self.i += 1
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            self.i += 1
            return ("chars", frozenset(self.alphabet) - {"\n"})
        if ch == "\\":
            return self._escape()
        if ch in "*+?{":
            self.error(f"quantifier {ch!r} with nothing to repeat")
        if ch in ")]":
            self.error(f"unmatched {ch!r}")
        if ch in "^$":
            self.error(f"anchors are implicit (whole-output match); "
                       f"{ch!r} unsupported")
        self.i += 1
        return ("chars", frozenset((ch,)))

    def _escape(self):
        self.i += 1
        ch = self.peek()
        if ch is None:
            self.error("dangling backslash")
        self.i += 1
        if ch in _ESC_CLASSES:
            return ("chars", _ESC_CLASSES[ch] & self.alphabet)
        if ch in ("D", "W", "S"):
            return ("chars",
                    self.alphabet - _ESC_CLASSES[ch.lower()])
        if ch in _ESC_LITERALS:
            return ("chars", frozenset((_ESC_LITERALS[ch],)))
        return ("chars", frozenset((ch,)))

    def _char_class(self):
        self.i += 1                  # past '['
        negate = self.peek() == "^"
        if negate:
            self.i += 1
        chars = set()
        first = True
        while True:
            ch = self.peek()
            if ch is None:
                self.error("unterminated character class")
            if ch == "]" and not first:
                self.i += 1
                break
            first = False
            if ch == "\\":
                node = self._escape()
                chars |= set(node[1])
                continue
            self.i += 1
            if (self.peek() == "-" and self.i + 1 < len(self.p)
                    and self.p[self.i + 1] != "]"):
                self.i += 1
                hi = self.p[self.i]
                self.i += 1
                for o in range(ord(ch), ord(hi) + 1):
                    chars.add(chr(o))
            else:
                chars.add(ch)
        if negate:
            return ("chars", self.alphabet - chars)
        return ("chars", frozenset(chars) & self.alphabet
                if chars & self.alphabet or not chars
                else frozenset(chars) & self.alphabet)


# ---- Thompson NFA ----

class _NFA:
    """States are dicts {"eps": [ids], "edges": [(frozenset, id)]};
    fragments carry one start and one end id (epsilon-linked), so
    {m,n} expansion can recompile the same AST node repeatedly."""

    def __init__(self):
        self.states = []

    def new(self):
        self.states.append({"eps": [], "edges": []})
        return len(self.states) - 1

    def build(self, node):
        kind = node[0]
        if kind == "chars":
            s, e = self.new(), self.new()
            if node[1]:              # empty class: no edge = dead atom
                self.states[s]["edges"].append((node[1], e))
            return s, e
        if kind == "cat":
            if not node[1]:
                s = self.new()
                return s, s
            s, e = self.build(node[1][0])
            for sub in node[1][1:]:
                s2, e2 = self.build(sub)
                self.states[e]["eps"].append(s2)
                e = e2
            return s, e
        if kind == "alt":
            s, e = self.new(), self.new()
            for sub in node[1]:
                s2, e2 = self.build(sub)
                self.states[s]["eps"].append(s2)
                self.states[e2]["eps"].append(e)
            return s, e
        if kind == "star":
            s, e = self.new(), self.new()
            s2, e2 = self.build(node[1])
            self.states[s]["eps"] += [s2, e]
            self.states[e2]["eps"] += [s2, e]
            return s, e
        if kind == "plus":
            s2, e2 = self.build(node[1])
            e = self.new()
            self.states[e2]["eps"] += [s2, e]
            return s2, e
        if kind == "opt":
            s, e = self.new(), self.new()
            s2, e2 = self.build(node[1])
            self.states[s]["eps"] += [s2, e]
            self.states[e2]["eps"].append(e)
            return s, e
        if kind == "rep":
            _, sub, lo, hi = node
            parts = [sub] * lo
            if hi is None:
                parts.append(("star", sub))
            else:
                parts += [("opt", sub)] * (hi - lo)
            return self.build(("cat", parts))
        raise GrammarError(f"grammar=: internal: unknown node {kind!r}")


def _eps_closure(states, seed):
    out = set(seed)
    stack = list(seed)
    while stack:
        for t in states[stack.pop()]["eps"]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


def _char_dfa(pattern, alphabet, max_states):
    """Subset construction → (trans {state: {char: next}}, accept set).
    Aborts with GrammarError the moment the DFA exceeds max_states —
    the budget check runs DURING the walk, not after."""
    ast = _Parser(pattern, frozenset(alphabet)).parse()
    nfa = _NFA()
    start, end = nfa.build(ast)
    st = nfa.states
    d0 = _eps_closure(st, {start})
    index = {d0: 0}
    queue = [d0]
    trans = {0: {}}
    accept = set()
    if end in d0:
        accept.add(0)
    while queue:
        cur = queue.pop(0)
        ci = index[cur]
        for ch in alphabet:
            nxt = set()
            for sid in cur:
                for cs, t in st[sid]["edges"]:
                    if ch in cs:
                        nxt.add(t)
            if not nxt:
                continue
            closed = _eps_closure(st, nxt)
            ni = index.get(closed)
            if ni is None:
                if len(index) >= max_states:
                    _STRUCT_REJECTS.inc()
                    raise GrammarError(
                        f"grammar=: DFA for {pattern!r} exceeds the "
                        f"state budget ({max_states}); raise "
                        "LLMEngineConfig(grammar_states=...) or "
                        "simplify the grammar")
                ni = index[closed] = len(index)
                trans[ni] = {}
                if end in closed:
                    accept.add(ni)
                queue.append(closed)
            trans[ci][ch] = ni
    return trans, accept


class CompiledGrammar:
    """One grammar's token-level DFA (module docstring). Immutable
    after construction; shared freely across requests and threads."""

    def __init__(self, pattern, trans, accept, eos_id, vocab_fp):
        self.pattern = pattern
        self.trans = trans                 # int32 [n_states, vocab]
        self.accept = accept               # bool [n_states]
        self.eos_id = eos_id
        self.n_states = int(trans.shape[0])
        self.vocab = int(trans.shape[1])
        self._allowed = trans >= 0         # bool [n_states, vocab]
        h = hashlib.sha1()
        h.update(pattern.encode("utf-8"))
        h.update(str(eos_id).encode())
        h.update(vocab_fp)
        self.hash = h.hexdigest()

    def advance(self, state, token):
        """Host-side replay of ONE emitted token — the engine keeps
        each constrained request's DFA state as a pure function of its
        generated tokens, so preemption replay is correct for free.
        A disallowed token (impossible under in-executable masking;
        defensive) leaves the state unchanged."""
        ns = int(self.trans[int(state), int(token)])
        return ns if ns >= 0 else int(state)

    def replay(self, tokens, state=0):
        """DFA state after emitting `tokens` from `state` — the
        reference the preemption test pins the live state against."""
        for t in tokens:
            if self.eos_id is not None and int(t) == self.eos_id:
                break
            state = self.advance(state, t)
        return state

    def allowed_np(self, state):
        """bool [vocab] mask for one state — the HOST tick's masking
        row (the single-tick path masks logits before argmax/sampling
        on the host; the fused/verify executables use the arena
        bitsets instead)."""
        return self._allowed[int(state)]

    def is_complete(self, state):
        return bool(self.accept[int(state)])


def compile_regex(pattern, token_strs, eos_id=None, max_states=128):
    """Compile one regex into a token-level `CompiledGrammar` over the
    engine's vocabulary. ``token_strs[t]`` is token ``t``'s surface
    string; empty strings (specials, padding ids) are disallowed in
    every state. ``eos_id`` (required by the engine for constrained
    requests) is allowed exactly in accepting states, as a self-loop —
    generation ends there anyway, the self-loop just keeps `advance`
    total. Raises `GrammarError` over ``max_states``."""
    if not isinstance(pattern, str) or not pattern:
        raise GrammarError(
            "grammar=: expected a non-empty regex string, got "
            f"{pattern!r}")
    vocab = len(token_strs)
    alphabet = sorted({ch for s in token_strs for ch in s})
    ctrans, caccept = _char_dfa(pattern, alphabet, int(max_states))
    n = len(ctrans)
    trans = np.full((n, vocab), -1, np.int32)
    for t, s in enumerate(token_strs):
        if not s or (eos_id is not None and t == eos_id):
            continue
        # run the token's character path from every state; surviving
        # paths define the token-level transition
        for q in range(n):
            cur = q
            for ch in s:
                cur = ctrans[cur].get(ch)
                if cur is None:
                    break
            else:
                trans[q, t] = cur
    accept = np.zeros((n,), bool)
    for q in caccept:
        accept[q] = True
    if eos_id is not None:
        if not 0 <= int(eos_id) < vocab:
            raise GrammarError(
                f"grammar=: eos_token_id {eos_id} outside the "
                f"vocabulary [0, {vocab})")
        for q in range(n):
            if accept[q]:
                trans[q, int(eos_id)] = q
    if not (trans[0] >= 0).any():
        _STRUCT_REJECTS.inc()
        raise GrammarError(
            f"grammar=: {pattern!r} is unsatisfiable over this "
            "vocabulary (no token is allowed in the start state)")
    vocab_fp = hashlib.sha1(
        "\x00".join(token_strs).encode("utf-8")).digest()
    _STRUCT_COMPILES.inc()
    return CompiledGrammar(pattern, trans, accept,
                           None if eos_id is None else int(eos_id),
                           vocab_fp)
