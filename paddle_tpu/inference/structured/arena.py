"""Grammar arena — fixed-shape device tables for in-scan masking.

The zero-recompile contract (docs/SERVING.md "Fused decode") means the
fused/spec executables can never see a new array SHAPE. So the engine
does not thread per-grammar tables into the scan; it threads ONE
engine-lifetime arena:

* ``trans`` int32 ``[G, vocab]`` — arena-ABSOLUTE next state for token
  ``t`` in arena state ``g``;
* ``mask``  uint32 ``[G, ceil(vocab/32)]`` — per-state allowed-token
  bitsets, expanded to a boolean row inside the executable.

Row 0 is the MASK-IDENTITY row: every token allowed, self-transition.
Unconstrained slots carry arena state 0 through the whole window, the
mask row is all-ones (a value-level no-op on the logits), and a
``lax.cond`` on ``any(gstate > 0)`` skips even that gather when no
constrained row is resident — unconstrained traffic pays nothing,
same discipline as the all-greedy fast path in ``sample_tokens``.

Compiled grammars load at base offsets ≥ 1 with their local next
states rebased to arena-absolute; disallowed transitions clamp to 0,
which is safe because masking (fused) / exact-match acceptance
(verify) guarantees a disallowed token's transition is never consumed.
``G`` is static for the engine's lifetime (`LLMEngineConfig(
grammar_states=...)`); a grammar that cannot fit even after compacting
away unreferenced entries raises ``GrammarError`` loudly. Device
copies are remade only when the host arena changed (value swap, same
shape/sharding — never a recompile).
"""
import threading

import numpy as np

from .compiler import GrammarError, _STRUCT_REJECTS, _STRUCT_STATES

__all__ = ["GrammarArena", "GrammarCache"]


class GrammarCache:  # ptlint: thread-shared
    """Hash-keyed ``(pattern, eos_id) -> CompiledGrammar`` compile
    cache plus its compile/hit/reject counters, lock-guarded:
    ``LLMServer.submit`` compiles grammars on the CALLER's thread
    (loud reject at submit) while ``add_request`` may compile on the
    engine thread. Split out of ``LLMEngine`` so the lock naming this
    one multi-writer contract does not drag the whole engine — whose
    stats are serve-loop-owned, single-writer — under the class-wide
    lock fence (ptlint PTL702)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}
        self.compiles = 0
        self.cache_hits = 0
        self.rejects = 0

    def lookup(self, key):
        """The cached grammar for ``key`` (counting the hit), or None."""
        with self._lock:
            hit = self._cache.get(key)
            if hit is not None:
                self.cache_hits += 1
            return hit

    def insert(self, key, grammar):
        """Publish a freshly-compiled grammar; first writer wins (a
        racing duplicate compile is wasted work, not corruption)."""
        with self._lock:
            self.compiles += 1
            return self._cache.setdefault(key, grammar)

    def reject(self):
        with self._lock:
            self.rejects += 1

    def snapshot(self):
        with self._lock:
            return {"compiles": self.compiles,
                    "cache_hits": self.cache_hits,
                    "rejects": self.rejects}


class GrammarArena:
    def __init__(self, vocab, n_states):
        self.vocab = int(vocab)
        self.n_states = max(1, int(n_states))
        self.words = (self.vocab + 31) // 32
        self.trans = np.zeros((self.n_states, self.vocab), np.int32)
        self.mask = np.zeros((self.n_states, self.words), np.uint32)
        # identity row: all tokens allowed (surplus bits past vocab in
        # the last word are set too — they index nothing), stay in 0
        self.mask[0, :] = np.uint32(0xFFFFFFFF)
        self._next = 1
        self._loaded = {}            # hash -> (base, CompiledGrammar)
        self._dirty = True
        self._dev = None             # (trans_dev, mask_dev)

    @property
    def capacity(self):
        """States available to a single grammar (row 0 is reserved)."""
        return self.n_states - 1

    @property
    def states_used(self):
        return self._next

    def base_of(self, grammar):
        """Arena base offset of a loaded grammar (by object or hash)."""
        h = grammar if isinstance(grammar, str) else grammar.hash
        return self._loaded[h][0]

    def load(self, grammar, live=None):
        """Ensure `grammar` is resident; return its base offset. When
        the arena is full, compact away grammars outside `live` (an
        iterable of hashes still referenced by queued/running
        requests) and retry; still over budget → loud GrammarError."""
        ent = self._loaded.get(grammar.hash)
        if ent is not None:
            return ent[0]
        need = grammar.n_states
        if self._next + need > self.n_states:
            keep = set(live or ())
            self._compact(keep)
        if self._next + need > self.n_states:
            _STRUCT_REJECTS.inc()
            raise GrammarError(
                f"grammar=: arena full ({self._next}/{self.n_states} "
                f"states used, grammar needs {need}); raise "
                "LLMEngineConfig(grammar_states=...) or retire live "
                "constrained requests")
        base = self._next
        self._write(base, grammar)
        self._loaded[grammar.hash] = (base, grammar)
        self._next = base + need
        self._dirty = True
        _STRUCT_STATES.set(float(self._next))
        return base

    def _write(self, base, grammar):
        n = grammar.n_states
        t = grammar.trans.astype(np.int64)
        allowed = t >= 0
        # rebase local next states to arena-absolute; clamp disallowed
        # to 0 (never consumed — the mask/acceptance gate runs first)
        self.trans[base:base + n] = np.where(
            allowed, t + base, 0).astype(np.int32)
        words = np.zeros((n, self.words), np.uint32)
        q_idx, t_idx = np.nonzero(allowed)
        np.bitwise_or.at(
            words, (q_idx, t_idx // 32),
            (np.uint32(1) << (t_idx % 32).astype(np.uint32)))
        self.mask[base:base + n] = words

    def _compact(self, keep):
        """Rebuild the arena keeping only grammars in `keep` — the
        rebase invalidates dropped grammars' offsets, which is fine
        because nothing references them."""
        survivors = [g for h, (_, g) in sorted(self._loaded.items(),
                                               key=lambda kv: kv[1][0])
                     if h in keep]
        self.trans[1:] = 0
        self.mask[1:] = 0
        self._loaded = {}
        self._next = 1
        for g in survivors:
            base = self._next
            self._write(base, g)
            self._loaded[g.hash] = (base, g)
            self._next = base + g.n_states
        self._dirty = True
        _STRUCT_STATES.set(float(self._next))

    def device_tables(self):
        """The committed (trans, mask) device pair the executables
        take as plain arguments. Re-placed only when the host arena
        changed — a VALUE swap at fixed shape/dtype/sharding, so the
        one-executable contract holds across grammar churn."""
        if self._dirty or self._dev is None:
            import jax
            import jax.numpy as jnp
            from ...distributed import mesh as mesh_mod
            sharding = mesh_mod.named_sharding()
            self._dev = (
                jax.device_put(jnp.asarray(self.trans), sharding),
                jax.device_put(jnp.asarray(self.mask), sharding))
            self._dirty = False
        return self._dev
