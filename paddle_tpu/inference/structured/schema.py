"""JSON schema → regex lowering for constrained decoding.

A restricted-but-useful JSON Schema subset lowers to a single regex in
the dialect `compiler.compile_regex` accepts (which is also a python
``re`` subset, so tests can cross-check emitted output with
``re.fullmatch`` + ``json.loads``). The emitted grammar is CANONICAL
JSON: no whitespace, object properties in schema declaration order,
every declared property present. That trade keeps the DFA tiny (tens
of states for typical function-calling schemas) while still
guaranteeing the output parses and type-checks; callers needing
free-form key order should pass an explicit ``grammar=`` regex
instead.

Supported: ``object`` (properties, declaration order), ``string``
(optionally ``enum`` or ``pattern``), ``integer``, ``number``,
``boolean``, ``null``, bounded ``array`` (``minItems``/``maxItems``),
and top-level/nested ``enum`` of JSON scalars. Anything else raises
``GrammarError`` naming the unsupported construct — loud at submit
time, never inside the serve loop.
"""
import json

from .compiler import GrammarError

__all__ = ["schema_to_regex"]

_META = set("\\.[](){}*+?|^$")

# string contents when the schema gives no pattern/enum: printable
# ASCII minus '"' and '\' so no JSON escaping is ever needed
_STRING_BODY = r'[ !#-\[\]-~]*'

_INT = r"-?(0|[1-9][0-9]*)"
_NUMBER = _INT + r"(\.[0-9]+)?"


def _esc(s):
    return "".join("\\" + c if c in _META else c for c in s)


def _scalar_literal(v):
    """One JSON scalar as an exact-match regex fragment."""
    if isinstance(v, bool) or v is None or isinstance(v, (int, float)):
        return _esc(json.dumps(v))
    if isinstance(v, str):
        return _esc(json.dumps(v))
    raise GrammarError(
        f"json_schema: enum values must be JSON scalars, got {v!r}")


def _lower(schema, path):
    if not isinstance(schema, dict):
        raise GrammarError(
            f"json_schema: expected an object at {path}, got "
            f"{type(schema).__name__}")
    if "enum" in schema:
        vals = schema["enum"]
        if not isinstance(vals, (list, tuple)) or not vals:
            raise GrammarError(
                f"json_schema: enum at {path} must be a non-empty list")
        return "(" + "|".join(_scalar_literal(v) for v in vals) + ")"
    typ = schema.get("type")
    if typ is None and "properties" in schema:
        typ = "object"
    if typ == "object":
        props = schema.get("properties", {})
        if not isinstance(props, dict) or not props:
            raise GrammarError(
                f"json_schema: object at {path} needs non-empty "
                "'properties' (free-form objects are unsupported)")
        fields = ",".join(
            _esc(json.dumps(str(k))) + ":" + _lower(v, f"{path}.{k}")
            for k, v in props.items())
        return r"\{" + fields + r"\}"
    if typ == "string":
        pat = schema.get("pattern")
        if pat is not None:
            if not isinstance(pat, str) or not pat:
                raise GrammarError(
                    f"json_schema: pattern at {path} must be a "
                    "non-empty string")
            return '"(' + pat + ')"'
        return '"' + _STRING_BODY + '"'
    if typ == "integer":
        return "(" + _INT + ")"
    if typ == "number":
        return "(" + _NUMBER + ")"
    if typ == "boolean":
        return "(true|false)"
    if typ == "null":
        return "null"
    if typ == "array":
        items = schema.get("items")
        if items is None:
            raise GrammarError(
                f"json_schema: array at {path} needs 'items'")
        lo = int(schema.get("minItems", 0))
        hi = int(schema.get("maxItems", 8))
        if lo < 0 or hi < lo:
            raise GrammarError(
                f"json_schema: bad minItems/maxItems at {path}")
        if hi > 64:
            raise GrammarError(
                f"json_schema: maxItems at {path} capped at 64 "
                "(DFA budget); pass an explicit grammar= for more")
        item = _lower(items, f"{path}[]")
        if hi == 0:
            return r"\[\]"
        body = item + "(," + item + "){0,%d}" % (hi - 1)
        if lo == 0:
            return r"\[(" + body + r")?\]"
        if lo > 1:
            body = item + "(," + item + "){%d,%d}" % (lo - 1, hi - 1)
        return r"\[" + body + r"\]"
    raise GrammarError(
        f"json_schema: unsupported type {typ!r} at {path} (supported: "
        "object, string, integer, number, boolean, null, array, enum)")


def schema_to_regex(schema):
    """Lower one JSON schema (dict) to the canonical-JSON regex the
    grammar compiler consumes. Raises ``GrammarError`` for anything
    outside the supported subset, naming the offending path."""
    if not isinstance(schema, dict):
        raise GrammarError(
            "json_schema= must be a dict (a parsed JSON schema), got "
            f"{type(schema).__name__}")
    return _lower(schema, "$")
