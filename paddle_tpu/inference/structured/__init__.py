"""Structured decoding — grammar-constrained generation, n-gram
speculation, and the per-request constraint surface.

Three legs (docs/SERVING.md "Structured decoding"):

* `compiler` / `schema` — host-side grammar compilation: a regex (or a
  JSON schema lowered through `schema_to_regex`) becomes a token-level
  DFA (`CompiledGrammar`) whose tables the engine's `GrammarArena`
  threads into the compiled decode executables, so constrained rows
  mask logits INSIDE the fused/verify scans at zero recompiles.
* `arena` — the fixed-shape device-table arena (mask-identity row 0:
  unconstrained rows pay nothing).
* `ngram` — `NgramSpeculator`, draft-model-free prompt-lookup
  speculation through the existing ragged verify executable
  (`LLMEngineConfig(spec_mode="ngram")`).

`validate_constraints` is the shared submit-time gate every ingress
(`LLMServer.submit`, `LocalReplica.submit`, `FleetRouter.submit`,
`LLMEngine.add_request`) runs, so a malformed constraint kwarg raises
at submit() with the offending name instead of dying inside the serve
loop and aborting co-resident requests.

`NgramSpeculator` is NOT imported here: ngram pulls in the speculative
/ engine stack, which imports this package for validation — import it
from `paddle_tpu.inference.structured.ngram` (the engine does).
"""
from .arena import GrammarArena
from .compiler import CompiledGrammar, GrammarError, compile_regex
from .schema import schema_to_regex

__all__ = [
    "CompiledGrammar", "GrammarArena", "GrammarError", "SPEC_MODES",
    "compile_regex", "schema_to_regex", "validate_constraints",
]

SPEC_MODES = ("off", "draft", "ngram")


def validate_constraints(grammar=None, json_schema=None,
                         spec_mode=None):
    """Structural validation of the per-request constraint kwargs —
    loud, at submit() time, naming the offending kwarg. Engine-context
    checks (token_strs configured, spec_mode matching the engine's,
    grammar compilation itself) run on the engine's submit surface;
    this gate is what remote ingresses (the fleet router) can run
    without an engine in hand."""
    if grammar is not None and json_schema is not None:
        raise ValueError(
            "grammar=/json_schema=: pass ONE constraint per request, "
            "not both")
    if grammar is not None and not isinstance(
            grammar, (str, CompiledGrammar)):
        raise ValueError(
            "grammar= must be a regex string or a CompiledGrammar, "
            f"got {type(grammar).__name__}")
    if isinstance(grammar, str) and not grammar:
        raise ValueError("grammar= must be a non-empty regex string")
    if json_schema is not None and not isinstance(json_schema, dict):
        raise ValueError(
            "json_schema= must be a dict (a parsed JSON schema), got "
            f"{type(json_schema).__name__}")
    if spec_mode is not None and spec_mode not in SPEC_MODES:
        raise ValueError(
            f"spec_mode= must be one of {SPEC_MODES} or None, got "
            f"{spec_mode!r}")
