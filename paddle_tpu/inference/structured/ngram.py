"""Draft-model-free speculation: n-gram / prompt-lookup proposals.

`SpeculativeDecoder` (speculative.py) pays for its proposals with a
resident draft model — per-replica weight + KV memory the Gemma
serving paper (PAPERS.md) frames as THE fleet-scale cost. For the
workloads where speculation pays most (templated JSON, code repair,
retrieval-augmented answers that quote their context), the cheapest
draft is the request itself: when the last n tokens of the sequence
also occur earlier in prompt+generated text, the tokens that followed
that earlier occurrence are a strong guess for what follows now.

`NgramSpeculator` mines exactly that — longest-suffix match (n down
to 1) against the request's own token history, most recent occurrence
wins, the k tokens after the match are the proposal — and feeds it to
the SAME `_CompiledVerifyStep` ragged verify the draft path uses.
Selected via ``LLMEngineConfig(spec_mode="ngram")``; no second model,
no draft pool, no catch-up ticks: the window is [host proposal scan]
+ 1 verify dispatch. Slots with no match run verify-only (width 0 —
a plain decode row inside the same executable), so the engine never
falls off the one-executable path.

Losslessness is inherited wholesale: acceptance is exact-match against
`sample_tokens`' (seed, stream, position)-keyed pick, so output is
token-identical to the non-speculative engine for greedy AND sampled
rows regardless of proposal quality — bad proposals cost width, never
correctness. Grammar constraints compose the same way they do in the
draft path: the verify chains arena DFA states across each row's
proposal positions, and a proposal token the grammar masks simply
fails exact-match and truncates acceptance there.

Duck-typed to the `SpeculativeDecoder` surface the engine drives
(`try_window` / `window_headroom` / `release_pools` / `reset_pools` /
`pool_bytes` / `.k`), reporting 0 pool bytes — brownout L2 has
nothing to release and preemption owes no draft replay
(`draft_prefilled` is dead weight here).
"""
import time as _time

import numpy as np

from ...observability import metrics as _obs
from ...observability.tracing import trace_span as _trace_span

__all__ = ["NgramSpeculator"]

_NGRAM_WINDOWS = _obs.counter(
    "pt_ngram_spec_windows_total",
    "n-gram speculative windows dispatched (one verify executable "
    "call each)")
_NGRAM_PROPOSED = _obs.counter(
    "pt_ngram_spec_proposed_total",
    "prompt-lookup tokens proposed to the verify step (window widths "
    "summed; match-less slots propose 0 and run verify-only)")
_NGRAM_ACCEPTED = _obs.counter(
    "pt_ngram_spec_accepted_total",
    "accepted prompt-lookup tokens that entered the output")
_NGRAM_ACC_RATE = _obs.gauge(
    "pt_ngram_spec_acceptance_rate",
    "accepted / proposed for the n-gram proposer, process-cumulative "
    "(prompt-lookup-favorable workloads sit near 1.0; adversarial "
    "ones near 0 — and still lose nothing but the window width)")


class NgramSpeculator:
    mode = "ngram"

    def __init__(self, engine, spec_k, max_match=3, scan_window=512):
        from ..speculative import _CompiledVerifyStep

        self.engine = engine
        self.k = int(spec_k)
        if self.k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.k}")
        self.max_match = int(max_match)
        self.scan_window = int(scan_window)
        self._verify_fn = _CompiledVerifyStep(
            engine.model, self.k, engine.page_size)
        self._stats = engine.stats
        for key in ("ngram_windows", "ngram_proposed",
                    "ngram_accepted"):
            self._stats.setdefault(key, 0)

    # ---- SpeculativeDecoder duck-type surface ----

    def pool_bytes(self):
        return 0

    def window_headroom(self):
        """Same admission headroom contract as the draft decoder: one
        free page per live frontier slot so the next verify window's
        k-token reservation doesn't collapse to width 0."""
        return sum(
            1 for r in self.engine._slots
            if r is not None and r.n_prefilled == len(r.tokens) - 1)

    def reset_pools(self):
        pass                      # no draft pool to re-zero

    def release_pools(self):
        pass                      # brownout L2: nothing resident

    # ---- proposal mining ----

    def _propose(self, req):
        """Longest-suffix prompt lookup over the request's own token
        history: match the last n tokens (n = max_match..1) against an
        earlier occurrence (most recent wins, bounded to the trailing
        `scan_window` positions) and propose the ≤ k tokens that
        followed it. Empty list = no match = verify-only row."""
        toks = req.tokens
        n_max = min(self.max_match, len(toks) - 1)
        for n in range(n_max, 0, -1):
            tail = toks[-n:]
            hi = len(toks) - n - 1   # latest start with a continuation
            lo = max(0, hi - self.scan_window)
            for j in range(hi, lo - 1, -1):
                if toks[j:j + n] == tail:
                    cont = toks[j + n:j + n + self.k]
                    if cont:
                        return cont
        return []

    # ---- the speculative window ----

    def try_window(self, frontier):
        """One n-gram speculative window over the frontier rows, or
        None when even the frontier token's page cannot be covered —
        same contract, page reservation, and consumption accounting as
        `SpeculativeDecoder.try_window`, minus every draft-model leg
        (no catch-up, no propose dispatch, no device gather)."""
        from ..llm_engine import (
            _DISPATCHES, _FUSED_STEPS, _LIVE_SLOTS, _PAGE_FRAG,
            _PAGE_OCC, _QUEUE_DEPTH, _SLOT_OCC, _STEPS_TOTAL,
            _TOK_PER_DISPATCH, _TOKENS_TOTAL, _TTFT_SECONDS,
            PoolExhausted,
        )

        eng = self.engine
        ps = eng.page_size
        k = self.k
        S = eng.num_slots

        cap = eng._brownout.get("spec_k_cap")
        k_eff = k if cap is None else max(0, min(k, int(cap)))

        proposals = {}
        width = {}
        for slot, req in frontier:
            props = ([] if req.spec_off or not k_eff
                     else self._propose(req))
            w = min(len(props), k_eff, req.target - len(req.tokens))
            last = req.n_prefilled + w
            try:
                while last // ps >= len(req.pages):
                    page = eng._alloc_page()
                    eng._page_tables[slot, len(req.pages)] = page
                    req.pages.append(page)
            except PoolExhausted:
                covered = len(req.pages) * ps - 1 - req.n_prefilled
                if covered < 0:
                    return None   # frontier write itself has no page
                w = min(w, covered)
            width[slot] = w
            proposals[slot] = props[:w]

        tok0 = np.zeros((S,), np.int32)
        pos0 = np.zeros((S,), np.int32)
        drafts = np.zeros((S, k), np.int32)
        wid = np.zeros((S,), np.int32)
        rem = np.zeros((S,), np.int32)
        fin_v = np.ones((S,), bool)
        eos = np.full((S,), -1, np.int32)
        temps = np.zeros((S,), np.float32)
        tops = np.ones((S,), np.float32)
        streams = np.zeros((S,), np.int32)
        gen_before = {}
        for slot, req in frontier:
            tok0[slot] = req.tokens[-1]
            pos0[slot] = req.n_prefilled
            wid[slot] = width[slot]
            for j, t in enumerate(proposals[slot]):
                drafts[slot, j] = t
            rem[slot] = req.target - len(req.tokens)
            fin_v[slot] = False
            if req.eos is not None:
                eos[slot] = int(req.eos)
            temps[slot] = req.temperature
            tops[slot] = req.top_p
            streams[slot] = req.sample_stream
            gen_before[slot] = req.num_generated

        gst, gtrans, gmask = eng._grammar_args(frontier)

        t0 = _time.perf_counter()
        try:
            with _trace_span("llm_engine.ngram_window", k=k,
                             live=len(frontier)):
                emits, (eng._kv, eng._kv_scales, eng._key) = \
                    self._verify_fn(
                        tok0, pos0, drafts, wid, rem, fin_v, eos,
                        temps, tops, streams, gst, gtrans, gmask,
                        eng._page_tables,
                        (eng._kv, eng._kv_scales, eng._key))
                emits = np.asarray(emits)  # [k+1, S]: the host sync
        except Exception as e:
            eng.abort_all(e)
            raise
        eng.sched.note_boundary(_time.perf_counter() - t0)

        self._stats["steps"] += 1
        self._stats["ngram_windows"] += 1
        self._stats["occupancy_sum"] += len(frontier) / S
        _STEPS_TOTAL.inc()
        _FUSED_STEPS.inc()
        _DISPATCHES.inc()
        _NGRAM_WINDOWS.inc()

        finished = []
        now = _time.perf_counter()
        total = 0
        proposed = 0
        accepted = 0
        for slot, req in frontier:
            emitted, done, from_draft = 0, False, 0
            for j in range(k + 1):
                t = int(emits[j, slot])
                if t < 0:
                    break
                req.tokens.append(t)
                if req.grammar is not None:
                    req.gstate = req.grammar.advance(req.gstate, t)
                if j < k and t == int(drafts[slot, j]):
                    from_draft += 1
                emitted += 1
                if ((req.eos is not None and t == req.eos)
                        or len(req.tokens) >= req.target):
                    done = True
            req.n_prefilled += emitted
            total += emitted
            proposed += width[slot]
            accepted += from_draft
            self._stats["generated"] += emitted
            eng.sched.note_tokens(req.tenant, emitted)
            if gen_before[slot] == 0 and emitted > 0:
                ttft = now - req.t_submit
                req.t_first_token = now
                req.trace.stamp("first_token")
                eng._note_timeline(req)
                _TTFT_SECONDS.observe(ttft)
                eng.sched.note_first_token(req, ttft)
            if done:
                eng._finish(slot, req)
                finished.append(req)
        self._stats["tokens_in"] += total
        self._stats["ngram_proposed"] += proposed
        self._stats["ngram_accepted"] += accepted
        eng.sched.note_spec_window(proposed, accepted)
        _NGRAM_PROPOSED.inc(proposed)
        _NGRAM_ACCEPTED.inc(accepted)
        n_prop = _NGRAM_PROPOSED.value
        if n_prop:
            _NGRAM_ACC_RATE.set(_NGRAM_ACCEPTED.value / n_prop)
        _TOKENS_TOTAL.labels(phase="decode").inc(total)
        _TOK_PER_DISPATCH.set(total)
        _QUEUE_DEPTH.set(len(eng.waiting))
        live = sum(r is not None for r in eng._slots)
        _LIVE_SLOTS.set(live)
        _SLOT_OCC.set(live / S)
        _PAGE_OCC.set(eng.pool.num_live / (eng.pool.num_pages - 1))
        _PAGE_FRAG.set(eng.kv_fragmentation())
        return finished
