"""paddle_tpu.inference — deployment predictor API.

TPU-native re-design of the reference inference stack (reference:
python/paddle/inference/__init__.py exports; C++ AnalysisPredictor
paddle/fluid/inference/api/analysis_predictor.cc, Config
paddle_analysis_config.h, handle-based IO paddle_inference_api.h:53).

The reference loads a serialized program, runs IR passes (fusion, TRT
subgraphs), and executes on its own runtime. Here the serialized
artifact is a StableHLO export (paddle.jit.save) and "passes" are XLA's
compilation — `create_predictor(config)` deserializes, places weights on
the configured device, and compiles on first run. The handle-based
copy_from_cpu/run/copy_to_cpu surface is kept so reference deployment
code ports unchanged.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..jit import load as _jit_load

__all__ = [
    "Config", "Predictor", "create_predictor", "PredictorPool",
    "InferTensor", "DataType", "PlaceType", "PrecisionType",
    "get_version", "get_num_bytes_of_data_type",
    "convert_to_mixed_precision", "InferenceServer", "BatchingConfig",
    "LLMEngine", "LLMEngineConfig", "LLMServer", "PagePool",
    "fleet_serving", "RadixPrefixCache", "SLAPolicy", "SLAScheduler",
    "Priority", "SpeculativeDecoder", "FleetRouter", "AutoscalePolicy",
    "LocalReplica", "ReplicaRegistry", "KVPagePayload",
    "OverloadPolicy", "RequestShed", "RequestCancelled",
]

from .serving import BatchingConfig, InferenceServer  # noqa: E402,F401
from .llm_engine import (  # noqa: E402,F401
    LLMEngine, LLMEngineConfig, LLMServer, PagePool)
from .speculative import SpeculativeDecoder  # noqa: E402,F401
from . import fleet_serving  # noqa: E402,F401
from .fleet_serving import (  # noqa: E402,F401
    AutoscalePolicy, FleetRouter, KVPagePayload, LocalReplica,
    OverloadPolicy, Priority, RadixPrefixCache, ReplicaRegistry,
    RequestCancelled, RequestShed, SLAPolicy, SLAScheduler)


class DataType:
    FLOAT32 = "float32"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"
    INT32 = "int32"
    INT64 = "int64"
    UINT8 = "uint8"
    INT8 = "int8"
    BOOL = "bool"


class PlaceType:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"
    XPU = "xpu"


class PrecisionType:
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


def get_version():
    import paddle_tpu

    return getattr(paddle_tpu, "__version__", "0.0")


def get_num_bytes_of_data_type(dtype):
    return np.dtype(str(dtype)).itemsize


class Config:
    """reference paddle_analysis_config.h AnalysisConfig. Pass-pipeline
    knobs collapse into XLA; device/precision knobs are honored."""

    def __init__(self, prog_file=None, params_file=None):
        # paddle convention: Config("path/model") with side files; here
        # ONE prefix produces <prefix>.stablehlo + <prefix>.pdiparams
        self.model_path = prog_file
        self._device = "tpu"
        self._device_id = 0
        self._precision = PrecisionType.Float32
        self._profile = False
        self._compiler_options = {}

    # -- XLA compile hooks (the analysis-pass-pipeline analog:
    # reference analysis_predictor.cc registers IR passes per config;
    # here the per-predictor optimization surface is XLA compiler
    # option overrides applied at (re)compile) --
    def set_xla_compile_option(self, key, value):
        self._compiler_options[str(key)] = value
        return self

    def xla_compile_options(self):
        return dict(self._compiler_options)

    # -- device selection --
    def enable_use_gpu(self, memory_pool_init_size_mb=0, device_id=0):
        self._device, self._device_id = "tpu", device_id  # accelerator

    def disable_gpu(self):
        self._device = "cpu"

    def enable_xpu(self, *a, **k):
        self._device = "tpu"

    def set_cpu_math_library_num_threads(self, n):
        pass  # XLA owns host threading

    def use_gpu(self):
        return self._device != "cpu"

    def gpu_device_id(self):
        return self._device_id

    # -- precision / optimization --
    def enable_mixed_precision(self, precision=PrecisionType.Bfloat16):
        self._precision = precision

    def switch_ir_optim(self, flag=True):
        pass  # XLA always optimizes

    def enable_memory_optim(self, flag=True):
        pass

    def enable_profile(self):
        self._profile = True

    def summary(self):
        return (f"Config(model={self.model_path}, device={self._device}:"
                f"{self._device_id}, precision={self._precision})")


class InferTensor:
    """Handle-based IO tensor (reference paddle_inference_api.h Tensor:
    copy_from_cpu / copy_to_cpu / reshape / shape)."""

    def __init__(self, name):
        self.name = name
        self._data = None

    def reshape(self, shape):
        if self._data is not None:
            self._data = np.reshape(self._data, shape)
        else:
            self._data = np.zeros(shape, np.float32)

    def copy_from_cpu(self, arr):
        # the name is the contract: np.array COPIES, np.asarray would
        # alias the caller's buffer (PTL501)
        self._data = np.array(arr)

    def copy_to_cpu(self):
        return np.asarray(self._data)

    def shape(self):
        return list(np.shape(self._data))


class Predictor:
    """reference analysis_predictor.cc Predictor: named handles + run()."""

    def __init__(self, config, _shared_layer=None):
        self.config = config
        if config.model_path is None:
            raise ValueError("Config needs the saved-model path prefix")
        self._layer = (_shared_layer if _shared_layer is not None
                       else _jit_load(config.model_path))
        n_in = getattr(self._layer, "_n_inputs", None) or 1
        self._in_names = [f"x{i}" for i in range(n_in)]
        self._inputs = {n: InferTensor(n) for n in self._in_names}
        self._outputs = {}
        self._device = self._pick_device()
        self._place_params()
        if getattr(config, "_compiler_options", None):
            self._layer.set_compiler_options(config._compiler_options)

    def _pick_device(self):
        devs = jax.devices()
        if self.config._device == "cpu":
            cpus = [d for d in devs if d.platform == "cpu"]
            return cpus[0] if cpus else devs[0]
        accel = [d for d in devs if d.platform != "cpu"] or devs
        return accel[min(self.config._device_id, len(accel) - 1)]

    def _place_params(self):
        # dtypes are BAKED into the StableHLO signature at export time;
        # a precision knob that disagrees with the artifact cannot be
        # honored here — use convert_to_mixed_precision on the files
        if self.config._precision != PrecisionType.Float32:
            want = np.dtype(str(self.config._precision))
            have = {str(v.dtype) for v in self._layer._param_vals
                    if jnp.issubdtype(v.dtype, jnp.floating)}
            if have - {str(want)}:
                import warnings

                warnings.warn(
                    f"artifact was exported with param dtypes {have}; "
                    f"requested {want} — running as exported. Re-save "
                    "with convert_to_mixed_precision for bf16 storage.",
                    RuntimeWarning)
        self._layer._param_vals = [jax.device_put(v, self._device)
                                   for v in self._layer._param_vals]

    # -- reference API --
    def get_input_names(self):
        return list(self._in_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_names(self):
        return list(self._outputs) or ["output_0"]

    def get_output_handle(self, name):
        return self._outputs.setdefault(name, InferTensor(name))

    def run(self, inputs=None):
        """Handle-based (no args) or direct (list of arrays) execution."""
        if inputs is None:
            inputs = [self._inputs[n].copy_to_cpu()
                      for n in self._in_names]
        arrs = [jax.device_put(np.asarray(x), self._device)
                for x in inputs]
        out = self._layer(*arrs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        host = [np.asarray(o._value.astype(jnp.float32)
                           if o._value.dtype == jnp.bfloat16 else o._value)
                for o in outs]
        for i, h in enumerate(host):
            self.get_output_handle(f"output_{i}")._data = h
        return host

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config):
    return Predictor(config)


class PredictorPool:
    """N predictors over ONE loaded artifact (reference PredictorPool):
    the deserialized program and the device-placed weights are shared —
    pool members differ only in their IO handles."""

    def __init__(self, config, size=1):
        first = Predictor(config)
        self._preds = [first] + [
            Predictor(config, _shared_layer=first._layer)
            for _ in range(size - 1)]

    def retrieve(self, idx):
        return self._preds[idx]


def convert_to_mixed_precision(src_prefix, dst_prefix,
                               mixed_precision=PrecisionType.Bfloat16,
                               backend=None, **kw):
    """Re-export a jit.save artifact with parameters STORED in the mixed
    dtype (reference convert_to_mixed_precision tool). The new program
    casts params up at its boundary, so weight storage/transfer halves
    while the exported compute graph is reused unchanged (TPU matmuls
    already run bf16 on the MXU via default precision)."""
    from ..framework.io_state import save as t_save

    layer = _jit_load(src_prefix)
    if mixed_precision == PrecisionType.Int8:
        raise NotImplementedError(
            "int8 needs calibration scales, not a dtype cast — use "
            "paddle_tpu.quantization.PostTrainingQuantization")
    cast = (jnp.bfloat16 if mixed_precision == PrecisionType.Bfloat16
            else np.dtype(str(mixed_precision)))
    old_vals = layer._param_vals
    stored, orig_dtypes = [], []
    for v in old_vals:
        orig_dtypes.append(v.dtype)
        if jnp.issubdtype(v.dtype, jnp.floating):
            stored.append(v.astype(cast))
        else:
            stored.append(v)
    exported = layer._exported

    def fn(params, *xs):
        up = [p.astype(d) if jnp.issubdtype(p.dtype, jnp.floating) else p
              for p, d in zip(params, orig_dtypes)]
        return exported.call(up, *xs)

    n_params = len(old_vals)
    in_avals = list(exported.in_avals)
    input_shaped = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                    for a in in_avals[n_params:]]
    param_shaped = [jax.ShapeDtypeStruct(v.shape, v.dtype) for v in stored]
    new_exported = jax.export.export(jax.jit(fn))(param_shaped,
                                                  *input_shaped)
    with open(dst_prefix + ".stablehlo", "wb") as f:
        f.write(new_exported.serialize())
    t_save({"names": layer._names,
            "params": [np.asarray(v) for v in stored],
            "n_inputs": getattr(layer, "_n_inputs", None)},
           dst_prefix + ".pdiparams")
