"""Speculative decoding — draft-model propose, one-dispatch ragged verify.

PR-8's fused window killed the per-token host loop, but every accepted
token still costs one full-model forward: k tokens = k sequential big
matmul stacks inside the scan. Speculative decoding breaks that bound
(ROADMAP item 2(b); "Fine-Tuning and Serving Gemma on Cloud TPU" in
PAPERS.md is the serving-economics reference — accepted tokens per
big-model dispatch is the metric that pays for TPU serving):

* a small **draft model** (same GPT family, tied tokenizer) proposes k
  tokens per live sequence through its own paged KV pool — the cheap
  sequential part;
* the big model **verifies all k+1 positions of every slot in ONE
  ragged batched step** (`_CompiledVerifyStep` over
  `GPTGenerationMixin._paged_verify_fused`): the flat-token [1, T, d]
  layout and `F.paged_attention`'s per-token kv_lens already express
  "slot s, query j attends prefix pos0+j" with zero padding, so the k+1
  sequential big-model steps collapse into one batched matmul stack.

**Losslessness.** `sample_tokens` keys every draw on (engine seed,
stream, position) ONLY, so the target pick at a position is a
deterministic function of the accepted prefix. Acceptance is exact
match against that pick: for greedy rows this is longest-prefix argmax
match; for sampled rows the standard accept/reject test degenerates to
equality because the keyed categorical draw IS the target sample.
Greedy AND sampled outputs are therefore token-identical to the
non-speculative engine and invariant to spec_k (tests pin both). The
draft is *coupled* to the same key: `jax.random.categorical` is a
Gumbel argmax, so identical keys add identical noise to draft and
target logits — agreement is high whenever the distributions are
close, degrading gracefully (not catastrophically) at temperature.

**Pool mirroring.** The draft pool shares the engine's page tables and
page ids: same num_pages × page_size geometry, its own [N, P, h', d']
buffers sized by the draft config. One page allocation covers both
pools, so the PagePool/prefix-cache/preemption accounting is unchanged
— a page simply costs big-bytes + draft-bytes (`pool_bytes` reports
both; docs/SERVING.md "Speculative decoding" has the sizing table).

**Rollback is positional.** Rejected draft KV rows — in BOTH pools —
stay in place as stale garbage past the accepted frontier: kv_lens
masks them out of every later attention, and the rows are overwritten
(by position) when the real tokens arrive. No cleanup dispatch. The
draft's valid prefix is tracked per request (`draft_prefilled`) and
caught up through the draft's own flat-token prefill step — the same
chunked mechanism that replays the prompt into the draft pool after
admission or preemption.

Per window: [0-or-more draft catch-up ticks] + 1 draft propose scan +
1 big verify dispatch, emitting 1..k+1 tokens per live slot with ONE
host sync (the verify emits). All three executables follow the
TrainStep pattern — weights as jit arguments, (pools, scale planes,
PRNG key) one donated pytree; the key threads sequentially through
draft and big dispatches, so `reseed()` never recompiles any of them.
"""
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import metrics as _obs
from ..observability.tracing import trace_span as _trace_span
from .llm_engine import (
    _DISPATCHES, _FUSED_STEPS, _LIVE_SLOTS, _PAGE_FRAG, _PAGE_OCC,
    _QUEUE_DEPTH, _SLOT_OCC, _STEPS_TOTAL, _TOK_PER_DISPATCH,
    _TOKENS_TOTAL, _TTFT_SECONDS, PoolExhausted, _CompiledPagedStep,
    _CompiledStepBase,
)

__all__ = ["SpeculativeDecoder"]

# speculative-decoding telemetry (docs/OBSERVABILITY.md). Counters are
# process-global; the acceptance-rate gauge is derived from the global
# counters so several engines in one process don't stomp each other
# (same contract as pt_sched_ttft_slo_attainment).
_SPEC_PROPOSED = _obs.counter(
    "pt_spec_proposed_total",
    "draft tokens proposed to the verify step (window widths summed)")
_SPEC_ACCEPTED = _obs.counter(
    "pt_spec_accepted_total",
    "accepted draft tokens that entered the output (each window also "
    "emits one non-draft token: the target's own pick)")
_SPEC_ACC_RATE = _obs.gauge(
    "pt_spec_acceptance_rate",
    "accepted / proposed, process-cumulative (the multiplier that "
    "decides whether speculation pays)")
_SPEC_DRAFT_SECONDS = _obs.counter(
    "pt_spec_draft_seconds",
    "wall seconds spent in draft-model dispatches (catch-up prefill + "
    "propose scan) — the overhead side of the acceptance trade")


class _CompiledProposeStep(_CompiledStepBase):
    """The draft model's propose executable: the PR-8 fused scan
    (`_paged_decode_fused`) in PROPOSE mode — scan length k+1, per-row
    `lag`/`frontier` so the 1-token draft-KV lag a fully-accepted
    window leaves is replayed INSIDE this dispatch (iteration 0)
    instead of costing a separate catch-up tick on the steady-state
    hot path. Same compilation contract as every decode executable
    (`_CompiledStepBase`): weights as jit arguments, (pools, scales,
    key) donated, first compile outside the persistent cache."""

    def __init__(self, model, k, page_size):
        self._params = list(model.state_dict().values())
        self.k = int(k)
        ps = int(page_size)

        def pure(param_vals, tok0, pos0, rem, fin0, eos, temps, top_ps,
                 streams, lag, frontier, pt, kv_state):
            from ..autograd import engine as eng

            kv_vals, kv_scales, key = kv_state
            originals = [p._value for p in self._params]
            for p, v in zip(self._params, param_vals):
                p._value = v
            try:
                with eng.no_grad_guard():
                    emits, new_kv, new_scales = model._paged_decode_fused(
                        self.k + 1, ps, tok0, pos0, rem, fin0, eos,
                        temps, top_ps, streams, pt, list(kv_vals),
                        list(kv_scales) if kv_scales else None, key,
                        lag=lag, frontier=frontier)
            finally:
                for p, v in zip(self._params, originals):
                    p._value = v
            return emits, (new_kv, new_scales, key)

        self._jit = jax.jit(pure, donate_argnums=(12,))

    def __call__(self, tok0, pos0, rem, fin0, eos, temps, top_ps,
                 streams, lag, frontier, pt, kv_state):
        return self._run([p._value for p in self._params], tok0, pos0,
                         rem, fin0, eos, temps, top_ps, streams, lag,
                         frontier, pt, kv_state)


class _CompiledVerifyStep(_CompiledStepBase):
    """The big model's speculative-verify executable: ONE ragged
    batched step over all S·(k+1) positions
    (`GPTGenerationMixin._paged_verify_fused`) with exact-match
    acceptance, EOS and budget masking in-executable. Built exactly
    like `_CompiledFusedStep` (weights as jit ARGUMENTS, the kv pytree
    — pools + scale planes + PRNG key — DONATED, first compile outside
    the persistent cache). k is baked into the flat geometry, so one
    engine holds ONE verify executable per (k, geometry); narrow
    windows (pool pressure / short budgets) ride the width/rem
    arguments instead of re-tracing."""

    def __init__(self, model, k, page_size):
        self._params = list(model.state_dict().values())
        self.k = int(k)
        ps = int(page_size)

        def pure(param_vals, tok0, pos0, drafts, width, rem, fin0, eos,
                 temps, top_ps, streams, gstate0, gtrans, gmask, pt,
                 kv_state):
            from ..autograd import engine as eng

            kv_vals, kv_scales, key = kv_state
            originals = [p._value for p in self._params]
            for p, v in zip(self._params, param_vals):
                p._value = v
            try:
                with eng.no_grad_guard():
                    emits, new_kv, new_scales = model._paged_verify_fused(
                        self.k, ps, tok0, pos0, drafts, width, rem,
                        fin0, eos, temps, top_ps, streams, pt,
                        list(kv_vals),
                        list(kv_scales) if kv_scales else None, key,
                        gstate0=gstate0, gtrans=gtrans, gmask=gmask)
            finally:
                for p, v in zip(self._params, originals):
                    p._value = v
            return emits, (new_kv, new_scales, key)

        self._jit = jax.jit(pure, donate_argnums=(15,))

    def __call__(self, tok0, pos0, drafts, width, rem, fin0, eos, temps,
                 top_ps, streams, gstate0, gtrans, gmask, pt, kv_state):
        return self._run([p._value for p in self._params], tok0, pos0,
                         drafts, width, rem, fin0, eos, temps, top_ps,
                         streams, gstate0, gtrans, gmask, pt, kv_state)


class SpeculativeDecoder:
    """The engine's speculative-decoding state and window orchestration
    (module docstring has the design). Owned by `LLMEngine` when
    `LLMEngineConfig(draft_model=...)` is set; `try_window(frontier)`
    is the spec sibling of `_try_step_fused`."""

    mode = "draft"   # vs the n-gram speculator's "ngram" (metrics split)

    def __init__(self, engine, draft_model, spec_k):
        from ..distributed import mesh as mesh_mod
        from ..quantization import runtime as _qrt

        draft_model.eval()
        big_cfg = engine.model.config
        dcfg = draft_model.config
        if dcfg.vocab_size != big_cfg.vocab_size:
            raise ValueError(
                f"draft_model vocab_size {dcfg.vocab_size} != target "
                f"{big_cfg.vocab_size}: speculative decoding needs a "
                "tied tokenizer (proposals are target token ids)")
        if dcfg.max_seq_len < engine.max_model_len:
            raise ValueError(
                f"draft_model max_seq_len {dcfg.max_seq_len} < engine "
                f"max_model_len {engine.max_model_len}: the draft must "
                "reach every position it proposes at")
        self.engine = engine
        self.draft = draft_model
        self.k = int(spec_k)
        if self.k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.k}")
        ps = engine.page_size
        num_pages = engine.pool.num_pages
        nh = dcfg.num_heads
        hd = dcfg.hidden_size // nh
        # draft pool mirrors the engine pool's geometry — SAME page ids
        # and page tables, its own buffers in the engine's kv dtype
        draft_dt, self._quantized = _qrt.resolve_kv_dtype(
            engine.kv_dtype, draft_model.gpt.wte.weight._value.dtype)
        # packed int4 pools halve the stored head_dim (same shape
        # discriminator the engine pool uses)
        hd_store = hd // 2 if self._quantized == 4 else hd
        if self._quantized == 4 and hd % 2:
            raise ValueError(
                f"kv_dtype='int4': draft head_dim {hd} is odd — nibble "
                "packing pairs head_dim elements")
        sharding = mesh_mod.named_sharding()

        def _fresh_pools():
            pools = [
                jax.device_put(jnp.zeros((num_pages, ps, nh, hd_store),
                                         draft_dt), sharding)
                for _ in range(2 * dcfg.num_layers)]
            scales = []
            if self._quantized:
                sshape = _qrt.kv_scale_shape(num_pages, ps, nh)
                scales = [
                    jax.device_put(jnp.zeros(sshape, jnp.float32),
                                   sharding)
                    for _ in range(2 * dcfg.num_layers)]
            return pools, scales

        self._fresh_pools = _fresh_pools
        self._kv, self._kv_scales = _fresh_pools()
        # three executables, one each per (k, geometry): draft catch-up
        # prefill (flat tokens), draft propose (the PR-8 fused scan on
        # the draft model — the engine key couples its draws to the
        # target's), big verify (all k+1 positions, one dispatch)
        self._prefill_fn = _CompiledPagedStep(draft_model)
        self._propose_fn = _CompiledProposeStep(draft_model, self.k, ps)
        self._verify_fn = _CompiledVerifyStep(engine.model, self.k, ps)
        # catch-up geometry: its own flat budget (one executable) —
        # wide enough that a typical post-acceptance 1-token lag per
        # slot clears in one tick
        self._draft_T = max(engine.token_budget, engine.num_slots)
        self._stats = engine.stats
        for key in ("spec_windows", "spec_proposed", "spec_accepted"):
            self._stats.setdefault(key, 0)

    # ---- pool accounting ----

    def pool_bytes(self):
        """Draft-pool resident bytes (scale planes included) — added to
        the engine's `pool_bytes()`: a shared page costs big + draft."""
        return int(sum(int(a.nbytes) for a in self._kv)
                   + sum(int(s.nbytes) for s in self._kv_scales))

    def window_headroom(self):
        """Pages admission should leave free for the NEXT verify
        window: one per live frontier slot (the window's k-token
        reservation typically fits the slot's current tail page; one
        fresh page covers the spill). Keeps a burst of admissions from
        draining the pool to the point every window collapses to
        1-token widths (docs/SERVING.md)."""
        return sum(
            1 for r in self.engine._slots
            if r is not None and r.n_prefilled == len(r.tokens) - 1)

    def reset_pools(self):
        """abort_all path: the donated draft pytree may be consumed by
        a dispatch that died — re-zero (the engine re-creates the
        shared PRNG key via its own reseed)."""
        self._kv, self._kv_scales = self._fresh_pools()

    def release_pools(self):
        """Brownout L2 (fleet_serving.overload): drop the draft pool
        arrays — the HBM returns to the fleet NOW. pool_bytes() reads
        0 until `reset_pools` rebuilds; the engine parks this decoder
        while released, so no window can touch the empty lists."""
        self._kv, self._kv_scales = [], []

    # ---- draft catch-up ----

    def _catch_up(self, rows):
        """Replay tokens the draft pool is missing — down to a lag of
        at most ONE position per request — through the draft prefill
        executable, chunked to the flat budget. Covers initial prompt
        catch-up after admission and replay after preemption; the
        FINAL lagging position is deliberately left: the propose scan
        replays it in-dispatch (its lag/frontier mode), so the
        steady-state 1-token lag a fully-accepted window leaves never
        costs a catch-up tick here."""
        from ..distributed import mesh as mesh_mod

        eng = self.engine
        ps = eng.page_size
        T = self._draft_T
        sharding = mesh_mod.named_sharding()
        while True:
            todo = [(slot, req) for slot, req in rows
                    if req.draft_prefilled < req.n_prefilled - 1]
            if not todo:
                return
            tok = np.zeros((T,), np.int32)
            pos = np.zeros((T,), np.int32)
            sid = np.zeros((T,), np.int32)
            widx = np.zeros((T,), np.int32)
            klen = np.zeros((T,), np.int32)
            i = 0
            took = {}
            for slot, req in todo:
                take = min(req.n_prefilled - 1 - req.draft_prefilled,
                           T - i)
                for d in range(take):
                    p = req.draft_prefilled + d
                    tok[i] = req.tokens[p]
                    pos[i] = p
                    sid[i] = slot
                    widx[i] = (req.pages[p // ps] * ps + p % ps)
                    klen[i] = p + 1
                    i += 1
                took[slot] = take
                if i == T:
                    break
            _, (self._kv, self._kv_scales, eng._key) = self._prefill_fn(
                tok, pos, jax.device_put(sid, sharding), widx,
                eng._page_tables, klen,
                jax.device_put(np.zeros((1,), np.int32), sharding),
                (self._kv, self._kv_scales, eng._key))
            for slot, req in todo:
                req.draft_prefilled += took.get(slot, 0)

    # ---- the speculative window ----

    def try_window(self, frontier):
        """One speculative decode window over the frontier rows, or
        None when even the frontier token's page cannot be covered (the
        single-tick path takes the tick and owns preemption — same
        contract as `_try_step_fused`). Page capacity for positions
        pos0..pos0+width is reserved UP FRONT per row; pool pressure
        narrows a row's width (down to 0: verify-only plain decode for
        that row) instead of re-tracing anything."""
        eng = self.engine
        ps = eng.page_size
        k = self.k
        S = eng.num_slots

        # reserve pages: verify writes positions pos0..pos0+width (the
        # propose scan writes a prefix of the same range in the
        # mirrored draft pool — one reservation covers both)
        # brownout spec_k cap: a narrower proposal rides the `wid`/`rem`
        # runtime arguments of the SAME k-scan — degrading never
        # recompiles (fleet_serving.overload, ladder L1)
        cap = eng._brownout.get("spec_k_cap")
        k_eff = k if cap is None else max(0, min(k, int(cap)))

        width = {}
        for slot, req in frontier:
            w = min(0 if req.spec_off else k_eff,
                    req.target - len(req.tokens))
            last = req.n_prefilled + w
            try:
                while last // ps >= len(req.pages):
                    page = eng._alloc_page()
                    eng._page_tables[slot, len(req.pages)] = page
                    req.pages.append(page)
            except PoolExhausted:
                covered = len(req.pages) * ps - 1 - req.n_prefilled
                if covered < 0:
                    return None   # frontier write itself has no page
                w = min(w, covered)
            width[slot] = w

        # draft catch-up (prompt replay / post-acceptance lag)
        t_draft = _time.perf_counter()
        self._catch_up(frontier)

        tok0 = np.zeros((S,), np.int32)   # verify: the frontier token
        tok_p = np.zeros((S,), np.int32)  # propose: first scanned token
        pos0 = np.zeros((S,), np.int32)
        wid = np.zeros((S,), np.int32)
        rem = np.zeros((S,), np.int32)
        rem_p = np.zeros((S,), np.int32)
        lag = np.zeros((S,), np.int32)
        fin_v = np.ones((S,), bool)       # verify: dead slots
        fin_p = np.ones((S,), bool)       # propose: also width-0 rows
        eos = np.full((S,), -1, np.int32)
        temps = np.zeros((S,), np.float32)
        tops = np.ones((S,), np.float32)
        streams = np.zeros((S,), np.int32)
        gen_before = {}
        for slot, req in frontier:
            tok0[slot] = req.tokens[-1]
            pos0[slot] = req.n_prefilled
            wid[slot] = width[slot]
            rem[slot] = req.target - len(req.tokens)
            fin_v[slot] = False
            fin_p[slot] = width[slot] < 1
            if not fin_p[slot]:
                # after catch-up the draft lags by at most ONE row —
                # the propose scan replays it at iteration 0 (lag
                # mode), starting from the token BEFORE the frontier
                lag[slot] = req.n_prefilled - req.draft_prefilled
                tok_p[slot] = req.tokens[-1 - lag[slot]]
                rem_p[slot] = width[slot] + lag[slot]
            if req.eos is not None:
                eos[slot] = int(req.eos)
            temps[slot] = req.temperature
            tops[slot] = req.top_p
            streams[slot] = req.sample_stream
            gen_before[slot] = req.num_generated

        # structured decoding: arena DFA states + tables for the
        # verify's in-executable masking (the draft propose scan stays
        # unmasked — a grammar-illegal proposal simply fails
        # exact-match and truncates acceptance, losslessly)
        gst, gtrans, gmask = eng._grammar_args(frontier)

        t0 = _time.perf_counter()
        try:
            with _trace_span("llm_engine.spec_window", k=k,
                             live=len(frontier)):
                # draft propose: the PR-8 fused scan on the draft
                # model in propose mode, coupled to the engine key.
                # Proposals stay ON DEVICE into the verify call — the
                # window's single host sync is the verify emits below.
                d_emits, (self._kv, self._kv_scales, eng._key) = \
                    self._propose_fn(
                        tok_p, pos0, rem_p, fin_p, eos, temps, tops,
                        streams, lag, tok0, eng._page_tables,
                        (self._kv, self._kv_scales, eng._key))
                # row s's proposals start after its lag replay:
                # drafts[s, j] = emits[lag_s + j, s] (device gather —
                # no host sync)
                idx = (jnp.asarray(lag)[None, :]
                       + jnp.arange(k, dtype=jnp.int32)[:, None])
                drafts = jnp.swapaxes(
                    jnp.take_along_axis(d_emits, idx, axis=0), 0, 1)
                # block on the proposals before stamping: dispatch is
                # ASYNC, so the enqueue time alone would report the
                # draft as nearly free while its real cost hid inside
                # the verify's host sync. The verify consumes `drafts`
                # anyway, so the wait moves, it isn't added.
                jax.block_until_ready(drafts)
                _SPEC_DRAFT_SECONDS.inc(
                    _time.perf_counter() - t_draft)
                emits, (eng._kv, eng._kv_scales, eng._key) = \
                    self._verify_fn(
                        tok0, pos0, drafts, wid, rem, fin_v, eos,
                        temps, tops, streams, gst, gtrans, gmask,
                        eng._page_tables,
                        (eng._kv, eng._kv_scales, eng._key))
                emits = np.asarray(emits)  # [k+1, S]: the host sync
                # already materialized by the sync above — the host
                # copy feeds the exact accepted-token count below
                drafts_h = np.asarray(drafts)             # [S, k]
        except Exception as e:
            # the donated pytrees may be consumed mid-dispatch — same
            # recovery contract as the single tick and fused window
            eng.abort_all(e)
            raise
        eng.sched.note_boundary(_time.perf_counter() - t0)

        self._stats["steps"] += 1
        self._stats["spec_windows"] += 1
        self._stats["occupancy_sum"] += len(frontier) / S
        _STEPS_TOTAL.inc()
        _FUSED_STEPS.inc()
        _DISPATCHES.inc()

        finished = []
        now = _time.perf_counter()
        total = 0
        proposed = 0
        accepted = 0
        for slot, req in frontier:
            emitted, done, from_draft = 0, False, 0
            for j in range(k + 1):
                t = int(emits[j, slot])
                if t < 0:
                    break
                req.tokens.append(t)
                if req.grammar is not None:
                    # host replay of the DFA advance (llm_engine keeps
                    # gstate a pure function of the emitted tokens)
                    req.gstate = req.grammar.advance(req.gstate, t)
                # exact accepted count: an emitted pick equals the
                # draft at its position IFF that draft was accepted
                # (a rejected position's pick differs by definition),
                # so this also counts rem-clamped windows and an
                # accepted draft EOS correctly — emitted-1 would not
                if j < k and t == int(drafts_h[slot, j]):
                    from_draft += 1
                emitted += 1
                if ((req.eos is not None and t == req.eos)
                        or len(req.tokens) >= req.target):
                    done = True
            # positional rollback: n_prefilled advances over exactly
            # the verified-correct rows; stale draft/verify rows past
            # it are masked by kv_len and overwritten later
            req.n_prefilled += emitted
            # draft validity: the propose scan wrote width rows
            # starting at pos0 — correct up to the accepted prefix
            if width[slot] >= 1:
                req.draft_prefilled = min(
                    pos0[slot] + width[slot], req.n_prefilled)
            total += emitted
            proposed += width[slot]
            accepted += from_draft
            self._stats["generated"] += emitted
            eng.sched.note_tokens(req.tenant, emitted)
            if gen_before[slot] == 0 and emitted > 0:
                ttft = now - req.t_submit
                req.t_first_token = now
                req.trace.stamp("first_token")
                eng._note_timeline(req)
                _TTFT_SECONDS.observe(ttft)
                eng.sched.note_first_token(req, ttft)
            if done:
                eng._finish(slot, req)
                finished.append(req)
        self._stats["tokens_in"] += total
        self._stats["spec_proposed"] += proposed
        self._stats["spec_accepted"] += accepted
        eng.sched.note_spec_window(proposed, accepted)
        _SPEC_PROPOSED.inc(proposed)
        _SPEC_ACCEPTED.inc(accepted)
        n_prop = _SPEC_PROPOSED.value
        if n_prop:
            _SPEC_ACC_RATE.set(_SPEC_ACCEPTED.value / n_prop)
        _TOKENS_TOTAL.labels(phase="decode").inc(total)
        _TOK_PER_DISPATCH.set(total)
        _QUEUE_DEPTH.set(len(eng.waiting))
        # whole-engine load, not just the window's frontier rows — a
        # chunk-prefilling straggler still occupies its slot
        live = sum(r is not None for r in eng._slots)
        _LIVE_SLOTS.set(live)
        _SLOT_OCC.set(live / S)
        _PAGE_OCC.set(eng.pool.num_live / (eng.pool.num_pages - 1))
        _PAGE_FRAG.set(eng.kv_fragmentation())
        return finished
