"""Batch-serving inference — the TPU-idiomatic serving analog.

TPU-native counterpart of the reference serving surface
(reference: paddle/fluid/inference/api/analysis_predictor.cc — the
AnalysisPredictor the Paddle Serving server wraps; its zero-copy
request path + the server's dynamic request batching). The reference
optimizes a graph with IR passes and serves requests one
predictor-thread at a time; on TPU the win is the opposite shape: ONE
compiled program per PADDED BUCKET size, a dynamic batcher that groups
concurrent single requests into a bucket-sized batch (big batches keep
the MXU busy), and futures handing results back to the callers.

    server = InferenceServer(model)           # nn.Layer (fp32 or the
    with server:                              # int8 PTQ output), or a
        fut = server.submit(x_single)         # loaded Predictor
        y = fut.result()
        y2 = server.infer(x2)                 # submit + wait

Requests are SINGLE examples (no batch dim); the batcher stacks up to
`max_batch_size` of them (waiting at most `max_delay_ms` for
stragglers), pads the stack to the next configured bucket — one XLA
executable per bucket, not per observed batch size — runs one device
step, and scatters the rows back to the per-request futures. `stats`
reports requests/batches served and the mean occupancy.
"""
import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["BatchingConfig", "InferenceServer"]


class _FutureQueueServer:
    """Shared lifecycle for future/queue servers: ONE background thread
    owns the device; clients enqueue (payload, Future) pairs from any
    thread. Subclasses implement `_loop` (and usually a typed `submit`
    that builds the payload and calls `_enqueue`). Used by the dynamic
    batcher below and by the continuous-batching `LLMServer`
    (llm_engine.py)."""

    _thread_name = "serve-loop"

    def __init__(self):
        self._q = queue.Queue()
        self._thread = None
        self._running = False
        self._state_lock = threading.Lock()

    # -- lifecycle --
    def start(self):
        if self._running:
            return self
        if self._thread is not None and self._thread.is_alive():
            # a previous stop() timed out (e.g. serve loop stuck in a
            # long first compile): restarting would spawn a SECOND loop
            # consuming the same queue with the revived _running flag
            raise RuntimeError(
                "previous batcher thread is still shutting down; "
                "retry start() after it exits")
        self._running = True
        self._thread = threading.Thread(target=self._loop,
                                        name=self._thread_name,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        with self._state_lock:
            self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            # only forget the thread once it actually exited — a live
            # thread must block the next start() (see above)
            if not self._thread.is_alive():
                self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def _enqueue(self, payload):
        # check+put under the lock: a put racing stop() would otherwise
        # land in a queue the loop has already drained, leaving the
        # future unresolved forever
        with self._state_lock:
            if not self._running:
                raise RuntimeError(
                    "server not started (use `with server:`)")
            self._q.put(payload)

    def _loop(self):  # pragma: no cover - abstract
        raise NotImplementedError


class BatchingConfig:
    """Dynamic-batching policy: requests queue until `max_batch_size`
    are waiting or the oldest has waited `max_delay_ms`; the batch is
    padded up to the smallest bucket that fits (buckets default to
    powers of two up to max_batch_size — each bucket is one compiled
    executable, so shape churn never recompiles)."""

    def __init__(self, max_batch_size=32, max_delay_ms=2.0, buckets=None):
        self.max_batch_size = int(max_batch_size)
        self.max_delay_ms = float(max_delay_ms)
        if buckets is None:
            buckets = []
            b = 1
            while b < self.max_batch_size:
                buckets.append(b)
                b *= 2
            buckets.append(self.max_batch_size)
        self.buckets = sorted(set(int(b) for b in buckets))
        if self.buckets[-1] < self.max_batch_size:
            raise ValueError(
                f"largest bucket {self.buckets[-1]} < max_batch_size "
                f"{self.max_batch_size}")

    def bucket_for(self, n):
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]


def _layer_runner(layer):
    """(jitted_fn, param_vals) for an nn.Layer — one pure jax callable,
    jit-cached per input shape bucket."""
    from ..jit import _resolve_forward

    pure_fn, _names, param_vals = _resolve_forward(layer, None)
    jfn = jax.jit(pure_fn)

    def run(arrs):
        out = jfn(param_vals, *arrs)
        outs = out if isinstance(out, (list, tuple)) else (out,)
        return [np.asarray(o.astype(jnp.float32)
                           if o.dtype == jnp.bfloat16 else o)
                for o in outs]

    return run


def _predictor_runner(predictor):
    """Serve through a loaded Predictor artifact. Exported StableHLO is
    shape-specialized: the ONLY legal bucket is the exported batch size,
    so the server pads every batch to it."""
    fixed = None
    layer = predictor._layer
    avals = getattr(getattr(layer, "_exported", None), "in_avals", None)
    if avals is not None:
        n_params = len(layer._param_vals)
        input_avals = avals[n_params:]
        if input_avals:
            fixed = int(input_avals[0].shape[0])

    def run(arrs):
        return predictor.run(list(arrs))

    return run, fixed


class InferenceServer(_FutureQueueServer):
    """Dynamic-batching server over a model or Predictor (see module
    docstring). Thread-safe `submit`/`infer` from any number of client
    threads; one background batcher thread owns the device."""

    _thread_name = "infer-batcher"

    def __init__(self, source, batching=None):
        super().__init__()
        # private copy: a Predictor source rewrites the bucket list, and
        # a caller-shared config must not be mutated under another server
        src_cfg = batching or BatchingConfig()
        self.batching = BatchingConfig(
            max_batch_size=src_cfg.max_batch_size,
            max_delay_ms=src_cfg.max_delay_ms,
            buckets=list(src_cfg.buckets))
        self._fixed_bucket = None
        from ..nn import Layer

        if isinstance(source, Layer):
            source.eval()
            self._run = _layer_runner(source)
        elif hasattr(source, "_layer") and hasattr(source, "run"):
            self._run, self._fixed_bucket = _predictor_runner(source)
            if self._fixed_bucket is not None:
                self.batching.buckets = [self._fixed_bucket]
                self.batching.max_batch_size = min(
                    self.batching.max_batch_size, self._fixed_bucket)
        elif callable(source):
            self._run = lambda arrs: [
                np.asarray(o) for o in (
                    lambda out: out if isinstance(out, (list, tuple))
                    else (out,))(source(*arrs))]
        else:
            raise TypeError(
                f"InferenceServer source must be an nn.Layer, a "
                f"Predictor, or a callable; got {type(source)!r}")
        self.stats = {"requests": 0, "batches": 0, "rows_padded": 0}

    # -- client API --
    def submit(self, *example):
        """Enqueue ONE example (arrays without the batch dim). Returns a
        Future resolving to the list of output rows for this example."""
        fut = Future()
        self._enqueue((tuple(np.asarray(x) for x in example), fut))
        return fut

    def infer(self, *example):
        return self.submit(*example).result()

    @property
    def mean_batch_size(self):
        b = self.stats["batches"]
        return self.stats["requests"] / b if b else 0.0

    # -- batcher --
    def _collect(self):
        """Block for the first request, then sweep stragglers until the
        delay window closes or the batch is full."""
        try:
            first = self._q.get(timeout=0.1)
        except queue.Empty:
            return []
        batch = [first]
        deadline = time.monotonic() + self.batching.max_delay_ms / 1e3
        while len(batch) < self.batching.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(self._q.get(timeout=remaining))
            except queue.Empty:
                break
        return batch

    @staticmethod
    def _sig(example):
        return tuple((a.shape, str(a.dtype)) for a in example)

    def _loop(self):
        while self._running or not self._q.empty():
            collected = self._collect()
            if not collected:
                continue
            # group by input signature: requests with different shapes
            # (or one malformed request) must neither stack together
            # nor poison each other's futures
            groups = {}
            for ex, f in collected:
                groups.setdefault(self._sig(ex), []).append((ex, f))
            for batch in groups.values():
                try:
                    self._run_batch(batch)
                except Exception as e:  # defensive: never die silently
                    for _, f in batch:
                        if not f.done():
                            f.set_exception(e)

    def _run_batch(self, batch):
        examples = [ex for ex, _ in batch]
        futs = [f for _, f in batch]
        n = len(batch)
        bucket = self.batching.bucket_for(n)
        try:
            arrs = []
            for pos in range(len(examples[0])):
                rows = [ex[pos] for ex in examples]
                rows += [rows[0]] * (bucket - n)  # pad w/ row 0
                arrs.append(np.stack(rows))
            outs = self._run(arrs)
        except Exception as e:
            for f in futs:
                if not f.done():
                    f.set_exception(e)
            return
        self.stats["requests"] += n
        self.stats["batches"] += 1
        self.stats["rows_padded"] += bucket - n
        for i, f in enumerate(futs):
            f.set_result([o[i] for o in outs])
