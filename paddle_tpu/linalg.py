"""paddle.linalg namespace (reference: python/paddle/linalg.py).

Pure re-export layer: every op lives in ops/linalg.py or ops/extras.py
(XLA lowerings funneled through the autograd tape); this module pins the
reference's exact export list, including the `cond` and `inv` names that
clash with control-flow `cond` / are named `inverse` in the tensor API.
"""
from .ops.extras import eig, eigvals, inv, lu, lu_unpack  # noqa: F401
from .ops.linalg import (  # noqa: F401
    cholesky,
    cholesky_solve,
    corrcoef,
    cov,
    det,
    eigh,
    eigvalsh,
    lstsq,
    matrix_power,
    matrix_rank,
    multi_dot,
    norm,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)
from .ops.linalg import cond_number as cond  # noqa: F401

__all__ = [
    "cholesky", "norm", "eig", "cov", "corrcoef", "cond", "matrix_power",
    "solve", "cholesky_solve", "inv", "eigvals", "multi_dot", "matrix_rank",
    "svd", "eigvalsh", "qr", "lu", "lu_unpack", "eigh", "det", "slogdet",
    "pinv", "triangular_solve", "lstsq",
]
