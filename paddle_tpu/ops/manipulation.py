"""Shape / indexing / layout ops (paddle.tensor.manipulation equivalents).

reference: python/paddle/tensor/manipulation.py; phi kernels
paddle/phi/kernels/{reshape,concat,split,gather,scatter,...}_kernel.h.
All static-shape, XLA-friendly: dynamic result shapes (masked_select, nonzero)
are eager-only by design, same as the reference marks them non-inferable.
"""
import builtins

import numpy as np

import jax
import jax.numpy as jnp

from ..autograd import engine
from ..core.dtype import convert_dtype as _cd


def _i64():
    return _cd("int64")

from ..core import dtype as dtype_mod
from ..tensor_core import Tensor
from ._helpers import apply_jfn, defop, ensure_tensor


def _axes(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


@defop("cast")
def cast(x, dtype):
    d = dtype_mod.convert_dtype(dtype)
    x = ensure_tensor(x)
    return apply_jfn("cast", lambda a: a.astype(d), x)


@defop("reshape")
def reshape(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = [int(s._value) if isinstance(s, Tensor) else int(s) for s in shape]
    return apply_jfn("reshape", lambda a: jnp.reshape(a, shape), x)


@defop("reshape_")
def reshape_(x, shape, name=None):
    from . import _snapshot_for_inplace

    out = reshape(_snapshot_for_inplace(x, "reshape"), shape)
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


@defop("flatten")
def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = ensure_tensor(x)
    nd = x.ndim
    sa = start_axis % nd if nd else 0
    ea = stop_axis % nd if nd else 0
    new_shape = list(x.shape[:sa]) + [-1] + list(x.shape[ea + 1:])
    return apply_jfn("flatten", lambda a: jnp.reshape(a, new_shape), x)


@defop("transpose")
def transpose(x, perm=None, name=None):
    x = ensure_tensor(x)
    p = None if perm is None else tuple(int(i) for i in perm)
    return apply_jfn("transpose", lambda a: jnp.transpose(a, p), x)


@defop("moveaxis")
def moveaxis(x, source, destination, name=None):
    return apply_jfn(
        "moveaxis", lambda a: jnp.moveaxis(a, source, destination), ensure_tensor(x)
    )


@defop("swapaxes")
def swapaxes(x, axis0, axis1, name=None):
    return apply_jfn(
        "swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), ensure_tensor(x)
    )


@defop("squeeze")
def squeeze(x, axis=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        return apply_jfn("squeeze", jnp.squeeze, x)
    ax = _axes(axis)
    if isinstance(ax, int):
        ax = (ax,)
    ax = tuple(a for a in ax if x.shape[a] == 1)
    return apply_jfn("squeeze", lambda a: jnp.squeeze(a, ax), x)


@defop("unsqueeze")
def unsqueeze(x, axis, name=None):
    x = ensure_tensor(x)
    ax = _axes(axis)
    return apply_jfn("unsqueeze", lambda a: jnp.expand_dims(a, ax), x)


@defop("concat")
def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    tensors = tuple(ensure_tensor(t) for t in x)
    return engine.apply(
        "concat", lambda *xs: jnp.concatenate(xs, axis=axis), tensors
    )


@defop("stack")
def stack(x, axis=0, name=None):
    tensors = tuple(ensure_tensor(t) for t in x)
    return engine.apply("stack", lambda *xs: jnp.stack(xs, axis=axis), tensors)


@defop("split")
def split(x, num_or_sections, axis=0, name=None):
    x = ensure_tensor(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        if -1 in sizes:
            known = np.sum([s for s in sizes if s >= 0])
            sizes = [s if s >= 0 else int(dim - known) for s in sizes]
    idx = np.cumsum(sizes)[:-1].tolist()
    out = engine.apply(
        "split", lambda a: tuple(jnp.split(a, idx, axis=axis)), (x,)
    )
    return list(out) if isinstance(out, tuple) else [out]


@defop("chunk")
def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


@defop("unbind")
def unbind(x, axis=0, name=None):
    x = ensure_tensor(x)
    n = x.shape[axis]
    out = engine.apply(
        "unbind",
        lambda a: tuple(
            jnp.squeeze(s, axis) for s in jnp.split(a, n, axis=axis)
        ),
        (x,),
    )
    return list(out) if isinstance(out, tuple) else [out]


@defop("tile")
def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    reps = tuple(int(r) for r in repeat_times)
    return apply_jfn("tile", lambda a: jnp.tile(a, reps), ensure_tensor(x))


@defop("expand")
def expand(x, shape, name=None):
    x = ensure_tensor(x)
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    tgt = [int(s) for s in shape]
    cur = x.shape
    # -1 means keep dim
    off = len(tgt) - len(cur)
    for i in range(len(tgt)):
        if tgt[i] == -1:
            tgt[i] = cur[i - off]
    return apply_jfn("expand", lambda a: jnp.broadcast_to(a, tgt), x)


@defop("broadcast_to")
def broadcast_to(x, shape, name=None):
    return expand(x, shape)


@defop("expand_as")
def expand_as(x, y, name=None):
    return expand(x, ensure_tensor(y).shape)


@defop("broadcast_tensors")
def broadcast_tensors(inputs, name=None):
    tensors = tuple(ensure_tensor(t) for t in inputs)
    out = engine.apply(
        "broadcast_tensors", lambda *xs: tuple(jnp.broadcast_arrays(*xs)), tensors
    )
    return list(out)


@defop("flip")
def flip(x, axis, name=None):
    ax = _axes(axis)
    return apply_jfn("flip", lambda a: jnp.flip(a, ax), ensure_tensor(x))


@defop("rot90")
def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_jfn("rot90", lambda a: jnp.rot90(a, k, axes), ensure_tensor(x))


@defop("roll")
def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    ax = None if axis is None else _axes(axis)
    return apply_jfn("roll", lambda a: jnp.roll(a, shifts, ax), ensure_tensor(x))


# ---- gather / scatter family ----
@defop("gather")
def gather(x, index, axis=0, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())

    def jfn(a, idx):
        idx = idx.reshape(-1) if idx.ndim > 1 else idx
        return jnp.take(a, idx, axis=axis)

    return engine.apply("gather", jfn, (x, index))


@defop("gather_nd")
def gather_nd(x, index, name=None):
    x, index = ensure_tensor(x), ensure_tensor(index)

    def jfn(a, idx):
        # k = idx.shape[-1] leading dims are gathered; k < a.ndim keeps the
        # trailing dims (numpy advanced indexing handles both)
        return a[tuple(jnp.moveaxis(idx, -1, 0))]

    return engine.apply("gather_nd", jfn, (x, index))


@defop("take_along_axis")
def take_along_axis(arr, indices, axis, name=None):
    return engine.apply(
        "take_along_axis",
        lambda a, i: jnp.take_along_axis(a, i, axis=axis),
        (ensure_tensor(arr), ensure_tensor(indices)),
    )


@defop("put_along_axis")
def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices = ensure_tensor(arr), ensure_tensor(indices)
    values = ensure_tensor(values)

    def jfn(a, i, v):
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        dims = list(range(a.ndim))
        idx = [
            jnp.broadcast_to(
                jnp.expand_dims(
                    jnp.arange(a.shape[d]),
                    tuple(x for x in dims if x != d),
                ),
                i.shape,
            )
            if d != axis
            else i
            for d in dims
        ]
        if reduce == "assign":
            return a.at[tuple(idx)].set(v)
        if reduce in ("add", "sum"):
            return a.at[tuple(idx)].add(v)
        if reduce in ("mul", "multiply"):
            return a.at[tuple(idx)].multiply(v)
        raise ValueError(f"unknown reduce {reduce}")

    return engine.apply("put_along_axis", jfn, (arr, indices, values))


@defop("scatter")
def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = (
        ensure_tensor(x),
        ensure_tensor(index),
        ensure_tensor(updates),
    )

    def jfn(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        # accumulate mode: zero out target rows then add
        base = a.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)

    return engine.apply("scatter", jfn, (x, index, updates))


@defop("scatter_nd_add")
def scatter_nd_add(x, index, updates, name=None):
    return engine.apply(
        "scatter_nd_add",
        lambda a, i, u: a.at[tuple(jnp.moveaxis(i, -1, 0))].add(u),
        (ensure_tensor(x), ensure_tensor(index), ensure_tensor(updates)),
    )


@defop("scatter_nd")
def scatter_nd(index, updates, shape, name=None):
    index, updates = ensure_tensor(index), ensure_tensor(updates)
    return engine.apply(
        "scatter_nd",
        lambda i, u: jnp.zeros(tuple(shape), u.dtype)
        .at[tuple(jnp.moveaxis(i, -1, 0))]
        .add(u),
        (index, updates),
    )


@defop("index_select")
def index_select(x, index, axis=0, name=None):
    return engine.apply(
        "index_select",
        lambda a, i: jnp.take(a, i, axis=axis),
        (ensure_tensor(x), ensure_tensor(index)),
    )


@defop("index_sample")
def index_sample(x, index):
    return engine.apply(
        "index_sample",
        lambda a, i: jnp.take_along_axis(a, i, axis=1),
        (ensure_tensor(x), ensure_tensor(index)),
    )


@defop("masked_select")
def masked_select(x, mask, name=None):
    # dynamic output shape → eager only (same restriction class as reference's
    # LoD ops; under jit use masked_fill/where instead)
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    out = np.asarray(x._value)[np.asarray(mask._value)]
    return Tensor(jnp.asarray(out), True)


@defop("masked_fill")
def masked_fill(x, mask, value, name=None):
    x, mask = ensure_tensor(x), ensure_tensor(mask)
    v = value._value if isinstance(value, Tensor) else value
    return engine.apply(
        "masked_fill", lambda a, m: jnp.where(m, v, a), (x, mask)
    )


@defop("where")
def where(condition, x=None, y=None, name=None):
    condition = ensure_tensor(condition)
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    x, y = ensure_tensor(x), ensure_tensor(y)
    return engine.apply(
        "where", lambda c, a, b: jnp.where(c, a, b), (condition, x, y)
    )


@defop("nonzero")
def nonzero(x, as_tuple=False, name=None):
    x = ensure_tensor(x)
    nz = np.nonzero(np.asarray(x._value))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i), True) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)), True)


# ---- sort / search ----
@defop("sort")
def sort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)

    def jfn(a):
        s = jnp.sort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return apply_jfn("sort", jfn, x)


@defop("argsort")
def argsort(x, axis=-1, descending=False, name=None):
    x = ensure_tensor(x)

    def jfn(a):
        s = jnp.argsort(a, axis=axis)
        return jnp.flip(s, axis=axis) if descending else s

    return apply_jfn("argsort", jfn, x).astype("int64")


@defop("topk")
def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = ensure_tensor(x)
    if isinstance(k, Tensor):
        k = int(k.item())

    def jfn(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(moved, k)
        else:
            v, i = jax.lax.top_k(-moved, k)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax).astype(_i64())

    values, indices = engine.apply("topk", jfn, (x,))
    return values, indices


@defop("kthvalue")
def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = ensure_tensor(x)

    def jfn(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis).astype(_i64())
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix

    return engine.apply("kthvalue", jfn, (x,))


@defop("mode")
def mode(x, axis=-1, keepdim=False, name=None):
    # host-side mode (eager-only, like the reference's CPU kernel path)
    x = ensure_tensor(x)
    a = np.asarray(x._value)
    moved = np.moveaxis(a, axis, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], a.dtype)
    idxs = np.empty(flat.shape[0], np.int64)
    for r in range(flat.shape[0]):
        u, c = np.unique(flat[r], return_counts=True)
        v = u[np.argmax(c)]
        vals[r] = v
        idxs[r] = np.nonzero(flat[r] == v)[0][-1]
    shp = list(moved.shape[:-1])
    out_v = vals.reshape(shp)
    out_i = idxs.reshape(shp)
    if keepdim:
        out_v = np.expand_dims(out_v, axis)
        out_i = np.expand_dims(out_i, axis)
    return Tensor(jnp.asarray(out_v), True), Tensor(jnp.asarray(out_i), True)


@defop("searchsorted")
def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    ss, v = ensure_tensor(sorted_sequence), ensure_tensor(values)
    side = "right" if right else "left"

    def jfn(s, val):
        out = jnp.searchsorted(s, val, side=side)
        return out.astype(jnp.int32 if out_int32 else _i64())

    return engine.apply("searchsorted", jfn, (ss, v))


@defop("bucketize")
def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32, right)


@defop("unique")
def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    x = ensure_tensor(x)
    res = np.unique(
        np.asarray(x._value),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res), True)
    return tuple(Tensor(jnp.asarray(r), True) for r in res)


@defop("unique_consecutive")
def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       name=None):
    x = np.asarray(ensure_tensor(x)._value)
    if axis is not None:
        raise NotImplementedError
    flat = x.reshape(-1)
    keep = np.ones(len(flat), dtype=bool)
    keep[1:] = flat[1:] != flat[:-1]
    out = [Tensor(jnp.asarray(flat[keep]), True)]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv), True))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, len(flat)))
        out.append(Tensor(jnp.asarray(counts), True))
    return out[0] if len(out) == 1 else tuple(out)


# ---- padding ----
@defop("pad")
def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    x = ensure_tensor(x)
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        # full-form: [d0_lo, d0_hi, d1_lo, d1_hi, ...] (paddle: per-dim pairs)
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(nd)]
    else:
        # partial form applies to trailing spatial dims (NCHW/NCL/NCDHW)
        k = len(pad) // 2
        pairs = [(0, 0)] * (nd - k)
        # paddle order: last-dim-first pairs reversed
        spatial = [(pad[2 * i], pad[2 * i + 1]) for i in range(k)]
        if data_format.upper().startswith("NC"):
            pairs = [(0, 0)] * (nd - k) + spatial[::-1]
        else:
            pairs = [(0, 0)] + spatial[::-1] + [(0, 0)]
    jmode = {"constant": "constant", "reflect": "reflect",
             "replicate": "edge", "circular": "wrap"}[mode]

    def jfn(a):
        if jmode == "constant":
            return jnp.pad(a, pairs, mode="constant", constant_values=value)
        return jnp.pad(a, pairs, mode=jmode)

    return apply_jfn("pad", jfn, x)


@defop("repeat_interleave")
def repeat_interleave(x, repeats, axis=None, name=None):
    x = ensure_tensor(x)
    if isinstance(repeats, Tensor):
        r = np.asarray(repeats._value)
        out = np.repeat(np.asarray(x._value), r, axis=axis)
        return Tensor(jnp.asarray(out), True)
    return apply_jfn(
        "repeat_interleave",
        lambda a: jnp.repeat(a, repeats, axis=axis),
        x,
    )


@defop("as_strided")
def as_strided(x, shape, stride, offset=0, name=None):
    raise NotImplementedError("as_strided is not XLA-expressible; use reshape/slice")


@defop("tensordot")
def tensordot(x, y, axes=2, name=None):
    return engine.apply(
        "tensordot",
        lambda a, b: jnp.tensordot(a, b, axes=axes),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("slice")
def slice(input, axes, starts, ends):
    input = ensure_tensor(input)
    idx = [builtins.slice(None)] * input.ndim
    for ax, s, e in zip(axes, starts, ends):
        s = int(s.item()) if isinstance(s, Tensor) else int(s)
        e = int(e.item()) if isinstance(e, Tensor) else int(e)
        idx[ax] = builtins.slice(s, e)
    return apply_jfn("slice", lambda a: a[tuple(idx)], input)


@defop("strided_slice")
def strided_slice(x, axes, starts, ends, strides, name=None):
    x = ensure_tensor(x)
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(s), int(e), int(st))
    return apply_jfn("strided_slice", lambda a: a[tuple(idx)], x)


def _normalize_index(idx):
    """Convert Tensors inside an index expression to arrays."""
    if isinstance(idx, Tensor):
        v = idx._value
        if v.dtype == jnp.bool_:
            return np.asarray(v)  # boolean mask → host (dynamic shape)
        return v
    if isinstance(idx, tuple):
        return tuple(_normalize_index(i) for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(idx)
    return idx


def _getitem(x, idx):
    nidx = _normalize_index(idx)

    def has_bool(i):
        if isinstance(i, np.ndarray) and i.dtype == np.bool_:
            return True
        if isinstance(i, tuple):
            return any(has_bool(j) for j in i)
        return False

    if has_bool(nidx):
        out = np.asarray(x._value)[
            nidx if not isinstance(nidx, tuple) else tuple(
                np.asarray(i) if hasattr(i, "shape") else i for i in nidx
            )
        ]
        return Tensor(jnp.asarray(out), True)
    return apply_jfn("getitem", lambda a: a[nidx], x)


def _setitem(x, idx, value):
    from . import _snapshot_for_inplace

    nidx = _normalize_index(idx)
    v = value._value if isinstance(value, Tensor) else value
    vt = ensure_tensor(value) if isinstance(value, Tensor) else None
    if isinstance(nidx, np.ndarray) and nidx.dtype == np.bool_:
        nidx = jnp.asarray(nidx)
    old = _snapshot_for_inplace(x, "setitem")
    if vt is not None and (not x.stop_gradient or not vt.stop_gradient):
        out = engine.apply(
            "setitem", lambda a, u: a.at[nidx].set(u.astype(a.dtype)), (old, vt)
        )
    else:
        out = apply_jfn(
            "setitem",
            lambda a: a.at[nidx].set(
                jnp.asarray(v).astype(a.dtype)
                if not np.isscalar(v)
                else v
            ),
            old,
        )
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
