"""Pallas flash attention for TPU.

TPU-native fused attention kernel — the counterpart of the reference's CUDA
fused attention (reference: paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h). Algorithm: FlashAttention-2 style online softmax — the score
matrix is never materialized in HBM; each (batch·head, q-block) accumulates
over k/v blocks in VMEM with running (max, sum) statistics, so HBM traffic is
O(seq·d) instead of O(seq²).

Grid layout: (batch·heads, q_blocks, kv_blocks) with the kv dimension
innermost — Mosaic revisits the same output block across kv steps, so the
f32 accumulator and the (m, l) statistics live in VMEM scratch and are
finalized on the last kv step. Matmuls are issued at (128, head_dim) tiles
with preferred_element_type=f32 so bf16 inputs still accumulate in f32 on
the MXU.

Backward: forward returns the per-row logsumexp; the registered custom VJP
recomputes scores blockwise from (q, k, v, lse) with plain XLA ops (the
remat-style backward — no O(seq²) residuals saved from the forward).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_bshd"]

NEG_INF = -1e30

# 512-tiles won the on-chip sweep (8.1ms vs 12.3ms at 128-tiles for
# b4·s2048·h16·d64 causal, and ahead of both the jnp path and jax's
# reference pallas kernel at the same shape)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _fa_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
               acc_ref, m_ref, l_ref, *, causal, scale, block_q, block_k,
               kv_blocks, seq_k):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks strictly above the diagonal
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1) + ki * block_k
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            s = jnp.where(rows >= cols, s, NEG_INF)
        if seq_k % block_k != 0:
            # mask the padded tail of the last kv block; without this the
            # padding columns inflate the softmax sum — and zero padded v
            # rows, since even 0-weight × garbage (NaN) rows would poison
            # the accumulator
            s = jnp.where(cols < seq_k, s, NEG_INF)
            vrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(vrows < seq_k, v, jnp.zeros_like(v))

        m_prev = m_ref[:, :1]  # [block_q, 1] (stats broadcast over lanes)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [block_q, block_k] f32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # fully-masked rows (can't happen under causal) would have l == 0
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(safe_l)


def _fa_forward(q, k, v, causal, block_q, block_k, interpret):
    """q,k,v: [bh, seq, d] → (out [bh, seq, d], lse [bh, seq])."""
    bh, seq, d = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq)
    block_k = min(block_k, seq_k)
    scale = 1.0 / math.sqrt(d)
    q_blocks = pl.cdiv(seq, block_q)
    kv_blocks = pl.cdiv(seq_k, block_k)

    kernel = functools.partial(
        _fa_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, kv_blocks=kv_blocks, seq_k=seq_k)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            # trailing singleton keeps the (block_q, 1) tile legal on TPU
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, seq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


def _attn_bwd_dense(q, k, v, out, lse, g, causal):
    """Remat backward from saved logsumexp (plain XLA; O(seq²) transient
    but nothing saved from forward). All math in f32."""
    d = q.shape[-1]
    scale = 1.0 / math.sqrt(d)
    qf, kf, vf = (t.astype(jnp.float32) for t in (q, k, v))
    gf, of = g.astype(jnp.float32), out.astype(jnp.float32)
    s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jnp.exp(s - lse[..., None])  # [b, q, k] == softmax(s)
    dv = jnp.einsum("bqk,bqd->bkd", p, gf)
    dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
    # d(softmax): rowwise dot(p, dp) term — equals sum(g*out) per row
    delta = jnp.sum(gf * of, axis=-1, keepdims=True)  # [b, q, 1]
    ds = p * (dp - delta) * scale
    dq = jnp.einsum("bqk,bkd->bqd", ds, kf)
    dk = jnp.einsum("bqk,bqd->bkd", ds, qf)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_bhd(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fa_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fa_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fa_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _fa_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse = res
    return _attn_bwd_dense(q, k, v, out, lse, g, causal)


_flash_attention_bhd.defvjp(_fa_fwd_rule, _fa_bwd_rule)


def flash_attention_bshd(q, k, v, causal=False,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False):
    """Fused attention on [batch, seq, heads, head_dim] (paddle layout).

    Differentiable; forward is the Pallas kernel, backward is the
    lse-remat formulation. `interpret=True` runs the kernel in the Pallas
    interpreter (CPU test tier).
    """
    b, s, h, d = q.shape
    sk = k.shape[1]
    if causal and s != sk:
        # the kernel's diagonal is top-aligned; the jnp/backward reference
        # is bottom-aligned — only identical for self-attention
        raise ValueError(
            f"causal flash attention requires seq_q == seq_k, got {s} vs "
            f"{sk}; use the jnp path for cross-length causal masks")

    def to_bhd(t, sl):
        return jnp.swapaxes(t, 1, 2).reshape(b * h, sl, t.shape[-1])

    qf = to_bhd(q, s)
    kf = to_bhd(k, sk)
    vf = to_bhd(v, sk)
    out = _flash_attention_bhd(qf, kf, vf, bool(causal), int(block_q),
                               int(block_k), bool(interpret))
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
