"""Pallas flash attention for TPU.

TPU-native fused attention kernel — the counterpart of the reference's CUDA
fused attention (reference: paddle/fluid/operators/fused/fused_attention_op.cu,
fmha_ref.h). Algorithm: FlashAttention-2 style online softmax — the score
matrix is never materialized in HBM; each (batch·head, q-block) accumulates
over k/v blocks in VMEM with running (max, sum) statistics, so HBM traffic is
O(seq·d) instead of O(seq²).

Grid layout: (batch·heads, q_blocks, kv_blocks) with the kv dimension
innermost — Mosaic revisits the same output block across kv steps, so the
f32 accumulator and the (m, l) statistics live in VMEM scratch and are
finalized on the last kv step. Matmuls are issued at (128, head_dim) tiles
with preferred_element_type=f32 so bf16 inputs still accumulate in f32 on
the MXU.

Backward: forward returns the per-row logsumexp; the registered custom VJP
recomputes scores blockwise from (q, k, v, lse) in two Pallas kernels (a dq
pass and a dk/dv pass, FlashAttention-2 style) — no O(seq²) tensor ever
reaches HBM in either direction. Tests check both directions against a
dense jnp attention in interpret mode (tests/test_pallas_kernels.py).
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention_bshd"]

NEG_INF = -1e30

# 512-tiles won the on-chip sweep (8.1ms vs 12.3ms at 128-tiles for
# b4·s2048·h16·d64 causal, and ahead of both the jnp path and jax's
# reference pallas kernel at the same shape)
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512


def _fa_kernel(q_ref, k_ref, v_ref, lens_ref, o_ref, lse_ref,
               acc_ref, m_ref, l_ref, *, causal, scale, block_q, block_k,
               kv_blocks, seq_k, use_lens):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # causal: skip blocks strictly above the diagonal
    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
    if use_lens:
        # per-batch valid kv length (key-padding mask): whole blocks past
        # the valid prefix are skipped dynamically
        kl = lens_ref[0, 0, 0]
        run = jnp.logical_and(run, ki * block_k < kl)

    @pl.when(run)
    def _compute():
        q = q_ref[0]  # [block_q, d]
        k = k_ref[0]  # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # [block_q, block_k]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1) + ki * block_k
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            s = jnp.where(rows >= cols, s, NEG_INF)
        if use_lens:
            # kl <= seq_k, so this also covers the padded buffer tail
            s = jnp.where(cols < kl, s, NEG_INF)
            vrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(vrows < kl, v, jnp.zeros_like(v))
        elif seq_k % block_k != 0:
            # mask the padded tail of the last kv block; without this the
            # padding columns inflate the softmax sum — and zero padded v
            # rows, since even 0-weight × garbage (NaN) rows would poison
            # the accumulator
            s = jnp.where(cols < seq_k, s, NEG_INF)
            vrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(vrows < seq_k, v, jnp.zeros_like(v))

        m_prev = m_ref[:, :1]  # [block_q, 1] (stats broadcast over lanes)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [block_q, block_k] f32
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        # fully-masked rows (can't happen under causal) would have l == 0
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)
        # lse laid out [bh, 1, seq]: the row vector lives on the LANE dim,
        # so the tile pads 8x (sublane), not 128x — a [bh, seq, 1] layout
        # padded each per-layer residual from 1.5M to 192M
        lse_ref[0, 0] = m_ref[:, 0] + jnp.log(safe_l[:, 0])


def _lens_operand(lens, bh, seq_k):
    """[bh] int32 lengths → a [bh, 1, 128] VMEM-tileable operand (the
    kernel reads lane 0); full-length dummy when lens is None."""
    if lens is None:
        return jnp.full((bh, 1, 128), seq_k, jnp.int32)
    return jnp.broadcast_to(
        lens.astype(jnp.int32)[:, None, None], (bh, 1, 128))


def _fa_forward(q, k, v, causal, block_q, block_k, interpret, lens=None):
    """q,k,v: [bh, seq, d] → (out [bh, seq, d], lse [bh, 1, seq]).
    lens: optional [bh] int32 per-row valid kv length (key padding)."""
    bh, seq, d = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq)
    block_k = min(block_k, seq_k)
    scale = 1.0 / math.sqrt(d)
    q_blocks = pl.cdiv(seq, block_q)
    kv_blocks = pl.cdiv(seq_k, block_k)

    use_lens = lens is not None
    kernel = functools.partial(
        _fa_kernel, causal=causal, scale=scale, block_q=block_q,
        block_k=block_k, kv_blocks=kv_blocks, seq_k=seq_k,
        use_lens=use_lens)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
    ]
    operands = [q, k, v]
    if use_lens:
        in_specs.append(pl.BlockSpec((1, 1, 128), lambda b, i, j: (b, 0, 0)))
        operands.append(_lens_operand(lens, bh, seq_k))
    else:
        # keep the hot path free of a dummy operand: adapt the kernel's
        # lens_ref slot away (it is only read under use_lens)
        body = kernel
        kernel = lambda qr, kr, vr, *rest: body(qr, kr, vr, None, *rest)

    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, q_blocks, kv_blocks),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, seq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),    # acc
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running sum
        ],
        interpret=interpret,
    )(*operands)
    return out, lse  # lse: [bh, 1, seq]


def _fa_bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                      lens_ref, dq_ref, acc_ref, *, causal, scale, block_q,
                      block_k, kv_blocks, seq_q, seq_k, use_lens):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = True
    if causal:
        run = ki * block_k <= qi * block_q + block_q - 1
    if use_lens:
        kl = lens_ref[0, 0, 0]
        run = jnp.logical_and(run, ki * block_k < kl)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]                    # bf16 inputs stay on the MXU
        lse = lse_ref[0, 0][:, None]    # [block_q, 1] f32 (lane-major row)
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        cols = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1) + ki * block_k
        if causal:
            rows = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0) + qi * block_q
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        if use_lens:
            # key-padding columns contribute nothing to dq
            p = jnp.where(cols < kl, p, 0.0)
            kvrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(kvrows < kl, v, jnp.zeros_like(v))
            k = jnp.where(kvrows < kl, k, jnp.zeros_like(k))
        elif seq_k % block_k != 0:
            # padded kv tail: p→0 and k/v pad rows zeroed so 0·NaN never
            # forms in dp or the final ds·k product
            p = jnp.where(cols < seq_k, p, 0.0)
            kvrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(kvrows < seq_k, v, jnp.zeros_like(v))
            k = jnp.where(kvrows < seq_k, k, jnp.zeros_like(k))
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        acc_ref[:] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(ki == kv_blocks - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _fa_bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                       lens_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, causal,
                       scale, block_q, block_k, q_blocks, seq_q, seq_k,
                       use_lens):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = True
    if causal:
        run = qi * block_q + block_q - 1 >= ki * block_k
    if use_lens:
        # kv blocks entirely past the valid prefix get zero dk/dv: skip
        kl = lens_ref[0, 0, 0]
        run = jnp.logical_and(run, ki * block_k < kl)

    @pl.when(run)
    def _compute():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        g = g_ref[0]                    # bf16 inputs stay on the MXU
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        rows = jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0) + qi * block_q
        if causal:
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            s = jnp.where(rows >= cols, s, NEG_INF)
        p = jnp.exp(s - lse)
        if use_lens:
            # key-padding columns: p→0 so padded k/v rows accumulate
            # exactly zero gradient (ds = p·(dp−delta) follows)
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            p = jnp.where(cols < kl, p, 0.0)
        if seq_q % block_q != 0:
            # padded q tail: those rows carry garbage lse/delta/g/q — zero
            # their weight so they contribute nothing to dk/dv (and no
            # 0·NaN forms in the ds^T·q product)
            p = jnp.where(rows < seq_q, p, 0.0)
            grows = jax.lax.broadcasted_iota(
                jnp.int32, g.shape, 0) + qi * block_q
            g = jnp.where(grows < seq_q, g, jnp.zeros_like(g))
            q = jnp.where(grows < seq_q, q, jnp.zeros_like(q))
        if seq_k % block_k != 0:
            cols = jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1) + ki * block_k
            p = jnp.where(cols < seq_k, p, 0.0)
            vrows = jax.lax.broadcasted_iota(
                jnp.int32, v.shape, 0) + ki * block_k
            v = jnp.where(vrows < seq_k, v, jnp.zeros_like(v))
        dv_acc[:] += jax.lax.dot_general(
            p.astype(g.dtype), g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            g, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        if seq_q % block_q != 0:
            # delta/lse are garbage on padded q rows, so 0·NaN leaked into
            # ds despite p being zeroed there — mask ds itself
            ds = jnp.where(rows < seq_q, ds, 0.0)
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    @pl.when(qi == q_blocks - 1)
    def _finalize():
        dk_ref[0] = (dk_acc[:]).astype(dk_ref.dtype)
        dv_ref[0] = (dv_acc[:]).astype(dv_ref.dtype)


def _attn_bwd_pallas(q, k, v, out, lse, g, causal, block_q, block_k,
                     interpret, g_lse=None, lens=None):
    """Flash backward: dq pass + dk/dv pass, each O(seq·d) HBM traffic.

    g_lse: optional cotangent of the lse output (ring attention's
    streaming merge differentiates through lse). Math: the score grad is
    ds = p∘(dp − delta) with delta = rowsum(do·o); an lse cotangent adds
    +p·g_lse (d lse/d s = p), i.e. delta_eff = delta − g_lse — one
    subtraction, the kernels are unchanged."""
    bh, seq, d = q.shape
    seq_k = k.shape[1]
    block_q = min(block_q, seq)
    block_k = min(block_k, seq_k)
    scale = 1.0 / math.sqrt(d)
    q_blocks = pl.cdiv(seq, block_q)
    kv_blocks = pl.cdiv(seq_k, block_k)
    gf = g.astype(q.dtype)
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]  # [bh, 1, seq] (lane-major)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    lse3 = lse  # already [bh, 1, seq]

    use_lens = lens is not None

    def with_lens_slot(body):
        # no-lens path: adapt the kernel's lens_ref slot away so the hot
        # path carries no dummy operand (lens_ref only read under
        # use_lens)
        if use_lens:
            return body
        return lambda qr, kr, vr, gr, lr, dr, *rest: body(
            qr, kr, vr, gr, lr, dr, None, *rest)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    kv_spec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i))
    in_specs = [q_spec, kv_spec, kv_spec, q_spec, row_spec, row_spec]
    operands = [q, k, v, gf, lse3, delta]
    if use_lens:
        in_specs.append(pl.BlockSpec((1, 1, 128), lambda b, i, j: (b, 0, 0)))
        operands.append(_lens_operand(lens, bh, seq_k))

    dq = pl.pallas_call(
        with_lens_slot(functools.partial(
            _fa_bwd_dq_kernel, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, kv_blocks=kv_blocks, seq_q=seq, seq_k=seq_k,
            use_lens=use_lens)),
        grid=(bh, q_blocks, kv_blocks),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, seq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(*operands)

    # dkv pass: grid transposed so the q dimension is innermost
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0))
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i))
    in_specs2 = [q_spec2, kv_spec2, kv_spec2, q_spec2, row_spec2, row_spec2]
    operands2 = [q, k, v, gf, lse3, delta]
    if use_lens:
        in_specs2.append(
            pl.BlockSpec((1, 1, 128), lambda b, j, i: (b, 0, 0)))
        operands2.append(_lens_operand(lens, bh, seq_k))
    dk, dv = pl.pallas_call(
        with_lens_slot(functools.partial(
            _fa_bwd_dkv_kernel, causal=causal, scale=scale, block_q=block_q,
            block_k=block_k, q_blocks=q_blocks, seq_q=seq, seq_k=seq_k,
            use_lens=use_lens)),
        grid=(bh, kv_blocks, q_blocks),
        in_specs=in_specs2,
        out_specs=[kv_spec2, kv_spec2],
        out_shape=[jax.ShapeDtypeStruct((bh, seq_k, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, seq_k, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(*operands2)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_attention_bhd(q, k, v, lens, causal, block_q, block_k,
                         interpret):
    out, _ = _fa_forward(q, k, v, causal, block_q, block_k, interpret,
                         lens=lens)
    return out


def _fa_fwd_rule(q, k, v, lens, causal, block_q, block_k, interpret):
    out, lse = _fa_forward(q, k, v, causal, block_q, block_k, interpret,
                           lens=lens)
    return out, (q, k, v, lens, out, lse)


def _fa_bwd_rule(causal, block_q, block_k, interpret, res, g):
    import numpy as np

    q, k, v, lens, out, lse = res
    dq, dk, dv = _attn_bwd_pallas(q, k, v, out, lse, g, causal, block_q,
                                  block_k, interpret, lens=lens)
    d_lens = (None if lens is None
              else np.zeros(lens.shape, jax.dtypes.float0))
    return dq, dk, dv, d_lens


_flash_attention_bhd.defvjp(_fa_fwd_rule, _fa_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse_bhd(q, k, v, causal=False,
                            block_q=DEFAULT_BLOCK_Q,
                            block_k=DEFAULT_BLOCK_K, interpret=False):
    """(out [bh,s,d], lse [bh,1,s]) with BOTH outputs differentiable —
    the building block for cross-device streaming merges (ring
    attention): the caller combines per-block results by lse and AD
    composes through the merge."""
    return _fa_forward(q, k, v, causal, block_q, block_k, interpret)


def _fa_lse_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fa_forward(q, k, v, causal, block_q, block_k, interpret)
    return (out, lse), (q, k, v, out, lse)


def _fa_lse_bwd(causal, block_q, block_k, interpret, res, cts):
    q, k, v, out, lse = res
    g_out, g_lse = cts
    return _attn_bwd_pallas(q, k, v, out, lse, g_out, causal, block_q,
                            block_k, interpret, g_lse=g_lse)


flash_attention_lse_bhd.defvjp(_fa_lse_fwd, _fa_lse_bwd)


def flash_attention_bshd(q, k, v, causal=False,
                         block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                         interpret=False, kv_lens=None):
    """Fused attention on [batch, seq, heads, head_dim] (paddle layout).

    Differentiable; forward and backward are Pallas kernels over the
    [batch·heads, seq, d] layout (Mosaic requires the tiled last-two dims,
    so a head-sliced 4-D blocking is not expressible — the wrapper pays
    one transpose each way instead). `interpret=True` runs in the Pallas
    interpreter (CPU test tier).

    kv_lens: optional [batch] int per-example valid key length (prefix
    key-padding mask, the BERT/ERNIE padded-batch case): columns >= len
    get zero attention weight and their k/v rows zero gradient; whole kv
    blocks past the valid prefix are skipped. Composes with `causal`.
    """
    b, s, h, d = q.shape
    sk = k.shape[1]
    if causal and s != sk:
        # the kernel's diagonal is top-aligned; the jnp/backward reference
        # is bottom-aligned — only identical for self-attention
        raise ValueError(
            f"causal flash attention requires seq_q == seq_k, got {s} vs "
            f"{sk}; use the jnp path for cross-length causal masks")

    def to_bhd(t, sl):
        return jnp.swapaxes(t, 1, 2).reshape(b * h, sl, t.shape[-1])

    qf = to_bhd(q, s)
    kf = to_bhd(k, sk)
    vf = to_bhd(v, sk)
    lens = None
    if kv_lens is not None:
        # [b] -> [b*h]: bh layout is batch-major then head. Clamp to
        # seq_k: the kernels' `cols < kl` masking subsumes the buffer
        # tail mask ONLY when kl <= seq_k — an oversized length would
        # let uninitialized block padding into the softmax.
        lens = jnp.repeat(
            jnp.minimum(jnp.asarray(kv_lens, jnp.int32), sk), h)
    out = _flash_attention_bhd(qf, kf, vf, lens, bool(causal), int(block_q),
                               int(block_k), bool(interpret))
    return jnp.swapaxes(out.reshape(b, h, s, d), 1, 2)
