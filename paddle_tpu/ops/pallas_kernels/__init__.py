"""Hand-written Pallas TPU kernels for the hottest fused ops.

This package is the TPU counterpart of the reference's native fused-op
corpus (reference: paddle/fluid/operators/fused/ — 110 files of CUDA fusion
kernels). On TPU, XLA already fuses elementwise chains into matmuls, so only
the ops where manual tiling beats the compiler get kernels here; everything
else stays jnp.

Kernels run in compiled mode on real TPU backends and in Pallas interpret
mode in the CPU test tier (tests/test_pallas_kernels.py).
"""
from . import flash_attention  # noqa: F401
from . import paged_attention  # noqa: F401
from .flash_attention import flash_attention_bshd  # noqa: F401
from .paged_attention import ragged_paged_attention  # noqa: F401

__all__ = ["flash_attention", "flash_attention_bshd",
           "paged_attention", "ragged_paged_attention"]
