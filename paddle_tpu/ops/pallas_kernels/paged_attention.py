"""Pallas ragged paged attention for TPU — the serving decode kernel.

TPU-native kernel for the continuous-batching LLM engine
(inference/llm_engine.py): attention over a PAGED KV cache, one query per
flat scheduled token, so decode tokens (1 per sequence) and chunked
prefill tokens (many per sequence) ride one launch with zero padding
between sequences (PAPERS.md "Ragged Paged Attention"; the reference's
serving stack keeps a contiguous per-request cache instead — paging is
what lets HBM scale with live tokens).

Layout: q [T, heads, head_dim]; the pool [num_pages, page_size, heads,
head_dim]. Grid (T, pages_per_seq) with the page dimension innermost:
each token revisits its output block across page steps, so the f32
accumulator and the online-softmax (m, l) statistics live in VMEM
scratch and are finalized on the last page step — the same
FlashAttention-2 shape as flash_attention.py, but the kv blocks are
GATHERED through the page table: the page id for grid step (t, j) is
read from scalar-prefetch SMEM (page_tables[slot_ids[t], j]) inside the
BlockSpec index_map, so Mosaic DMAs exactly the pages the token needs
and blocks past the token's kv length are skipped.

Decode-only (no VJP): serving runs under no_grad. Numerics follow the
flash kernel: matmuls accumulate f32 on the MXU, masked lanes get -1e30,
fully-masked rows (padding tokens, kv_len 0) finalize to exact zeros.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["ragged_paged_attention"]

NEG_INF = -1e30


def _unpack_nibbles(k):
    """Packed int4 page block [P, H, D/2] → sign-extended int8 codes
    [P, H, D] in VMEM — the ONE nibble codec, reused from
    quantization.runtime (shift/mask int32 arithmetic + a CONCATENATE
    on the lane dim — an interleave reshape would not lower on Mosaic;
    the split-halves layout was chosen for exactly this). A second
    copy here would have to stay bit-identical with `pack_int4`
    forever; lazy import keeps the kernel module free of the package
    import cycle."""
    from ...quantization.runtime import unpack_int4

    return unpack_int4(k, axis=-1)


def _rpa_kernel(sid_ref, pt_ref, lens_ref, off_ref, q_ref, k_ref, v_ref,
                *rest, page_size, pages_per_seq, scale, quantized):
    if quantized:
        # int8/int4 pools ride with per-row fp32 scale planes, gathered
        # through the SAME page_map (quantization runtime, PT_KV_DTYPE);
        # quantized == 4 marks packed nibbles (pool lane dim D/2)
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    t = pl.program_id(0)
    j = pl.program_id(1)
    # the frontier offset (scalar-prefetch SMEM) advances every LIVE
    # token's kv length; padding rows (base 0) stay padding — the fused
    # decode window's per-iteration frontier (one scalar per iteration,
    # the lens vector itself stays window-invariant)
    base = lens_ref[t]
    kvlen = jnp.where(base > 0, base + off_ref[0], 0)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # pages entirely past the token's valid prefix contribute nothing —
    # skip (padding tokens have kvlen 0, so they skip every page)
    @pl.when(j * page_size < kvlen)
    def _compute():
        q = q_ref[0]                     # [H, D]
        k = k_ref[0]                     # [P, H, D] (or [P, H, D/2] int4)
        v = v_ref[0]
        if quantized:
            # dequant-on-gather: the DMA moved int8 (or packed int4)
            # + [P, H] scales; the f32 rows only ever exist in VMEM
            if quantized == 4:
                k = _unpack_nibbles(k)
                v = _unpack_nibbles(v)
            k = k.astype(jnp.float32) * ks_ref[0][:, :, None]
            v = v.astype(jnp.float32) * vs_ref[0][:, :, None]
        kt = jnp.swapaxes(k, 0, 1)       # [H, P, D]
        s = jax.lax.dot_general(
            q, kt, (((1,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                        # [H, P]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1) + j * page_size
        s = jnp.where(cols < kvlen, s, NEG_INF)
        # freed/unwritten page rows hold stale-but-finite garbage (the
        # pool is zero-initialized); their weight is exactly 0 below,
        # but zero the v rows anyway so no accidental inf·0 can form
        vrows = jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0) + j * page_size
        v = jnp.where(vrows < kvlen, v, jnp.zeros_like(v))
        vt = jnp.swapaxes(v, 0, 1)       # [H, P, D]

        m_prev = m_ref[:, :1]            # [H, 1] (stats broadcast lanes)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)           # [H, P] f32
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(vt.dtype), vt, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == pages_per_seq - 1)
    def _finalize():
        l = l_ref[:, :1]
        # padding tokens (kv_len 0) never ran a page: l == 0 → zeros out
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / safe_l).astype(o_ref.dtype)


def _rpa_qblock_kernel(sid_ref, pt_ref, lens_ref, off_ref, q_ref, k_ref,
                       v_ref, *rest, page_size, pages_per_seq, scale,
                       quantized, qb):
    """Query-blocked variant for the speculative VERIFY step: the flat
    token batch arrives slot-major in contiguous blocks of `qb` rows
    (one slot per block — the verify layout packs exactly k+1 query
    tokens per slot), so the grid is (T/qb, pages_per_seq) and each of
    the slot's pages is DMA'd ONCE per block instead of once per query
    row — the per-token kernel would move the same page k+1 times.
    Query lengths stay ragged PER ROW: row i of block b masks its
    scores at its own kv_len, which is what lets draft token j attend
    to drafts 0..j-1 written in this same dispatch and never to later
    ones."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, acc_ref, m_ref, l_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    # per-row lens from scalar-prefetch SMEM: unrolled scalar reads
    # over the STATIC block height (qb = k+1)
    base = jnp.stack([lens_ref[b * qb + i] for i in range(qb)])
    kvlen = jnp.where(base > 0, base + off_ref[0], 0)    # [qb]
    kvmax = jnp.max(kvlen)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # pages past the LONGEST row's prefix contribute to no row — skip
    @pl.when(j * page_size < kvmax)
    def _compute():
        q = q_ref[...]                   # [qb, H, D]
        k = k_ref[0]                     # [P, H, D] (or [P, H, D/2] int4)
        v = v_ref[0]
        if quantized:
            if quantized == 4:
                k = _unpack_nibbles(k)
                v = _unpack_nibbles(v)
            k = k.astype(jnp.float32) * ks_ref[0][:, :, None]
            v = v.astype(jnp.float32) * vs_ref[0][:, :, None]
        qt = jnp.swapaxes(q, 0, 1)       # [H, qb, D]
        kt = jnp.swapaxes(k, 0, 1)       # [H, P, D]
        s = jax.lax.dot_general(
            qt, kt, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * scale                        # [H, qb, P]
        cols = jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2) + j * page_size
        s = jnp.where(cols < kvlen[None, :, None], s, NEG_INF)
        vrows = jax.lax.broadcasted_iota(
            jnp.int32, v.shape, 0) + j * page_size
        v = jnp.where(vrows < kvmax, v, jnp.zeros_like(v))
        vt = jnp.swapaxes(v, 0, 1)       # [H, P, D]

        m_prev = m_ref[:, :, :1]         # [H, qb, 1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)           # [H, qb, P] f32
        # a row this page is entirely PAST (the block ran because a
        # longer sibling row needed it) is all-masked here: its m_new
        # stays NEG_INF and exp(s - m_new) would be exp(0) = 1 across
        # the lane — zero such rows' weights so l/acc only ever see
        # real probability mass (the per-token kernel gets this for
        # free from its per-token pl.when gate)
        p = jnp.where(kvlen[None, :, None] > j * page_size, p, 0.0)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True),
            l_ref.shape)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p.astype(vt.dtype), vt, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                # [H, qb, D]
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    @pl.when(j == pages_per_seq - 1)
    def _finalize():
        l = l_ref[:, :, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = jnp.swapaxes(acc_ref[:] / safe_l, 0, 1).astype(
            o_ref.dtype)                 # [qb, H, D]


def ragged_paged_attention(q, k_pool, v_pool, page_tables, slot_ids,
                           kv_lens, k_scales=None, v_scales=None,
                           frontier_offset=None, q_per_slot=None,
                           interpret=False):
    """q [T, H, D], pools [N, P, H, D], page_tables [S, MP] int,
    slot_ids [T] int, kv_lens [T] int → out [T, H, D].

    frontier_offset: optional scalar int32 added to every NONZERO
    kv_lens row (rides scalar-prefetch SMEM like the page table). The
    fused multi-token decode window passes its scan iteration here so
    one loop-invariant lens vector serves every iteration — rows with
    base 0 (padding / finished) keep skipping all pages.

    k_scales/v_scales [N, P, H] fp32: per-row dequant scales of INT8
    pools (quantization runtime). They are gathered through the same
    page-table index_map as the pools and the dequant happens in VMEM
    after the DMA, so HBM traffic for the cache stays int8 — the whole
    point of the quantized pool (page bytes ≈ ×4 down vs fp32).

    q_per_slot: optional STATIC int — the caller's guarantee that the
    T query rows are slot-major contiguous blocks of exactly this many
    rows, one slot per block (the speculative VERIFY layout: k+1 rows
    per slot). Switches to the query-blocked kernel whose grid is
    (T/q_per_slot, pages_per_seq): each slot's pages are DMA'd once
    per BLOCK instead of once per row, while per-row kv_lens keep the
    in-window causal raggedness. Ignored when T is not a multiple.

    A quantized pool whose last dim is HALF the query head_dim holds
    PACKED int4 nibbles (kv_dtype="int4"): the kernel unpacks in VMEM
    after the DMA, so HBM traffic for the cache is int4 — page bytes
    ≈ ×8 down vs fp32 (same shape discriminator as the jnp reference).

    Semantics contract: identical to the jnp reference in
    nn/functional/attention.py `paged_attention` (pinned by the
    interpret-mode parity tests in tests/test_llm_engine.py and
    tests/test_quant_runtime.py)."""
    tokens, heads, dim = q.shape
    _, page_size, _, kdim = k_pool.shape
    _, pages_per_seq = page_tables.shape
    scale = 1.0 / math.sqrt(dim)
    quantized = 0
    if k_scales is not None:
        quantized = 4 if kdim * 2 == dim else 8

    if frontier_offset is None:
        frontier_offset = 0
    off = jnp.asarray(frontier_offset, jnp.int32).reshape((1,))

    if q_per_slot is not None and tokens % int(q_per_slot) == 0:
        return _qblock_call(q, k_pool, v_pool, page_tables, slot_ids,
                            kv_lens, off, k_scales, v_scales,
                            int(q_per_slot), scale, interpret)

    kernel = functools.partial(
        _rpa_kernel, page_size=page_size, pages_per_seq=pages_per_seq,
        scale=scale, quantized=quantized)

    def _eff_last(t, lens, offv):
        # last live page under the offset frontier (index_map twin of
        # the kernel's kvlen = where(base > 0, base + off, 0))
        base = lens[t]
        eff = jnp.where(base > 0, base + offv[0], 0)
        return jnp.maximum(eff - 1, 0) // page_size

    def page_map(t, j, sid, pt, lens, offv):
        # clamp j to the token's LAST live page: grid steps past the
        # valid prefix re-request the same block, so Mosaic elides their
        # HBM→VMEM copy (the compute is already pl.when-gated) — without
        # the clamp every dead page would still be DMA'd and kernel
        # bandwidth would scale with max_model_len, not live tokens
        last = _eff_last(t, lens, offv)
        return (pt[sid[t] * pages_per_seq + jnp.minimum(j, last)],
                0, 0, 0)

    def scale_map(t, j, sid, pt, lens, offv):
        last = _eff_last(t, lens, offv)
        return (pt[sid[t] * pages_per_seq + jnp.minimum(j, last)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, heads, dim),
                     lambda t, j, sid, pt, lens, offv: (t, 0, 0)),
        pl.BlockSpec((1, page_size, heads, kdim), page_map),
        pl.BlockSpec((1, page_size, heads, kdim), page_map),
    ]
    inputs = [q, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, heads), scale_map),
                     pl.BlockSpec((1, page_size, heads), scale_map)]
        inputs += [k_scales, v_scales]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(tokens, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (1, heads, dim),
            lambda t, j, sid, pt, lens, offv: (t, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, dim), jnp.float32),   # acc
            pltpu.VMEM((heads, 128), jnp.float32),   # running max
            pltpu.VMEM((heads, 128), jnp.float32),   # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, heads, dim), q.dtype),
        interpret=interpret,
    )(jnp.asarray(slot_ids, jnp.int32),
      jnp.asarray(page_tables, jnp.int32).reshape(-1),
      jnp.asarray(kv_lens, jnp.int32), off,
      *inputs)


def _qblock_call(q, k_pool, v_pool, page_tables, slot_ids, kv_lens,
                 off, k_scales, v_scales, qb, scale, interpret):
    """Build the query-blocked pallas_call (`_rpa_qblock_kernel`):
    grid (T/qb, pages_per_seq), q/out blocked [qb, H, D], kv pages
    gathered once per BLOCK through the slot of the block's first row
    (the slot-major contract — one slot per block)."""
    tokens, heads, dim = q.shape
    _, page_size, _, kdim = k_pool.shape
    _, pages_per_seq = page_tables.shape
    quantized = 0
    if k_scales is not None:
        quantized = 4 if kdim * 2 == dim else 8
    nblocks = tokens // qb

    kernel = functools.partial(
        _rpa_qblock_kernel, page_size=page_size,
        pages_per_seq=pages_per_seq, scale=scale, quantized=quantized,
        qb=qb)

    def _blk_last(b, lens, offv):
        # last live page any row of block b needs (index_map twin of
        # the kernel's per-row kvlen; the block clamp uses the MAX so
        # every row's pages are covered). The prefetched operands are
        # SMEM refs here — scalar reads only, unrolled over the STATIC
        # block height (qb = k+1, single digits).
        eff_max = jnp.asarray(0, jnp.int32)
        for i in range(qb):
            base = lens[b * qb + i]
            eff = jnp.where(base > 0, base + offv[0], 0)
            eff_max = jnp.maximum(eff_max, eff)
        return jnp.maximum(eff_max - 1, 0) // page_size

    def page_map(b, j, sid, pt, lens, offv):
        last = _blk_last(b, lens, offv)
        return (pt[sid[b * qb] * pages_per_seq + jnp.minimum(j, last)],
                0, 0, 0)

    def scale_map(b, j, sid, pt, lens, offv):
        last = _blk_last(b, lens, offv)
        return (pt[sid[b * qb] * pages_per_seq + jnp.minimum(j, last)],
                0, 0)

    in_specs = [
        pl.BlockSpec((qb, heads, dim),
                     lambda b, j, sid, pt, lens, offv: (b, 0, 0)),
        pl.BlockSpec((1, page_size, heads, kdim), page_map),
        pl.BlockSpec((1, page_size, heads, kdim), page_map),
    ]
    inputs = [q, k_pool, v_pool]
    if quantized:
        in_specs += [pl.BlockSpec((1, page_size, heads), scale_map),
                     pl.BlockSpec((1, page_size, heads), scale_map)]
        inputs += [k_scales, v_scales]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(nblocks, pages_per_seq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(
            (qb, heads, dim),
            lambda b, j, sid, pt, lens, offv: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((heads, qb, dim), jnp.float32),   # acc
            pltpu.VMEM((heads, qb, 128), jnp.float32),   # running max
            pltpu.VMEM((heads, qb, 128), jnp.float32),   # running sum
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((tokens, heads, dim), q.dtype),
        interpret=interpret,
    )(jnp.asarray(slot_ids, jnp.int32),
      jnp.asarray(page_tables, jnp.int32).reshape(-1),
      jnp.asarray(kv_lens, jnp.int32), off,
      *inputs)
