"""Tensor creation ops (paddle.ones/zeros/to_tensor/...).

reference: python/paddle/tensor/creation.py; kernels
paddle/phi/kernels/full_kernel.h, arange_kernel.h, etc.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core import dtype as dtype_mod
from ..core import rng
from ..tensor_core import Tensor
from ..core.dtype import convert_dtype as _cd


def _i64():
    return _cd("int64")

from ._helpers import defop, ensure_tensor

__all__ = [
    "to_tensor",
    "zeros",
    "ones",
    "full",
    "empty",
    "zeros_like",
    "ones_like",
    "full_like",
    "empty_like",
    "arange",
    "linspace",
    "logspace",
    "eye",
    "rand",
    "randn",
    "normal",
    "uniform",
    "randint",
    "randperm",
    "bernoulli",
    "multinomial",
    "tril",
    "triu",
    "meshgrid",
    "diag",
    "diagflat",
    "diag_embed",
    "assign",
    "clone",
    "numel",
    "one_hot",
]


def _norm_shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _dt(dtype, default=None):
    d = dtype_mod.convert_dtype(dtype)
    if d is None:
        d = default or dtype_mod.get_default_dtype()
    return d


@defop("to_tensor")
def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    if isinstance(data, Tensor):
        v = data._value
        if dtype is not None:
            v = v.astype(dtype_mod.convert_dtype(dtype))
        return Tensor(v, stop_gradient=stop_gradient)
    d = dtype_mod.convert_dtype(dtype)
    if d is None and not isinstance(data, (np.ndarray, jax.Array)):
        # python scalars/lists of floats default to the framework dtype
        # (reference: python/paddle/tensor/creation.py to_tensor semantics)
        probe = np.asarray(data)
        if probe.dtype == np.float64:
            d = dtype_mod.get_default_dtype()
    v = jnp.asarray(data, dtype=d)
    return Tensor(v, stop_gradient=stop_gradient)


@defop("zeros")
def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_norm_shape(shape), _dt(dtype)), True)


@defop("ones")
def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_norm_shape(shape), _dt(dtype)), True)


@defop("full")
def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = dtype_mod.bool_
        elif isinstance(fill_value, int):
            dtype = dtype_mod.int64
        else:
            dtype = dtype_mod.get_default_dtype()
    return Tensor(jnp.full(_norm_shape(shape), fill_value, _dt(dtype)), True)


@defop("empty")
def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


@defop("zeros_like")
def zeros_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.zeros_like(x._value, dtype=dtype_mod.convert_dtype(dtype)), True)


@defop("ones_like")
def ones_like(x, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(jnp.ones_like(x._value, dtype=dtype_mod.convert_dtype(dtype)), True)


@defop("full_like")
def full_like(x, fill_value, dtype=None, name=None):
    x = ensure_tensor(x)
    return Tensor(
        jnp.full_like(x._value, fill_value, dtype=dtype_mod.convert_dtype(dtype)), True
    )


@defop("empty_like")
def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


@defop("arange")
def arange(start=0, end=None, step=1, dtype=None, name=None):
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(end, Tensor):
        end = end.item()
    if isinstance(step, Tensor):
        step = step.item()
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = (
            dtype_mod.int64
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else dtype_mod.get_default_dtype()
        )
    return Tensor(jnp.arange(start, end, step, _dt(dtype)), True)


@defop("linspace")
def linspace(start, stop, num, dtype=None, name=None):
    if isinstance(start, Tensor):
        start = start.item()
    if isinstance(stop, Tensor):
        stop = stop.item()
    if isinstance(num, Tensor):
        num = int(num.item())
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)), True)


@defop("logspace")
def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(
        jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)), True
    )


@defop("eye")
def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns and int(num_columns),
                          dtype=_dt(dtype)), True)


# ---- random ----
@defop("rand")
def rand(shape, dtype=None, name=None):
    return Tensor(
        jax.random.uniform(rng.next_key(), _norm_shape(shape), _dt(dtype)), True
    )


@defop("randn")
def randn(shape, dtype=None, name=None):
    return Tensor(
        jax.random.normal(rng.next_key(), _norm_shape(shape), _dt(dtype)), True
    )


@defop("normal")
def normal(mean=0.0, std=1.0, shape=None, name=None):
    if shape is None:
        shape = ()
    k = rng.next_key()
    return Tensor(
        jax.random.normal(k, _norm_shape(shape), dtype_mod.get_default_dtype())
        * std
        + mean,
        True,
    )


@defop("uniform")
def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    k = jax.random.PRNGKey(seed) if seed else rng.next_key()
    return Tensor(
        jax.random.uniform(k, _norm_shape(shape), _dt(dtype), min, max), True
    )


@defop("randint")
def randint(low=0, high=None, shape=(1,), dtype=None, name=None):
    if high is None:
        low, high = 0, low
    d = dtype_mod.convert_dtype(dtype) or dtype_mod.int64
    return Tensor(
        jax.random.randint(rng.next_key(), _norm_shape(shape), low, high, d), True
    )


@defop("randperm")
def randperm(n, dtype=None, name=None):
    d = dtype_mod.convert_dtype(dtype) or dtype_mod.int64
    return Tensor(
        jax.random.permutation(rng.next_key(), jnp.arange(n, dtype=d)), True
    )


@defop("bernoulli")
def bernoulli(x, name=None):
    x = ensure_tensor(x)
    return Tensor(
        jax.random.bernoulli(rng.next_key(), x._value).astype(x._value.dtype), True
    )


@defop("multinomial")
def multinomial(x, num_samples=1, replacement=False, name=None):
    x = ensure_tensor(x)
    probs = jnp.maximum(x._value, 0.0)
    if replacement:
        logits = jnp.log(jnp.maximum(probs, 1e-30))
        if x.ndim == 1:
            out = jax.random.categorical(
                rng.next_key(), logits, shape=(num_samples,)
            )
        else:
            out = jax.random.categorical(
                rng.next_key(), logits[:, None, :], axis=-1,
                shape=(logits.shape[0], num_samples),
            )
        return Tensor(out.astype(_i64()), True)
    # without replacement: per-row jax.random.choice
    if x.ndim == 1:
        out = jax.random.choice(
            rng.next_key(), probs.shape[0], (num_samples,), replace=False,
            p=probs / jnp.sum(probs),
        )
    else:
        rows = [
            jax.random.choice(
                rng.next_key(), probs.shape[1], (num_samples,), replace=False,
                p=probs[r] / jnp.sum(probs[r]),
            )
            for r in range(probs.shape[0])
        ]
        out = jnp.stack(rows)
    return Tensor(out.astype(_i64()), True)


# ---- structured ----
@defop("tril")
def tril(x, diagonal=0, name=None):
    from ._helpers import apply_jfn

    return apply_jfn("tril", lambda a: jnp.tril(a, diagonal), x)


@defop("triu")
def triu(x, diagonal=0, name=None):
    from ._helpers import apply_jfn

    return apply_jfn("triu", lambda a: jnp.triu(a, diagonal), x)


@defop("meshgrid")
def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    vals = [ensure_tensor(a)._value for a in args]
    outs = jnp.meshgrid(*vals, indexing="ij")
    return [Tensor(o, True) for o in outs]


@defop("diag")
def diag(x, offset=0, padding_value=0, name=None):
    from ._helpers import apply_jfn

    x = ensure_tensor(x)
    if x.ndim == 1 and padding_value != 0:
        def jfn(a):
            d = jnp.diag(a, offset)
            mask = jnp.eye(d.shape[0], dtype=bool)
            mask = jnp.roll(mask, offset, axis=1) if offset else mask
            return jnp.where(mask, d, padding_value).astype(a.dtype)

        return apply_jfn("diag", jfn, x)
    return apply_jfn("diag", lambda a: jnp.diag(a, offset), x)


@defop("diagflat")
def diagflat(x, offset=0, name=None):
    from ._helpers import apply_jfn

    return apply_jfn("diagflat", lambda a: jnp.diagflat(a, offset), x)


@defop("diag_embed")
def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    from ._helpers import apply_jfn

    x = ensure_tensor(input)

    def jfn(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        return jnp.moveaxis(out, (-2, -1), (dim1, dim2))

    return apply_jfn("diag_embed", jfn, x)


@defop("assign")
def assign(x, output=None):
    x = ensure_tensor(x)
    if output is None:
        return Tensor(x._value, True)
    output.set_value(x._value)
    return output


@defop("clone")
def clone(x, name=None):
    return ensure_tensor(x).clone()


@defop("numel")
def numel(x, name=None):
    return Tensor(jnp.asarray(ensure_tensor(x).size, _i64()), True)


@defop("one_hot")
def one_hot(x, num_classes, name=None):
    from ._helpers import apply_jfn

    return apply_jfn(
        "one_hot",
        lambda a: jax.nn.one_hot(a, num_classes, dtype=dtype_mod.get_default_dtype()),
        x,
    )
