"""Op-definition helpers.

TPU-native replacement for the reference's kernel registry + codegen
(reference: paddle/phi/core/kernel_registry.h PD_REGISTER_KERNEL and
paddle/phi/api/yaml/ generators). There is exactly one backend — XLA — so the
"registry" is: every op is a jax-traceable function funneled through the
autograd tape via `engine.apply`. Pallas kernels slot in by simply being the
jfn for their op.
"""
import functools

import jax.numpy as jnp

from ..autograd import engine
from ..core import dtype as dtype_mod

_OP_REGISTRY = {}


def register_op(name, fn):
    _OP_REGISTRY[name] = fn
    return fn


def get_op(name):
    return _OP_REGISTRY[name]


def list_ops():
    return sorted(_OP_REGISTRY)


def ensure_tensor(x, dtype=None):
    from ..tensor_core import Tensor

    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x, dtype=dtype), stop_gradient=True)


def value_of(x):
    from ..tensor_core import Tensor

    return x._value if isinstance(x, Tensor) else x


def unary_op(name, jfn, doc=None):
    """Build `op(x, name=None)` from an array function."""

    def op(x, name=None):
        x = ensure_tensor(x)
        return engine.apply(op.__name__, jfn, (x,))

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise {name} (thin XLA lowering)."
    register_op(name, op)
    return op


def binary_op(name, jfn, doc=None):
    """Build `op(x, y, name=None)`; y may be a python scalar."""

    def op(x, y, name=None):
        from ..tensor_core import Tensor

        if not isinstance(x, Tensor) and isinstance(y, Tensor):
            x = ensure_tensor(x, dtype=_scalar_dtype_for(x, y))
        elif not isinstance(x, Tensor):
            x = ensure_tensor(x)
        if not isinstance(y, Tensor):
            c = _const_for(y, x)
            return engine.apply(op.__name__, lambda a: jfn(a, c), (x,))
        y = ensure_tensor(y)
        return engine.apply(op.__name__, jfn, (x, y))

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Elementwise {name} with numpy broadcasting."
    register_op(name, op)
    return op


def _scalar_dtype_for(scalar, tensor):
    td = tensor.dtype
    if isinstance(scalar, bool):
        return None
    if isinstance(scalar, int) and dtype_mod.is_floating_point(td):
        return td
    if isinstance(scalar, float) and dtype_mod.is_floating_point(td):
        return td
    return None


def _const_for(scalar, tensor):
    """Keep python scalars weakly typed so x(float32) + 2 stays float32."""
    if isinstance(scalar, (int, float, bool, complex)):
        return scalar
    return jnp.asarray(scalar)


def reduce_op(name, jfn, doc=None):
    """Build `op(x, axis=None, keepdim=False, name=None)`."""

    def op(x, axis=None, keepdim=False, name=None):
        x = ensure_tensor(x)
        if isinstance(axis, (list, tuple)):
            axis = tuple(int(a) for a in axis)
        elif axis is not None:
            axis = int(axis)
        return engine.apply(
            op.__name__, lambda a: jfn(a, axis=axis, keepdims=keepdim), (x,)
        )

    op.__name__ = name
    op.__qualname__ = name
    op.__doc__ = doc or f"Reduction {name} over axis."
    register_op(name, op)
    return op


def defop(name):
    """Decorator: register a hand-written op under `name`."""

    def deco(fn):
        fn.__name__ = name
        register_op(name, fn)
        return fn

    return deco


def apply_jfn(name, jfn, *tensors):
    """Shortcut for hand-written ops."""
    return engine.apply(name, jfn, tuple(ensure_tensor(t) for t in tensors))
