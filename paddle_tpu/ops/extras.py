"""Op-corpus expansion: indexing, windowing, linalg and misc gaps.

Closes the remaining gaps vs the reference tensor API
(reference: python/paddle/tensor/manipulation.py, math.py, linalg.py,
search.py — e.g. index_add:4538, unfold:5721, as_strided:5638,
take:5850, renorm:3642, vander linalg.py:71, pdist/cdist incubate).
Every op funnels through the autograd tape (engine.apply) so gradients
flow wherever jax defines a VJP.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core import rng
from ..tensor_core import Tensor
from ._helpers import apply_jfn, defop, ensure_tensor, value_of

__all__ = [
    "cumulative_trapezoid", "logcumsumexp", "index_add", "index_put",
    "histogramdd", "diagonal", "take", "nanmedian", "nanquantile",
    "renorm", "nan_to_num", "vander", "polygamma", "fmod", "isreal",
    "as_complex", "as_real", "poisson", "standard_normal", "msort",
    "positive", "float_power", "unstack", "vsplit", "hsplit", "dsplit",
    "as_strided", "view", "view_as", "unflatten", "unfold", "pdist",
    "cdist", "inv", "svd_lowrank", "eig", "eigvals", "lu", "lu_unpack",
]


# ------------------------------------------------------------ reductions

@defop("logcumsumexp")
def logcumsumexp(x, axis=None, dtype=None, name=None):
    if dtype is not None:
        from ..core import dtype as dtype_mod

        dtype = dtype_mod.convert_dtype(dtype)

    def jfn(v):
        if dtype is not None:
            v = v.astype(dtype)
        if axis is None:
            return jax.lax.cumlogsumexp(v.reshape(-1), axis=0)
        return jax.lax.cumlogsumexp(v, axis=axis)

    return apply_jfn("logcumsumexp", jfn, x)


@defop("cumulative_trapezoid")
def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1, name=None):
    if x is not None:
        def jfn(yv, xv):
            d = jnp.diff(xv, axis=axis)
            avg = (_slice_axis(yv, axis, 1, None)
                   + _slice_axis(yv, axis, 0, -1)) * 0.5
            return jnp.cumsum(d * avg, axis=axis)

        return apply_jfn("cumulative_trapezoid", jfn, y, x)

    def jfn(yv):
        avg = (_slice_axis(yv, axis, 1, None)
               + _slice_axis(yv, axis, 0, -1)) * 0.5
        return jnp.cumsum(dx * avg, axis=axis)

    return apply_jfn("cumulative_trapezoid", jfn, y)


def _slice_axis(v, axis, start, stop):
    idx = [slice(None)] * v.ndim
    idx[axis] = slice(start, stop)
    return v[tuple(idx)]


@defop("nanmedian")
def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_jfn(
        "nanmedian",
        lambda v: jnp.nanmedian(v, axis=axis, keepdims=keepdim), x)


@defop("nanquantile")
def nanquantile(x, q, axis=None, keepdim=False, name=None):
    return apply_jfn(
        "nanquantile",
        lambda v: jnp.nanquantile(v, q, axis=axis, keepdims=keepdim), x)


# ------------------------------------------------------------- indexing

@defop("index_add")
def index_add(x, index, axis, value, name=None):
    """x with value added at `index` along `axis`
    (reference manipulation.py:4538)."""
    def jfn(xv, vv, iv):
        perm_idx = [slice(None)] * xv.ndim
        perm_idx[axis] = iv
        return xv.at[tuple(perm_idx)].add(vv)

    return apply_jfn("index_add", jfn, x, value, ensure_tensor(index))


@defop("index_put")
def index_put(x, indices, value, accumulate=False, name=None):
    """x[indices] = value (or += with accumulate)
    (reference manipulation.py:4747)."""
    indices = tuple(ensure_tensor(i) for i in indices)

    def jfn(xv, vv, *ivs):
        if accumulate:
            return xv.at[ivs].add(vv)
        return xv.at[ivs].set(vv)

    return apply_jfn("index_put", jfn, x, value, *indices)


@defop("take")
def take(x, index, mode="raise", name=None):
    """Gather from the FLATTENED input (reference manipulation.py:5850).
    mode: 'raise' (oob is an error — clipped in-graph, matching TPU
    semantics), 'wrap', 'clip'."""
    jmode = "clip" if mode == "raise" else mode

    def jfn(xv, iv):
        return jnp.take(xv.reshape(-1), iv, mode=jmode)

    return apply_jfn("take", jfn, x, ensure_tensor(index))


@defop("msort")
def msort(x, name=None):
    return apply_jfn("msort", lambda v: jnp.sort(v, axis=0), x)


# ------------------------------------------------------------ windowing

@defop("as_strided")
def as_strided(x, shape, stride, offset=0, name=None):
    """Functional as_strided (reference manipulation.py:5638): gathers
    flat indices offset + sum_d i_d * stride_d. A copy, not a view —
    XLA owns layout; there is no aliasing on TPU."""
    shape = tuple(int(s) for s in shape)
    stride = tuple(int(s) for s in stride)
    idx = jnp.full(shape, int(offset), jnp.int32)
    for d, (sz, st) in enumerate(zip(shape, stride)):
        ar = jnp.arange(sz, dtype=jnp.int32) * st
        idx = idx + ar.reshape((-1,) + (1,) * (len(shape) - d - 1))
    return apply_jfn("as_strided",
                     lambda v: jnp.take(v.reshape(-1), idx), x)


@defop("unfold")
def unfold(x, axis, size, step, name=None):
    """Sliding windows along `axis` (reference manipulation.py:5721):
    result appends a window dim of length `size`."""
    def jfn(v):
        ax = axis % v.ndim
        n = (v.shape[ax] - size) // step + 1
        starts = jnp.arange(n) * step
        windows = jax.vmap(
            lambda s: jax.lax.dynamic_slice_in_dim(v, s, size, axis=ax)
        )(starts)
        # windows: (n, ..., size at ax, ...) → move n to `ax`, window
        # length becomes the trailing dim
        win = jnp.moveaxis(windows, 0, ax)
        return jnp.moveaxis(win, ax + 1, -1)

    return apply_jfn("unfold", jfn, x)


@defop("view")
def view(x, shape_or_dtype, name=None):
    """Reshape (list/tuple) or bitcast reinterpret (dtype) — reference
    manipulation.py:5530. Functional copy under XLA."""
    if isinstance(shape_or_dtype, (list, tuple)):
        return apply_jfn(
            "view", lambda v: v.reshape(tuple(shape_or_dtype)), x)
    from ..core import dtype as dtype_mod

    dt = dtype_mod.convert_dtype(shape_or_dtype)

    def jfn(v):
        old, new = np.dtype(v.dtype).itemsize, np.dtype(dt).itemsize
        if new < old:
            # widening count: (..., d) → (..., d*ratio), not (..., d, ratio)
            out = jax.lax.bitcast_convert_type(v, dt)
            return out.reshape(v.shape[:-1] + (v.shape[-1] * (old // new),))
        if new > old:
            ratio = new // old
            grouped = v.reshape(v.shape[:-1] + (v.shape[-1] // ratio, ratio))
            return jax.lax.bitcast_convert_type(grouped, dt)
        return jax.lax.bitcast_convert_type(v, dt)

    return apply_jfn("view", jfn, x)


@defop("view_as")
def view_as(x, other, name=None):
    shape = tuple(value_of(ensure_tensor(other)).shape)
    return apply_jfn("view_as", lambda v: v.reshape(shape), x)


@defop("unflatten")
def unflatten(x, axis, shape, name=None):
    def jfn(v):
        ax = axis % v.ndim
        new = v.shape[:ax] + tuple(shape) + v.shape[ax + 1:]
        return v.reshape(new)

    return apply_jfn("unflatten", jfn, x)


@defop("unstack")
def unstack(x, axis=0, num=None, name=None):
    x = ensure_tensor(x)
    n = num or value_of(x).shape[axis]
    outs = apply_jfn(
        "unstack",
        lambda v: tuple(jnp.squeeze(s, axis=axis)
                        for s in jnp.split(v, n, axis=axis)), x)
    return list(outs)


def _np_style_split(name, jfn_split):
    def op(x, num_or_indices, name=None):
        x = ensure_tensor(x)
        outs = apply_jfn(name, lambda v: tuple(jfn_split(v, num_or_indices)),
                         x)
        return list(outs)

    op.__name__ = name
    return defop(name)(op)


vsplit = _np_style_split("vsplit", lambda v, n: jnp.vsplit(v, n))
hsplit = _np_style_split("hsplit", lambda v, n: jnp.hsplit(v, n))
dsplit = _np_style_split("dsplit", lambda v, n: jnp.dsplit(v, n))


# ----------------------------------------------------------------- misc

@defop("diagonal")
def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_jfn(
        "diagonal",
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2),
        x)


@defop("renorm")
def renorm(x, p, axis, max_norm, name=None):
    """Clamp each slice along `axis` to p-norm <= max_norm
    (reference math.py:3642)."""
    def jfn(v):
        dims = tuple(d for d in range(v.ndim) if d != axis)
        norms = jnp.sum(jnp.abs(v) ** p, axis=dims, keepdims=True) ** (1.0 / p)
        factor = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        return v * factor

    return apply_jfn("renorm", jfn, x)


@defop("nan_to_num")
def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_jfn(
        "nan_to_num",
        lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
        x)


@defop("vander")
def vander(x, n=None, increasing=False, name=None):
    return apply_jfn(
        "vander", lambda v: jnp.vander(v, N=n, increasing=increasing), x)


@defop("polygamma")
def polygamma(x, n, name=None):
    from jax.scipy.special import polygamma as _pg

    return apply_jfn("polygamma", lambda v: _pg(n, v), x)


@defop("fmod")
def fmod(x, y, name=None):
    return apply_jfn("fmod", jnp.fmod, x, ensure_tensor(y))


@defop("positive")
def positive(x, name=None):
    return apply_jfn("positive", lambda v: +v, x)


@defop("float_power")
def float_power(x, y, name=None):
    return apply_jfn("float_power",
                     lambda a, b: jnp.power(a.astype(jnp.float32),
                                            b.astype(jnp.float32)),
                     x, ensure_tensor(y))


@defop("histogramdd")
def histogramdd(x, bins=10, ranges=None, density=False, weights=None,
                name=None):
    xv = value_of(ensure_tensor(x))
    wv = None if weights is None else value_of(ensure_tensor(weights))
    h, edges = jnp.histogramdd(xv, bins=bins, range=ranges, density=density,
                               weights=wv)
    return Tensor(h, stop_gradient=True), [Tensor(e, True) for e in edges]


# -------------------------------------------------------------- complex

@defop("isreal")
def isreal(x, name=None):
    return apply_jfn("isreal", jnp.isreal, x)


@defop("as_complex")
def as_complex(x, name=None):
    """(..., 2) float → complex (reference manipulation.py as_complex)."""
    return apply_jfn(
        "as_complex", lambda v: jax.lax.complex(v[..., 0], v[..., 1]), x)


@defop("as_real")
def as_real(x, name=None):
    return apply_jfn(
        "as_real",
        lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), x)


# --------------------------------------------------------------- random

@defop("poisson")
def poisson(x, name=None):
    x = ensure_tensor(x)
    return Tensor(
        jax.random.poisson(rng.next_key(), value_of(x)).astype(
            value_of(x).dtype),
        stop_gradient=True)


@defop("standard_normal")
def standard_normal(shape, dtype=None, name=None):
    from .creation import randn

    return randn(shape, dtype=dtype)


# --------------------------------------------------------------- linalg

@defop("inv")
def inv(x, name=None):
    return apply_jfn("inv", jnp.linalg.inv, x)


def _safe_p_norm(diff, p):
    """p-norm over the last axis with a zero-safe VJP: the norm's gradient
    at 0 is NaN (0/||0||); identical points get gradient 0 instead.
    p=inf (Chebyshev) and p=0 (nonzero count) follow norm's ord rules."""
    if p == float("inf"):
        return jnp.max(jnp.abs(diff), axis=-1)
    if p == 0:
        return jnp.sum((diff != 0).astype(diff.dtype), axis=-1)
    sq = jnp.sum(jnp.abs(diff) ** p, axis=-1)
    nonzero = sq > 0
    safe = jnp.where(nonzero, sq, 1.0)
    return jnp.where(nonzero, safe ** (1.0 / p), 0.0)


@defop("pdist")
def pdist(x, p=2.0, name=None):
    """Condensed pairwise distances of rows (reference incubate
    pdist / torch-compatible). Differences are taken only for i<j pairs —
    a full n×n norm would put the zero diagonal through norm's VJP and
    poison every gradient with NaN."""
    def jfn(v):
        iu, ju = np.triu_indices(v.shape[0], k=1)
        return _safe_p_norm(v[iu] - v[ju], p)

    return apply_jfn("pdist", jfn, x)


@defop("cdist")
def cdist(x, y, p=2.0, name=None):
    def jfn(a, b):
        return _safe_p_norm(a[..., :, None, :] - b[..., None, :, :], p)

    return apply_jfn("cdist", jfn, x, ensure_tensor(y))


@defop("svd_lowrank")
def svd_lowrank(x, q=6, niter=2, M=None, name=None):
    """Randomized low-rank SVD (reference linalg svd_lowrank; Halko
    et al. structure, subspace iteration on a Gaussian sketch)."""
    xv = value_of(ensure_tensor(x))
    if M is not None:
        xv = xv - value_of(ensure_tensor(M))
    k = rng.next_key()
    m, n = xv.shape[-2], xv.shape[-1]
    q = min(q, m, n)
    omega = jax.random.normal(k, xv.shape[:-2] + (n, q), xv.dtype)
    y = xv @ omega
    for _ in range(niter):
        y = xv @ (jnp.swapaxes(xv, -1, -2) @ y)
    Q, _ = jnp.linalg.qr(y)
    B = jnp.swapaxes(Q, -1, -2) @ xv
    u, s, vh = jnp.linalg.svd(B, full_matrices=False)
    return (Tensor(Q @ u, True), Tensor(s, True),
            Tensor(jnp.swapaxes(vh, -1, -2), True))


@defop("eig")
def eig(x, name=None):
    """General (non-symmetric) eigendecomposition. XLA supports this on
    CPU only; on TPU the computation is lifted to the host via
    pure_callback (small-matrix host op, reference linalg.py eig)."""
    xv = value_of(ensure_tensor(x))
    try:
        w, v = jnp.linalg.eig(xv)
    except Exception:
        # complex128 needs x64; np.linalg.eig returns REAL arrays for an
        # all-real spectrum, so cast to the promised complex dtype
        wide = xv.dtype in (jnp.float64, jnp.complex128)
        cdt = (jnp.complex128 if wide and jax.config.jax_enable_x64
               else jnp.complex64)

        def _host_eig(a):
            w_, v_ = np.linalg.eig(np.asarray(a))
            return w_.astype(cdt), v_.astype(cdt)

        w, v = jax.pure_callback(
            _host_eig,
            (jax.ShapeDtypeStruct(xv.shape[:-1], cdt),
             jax.ShapeDtypeStruct(xv.shape, cdt)), xv)
    return Tensor(w, True), Tensor(v, True)


@defop("eigvals")
def eigvals(x, name=None):
    w, _ = eig(x)
    return w


@defop("lu")
def lu(x, pivot=True, get_infos=False, name=None):
    """LU factorization, packed LU + pivots (reference linalg.py lu)."""
    from jax.scipy.linalg import lu_factor

    xv = value_of(ensure_tensor(x))
    lu_, piv = lu_factor(xv)
    outs = (Tensor(lu_, True), Tensor(piv.astype(jnp.int32) + 1, True))
    if get_infos:
        outs = outs + (Tensor(jnp.zeros((), jnp.int32), True),)
    return outs


@defop("lu_unpack")
def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    lu_v = value_of(ensure_tensor(lu_data))
    piv = value_of(ensure_tensor(lu_pivots)) - 1
    m, n = lu_v.shape[-2], lu_v.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu_v[..., :, :k], -1) + jnp.eye(m, k, dtype=lu_v.dtype)
    U = jnp.triu(lu_v[..., :k, :])

    # pivots → permutation (batched: vmap the row-swap loop over leading
    # dims — lu_factor itself batches)
    def one_perm(p1d):
        perm = jnp.arange(m)
        for i in range(p1d.shape[0]):
            j = p1d[i]
            pi, pj = perm[i], perm[j]
            perm = perm.at[i].set(pj).at[j].set(pi)
        return perm

    batch = piv.shape[:-1]
    if batch:
        flat = piv.reshape((-1, piv.shape[-1]))
        perm = jax.vmap(one_perm)(flat).reshape(batch + (m,))
    else:
        perm = one_perm(piv)
    P = jnp.swapaxes(jnp.eye(m, dtype=lu_v.dtype)[perm], -1, -2)
    outs = []
    outs.append(Tensor(P, True) if unpack_pivots else None)
    outs.append(Tensor(L, True) if unpack_ludata else None)
    outs.append(Tensor(U, True) if unpack_ludata else None)
    return tuple(outs)
