"""Linear algebra ops (paddle.tensor.linalg / paddle.linalg equivalents).

reference: python/paddle/tensor/linalg.py; matmul kernel
paddle/phi/kernels/gpu/matmul_kernel.cu (cuBLAS). Here matmul lowers straight
onto the MXU via jnp.matmul (bf16/int8 handled by dtype); no BLAS wrapper
layer exists or is needed.
"""
import jax
import jax.numpy as jnp

from ..autograd import engine
from ._helpers import apply_jfn, defop, ensure_tensor


@defop("matmul")
def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)

    def jfn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)

    return engine.apply("matmul", jfn, (x, y))


@defop("mm")
def mm(input, mat2, name=None):
    return matmul(input, mat2)


@defop("bmm")
def bmm(x, y, name=None):
    return matmul(x, y)


@defop("dot")
def dot(x, y, name=None):
    return engine.apply(
        "dot",
        lambda a, b: jnp.sum(a * b, axis=-1),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("mv")
def mv(x, vec, name=None):
    return engine.apply(
        "mv", lambda a, v: a @ v, (ensure_tensor(x), ensure_tensor(vec))
    )


@defop("t")
def t(input, name=None):
    x = ensure_tensor(input)
    if x.ndim < 2:
        return x.clone()
    return apply_jfn("t", lambda a: jnp.swapaxes(a, -1, -2), x)


@defop("norm")
def norm(x, p="fro", axis=None, keepdim=False, name=None):
    x = ensure_tensor(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis

    def jfn(a):
        if p == "fro":
            if ax is None:
                return jnp.sqrt(jnp.sum(a * a))
            return jnp.sqrt(jnp.sum(a * a, axis=ax, keepdims=keepdim))
        if p in (float("inf"), "inf"):
            return jnp.max(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p in (float("-inf"), "-inf"):
            return jnp.min(jnp.abs(a), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=ax, keepdims=keepdim)
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply_jfn("norm", jfn, x)


@defop("dist")
def dist(x, y, p=2, name=None):
    return engine.apply(
        "dist",
        lambda a, b: jnp.linalg.norm((a - b).reshape(-1), ord=p),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("cond")
def cond_number(x, p=None, name=None):
    return apply_jfn("cond", lambda a: jnp.linalg.cond(a, p), ensure_tensor(x))


@defop("cross")
def cross(x, y, axis=9, name=None):
    x, y = ensure_tensor(x), ensure_tensor(y)
    ax = axis if axis != 9 else next(
        (i for i, s in enumerate(x.shape) if s == 3), -1
    )
    return engine.apply(
        "cross", lambda a, b: jnp.cross(a, b, axis=ax), (x, y)
    )


@defop("histogram")
def histogram(input, bins=100, min=0, max=0, name=None):
    import numpy as np

    from ..tensor_core import Tensor

    a = np.asarray(ensure_tensor(input)._value)
    lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
    h, _ = np.histogram(a, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(h.astype(np.int64)), True)


@defop("bincount")
def bincount(x, weights=None, minlength=0, name=None):
    x = ensure_tensor(x)
    if weights is None:
        return apply_jfn(
            "bincount", lambda a: jnp.bincount(a, length=None if minlength == 0 else minlength), x
        )
    w = ensure_tensor(weights)
    return engine.apply(
        "bincount",
        lambda a, ww: jnp.bincount(a, ww, length=None if minlength == 0 else minlength),
        (x, w),
    )


@defop("matrix_power")
def matrix_power(x, n, name=None):
    return apply_jfn(
        "matrix_power", lambda a: jnp.linalg.matrix_power(a, n), ensure_tensor(x)
    )


@defop("inverse")
def inverse(x, name=None):
    return apply_jfn("inverse", jnp.linalg.inv, ensure_tensor(x))


@defop("pinv")
def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_jfn(
        "pinv", lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian),
        ensure_tensor(x),
    )


@defop("det")
def det(x, name=None):
    return apply_jfn("det", jnp.linalg.det, ensure_tensor(x))


@defop("slogdet")
def slogdet(x, name=None):
    out = engine.apply(
        "slogdet", lambda a: tuple(jnp.linalg.slogdet(a)), (ensure_tensor(x),)
    )
    from .manipulation import stack

    return stack(list(out), axis=0)


@defop("svd")
def svd(x, full_matrices=False, name=None):
    return engine.apply(
        "svd",
        lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)),
        (ensure_tensor(x),),
    )


@defop("qr")
def qr(x, mode="reduced", name=None):
    return engine.apply(
        "qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), (ensure_tensor(x),)
    )


@defop("eigh")
def eigh(x, UPLO="L", name=None):
    return engine.apply(
        "eigh",
        lambda a: tuple(jnp.linalg.eigh(a, symmetrize_input=(UPLO == "L"))),
        (ensure_tensor(x),),
    )


@defop("eigvalsh")
def eigvalsh(x, UPLO="L", name=None):
    return apply_jfn("eigvalsh", jnp.linalg.eigvalsh, ensure_tensor(x))


@defop("cholesky")
def cholesky(x, upper=False, name=None):
    def jfn(a):
        c = jnp.linalg.cholesky(a)
        return jnp.swapaxes(c, -1, -2) if upper else c

    return apply_jfn("cholesky", jfn, ensure_tensor(x))


@defop("cholesky_solve")
def cholesky_solve(x, y, upper=False, name=None):
    return engine.apply(
        "cholesky_solve",
        lambda b, L: jax.scipy.linalg.cho_solve((L, not upper), b),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("solve")
def solve(x, y, name=None):
    return engine.apply(
        "solve", jnp.linalg.solve, (ensure_tensor(x), ensure_tensor(y))
    )


@defop("triangular_solve")
def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False,
                     name=None):
    return engine.apply(
        "triangular_solve",
        lambda a, b: jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular,
        ),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("lstsq")
def lstsq(x, y, rcond=None, driver=None, name=None):
    out = engine.apply(
        "lstsq",
        lambda a, b: tuple(jnp.linalg.lstsq(a, b, rcond=rcond)),
        (ensure_tensor(x), ensure_tensor(y)),
    )
    return out


@defop("matrix_rank")
def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_jfn(
        "matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=tol),
        ensure_tensor(x),
    )


@defop("multi_dot")
def multi_dot(x, name=None):
    tensors = tuple(ensure_tensor(t) for t in x)
    return engine.apply(
        "multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), tensors
    )


@defop("einsum")
def einsum(equation, *operands):
    tensors = tuple(ensure_tensor(t) for t in operands)
    return engine.apply(
        "einsum", lambda *xs: jnp.einsum(equation, *xs), tensors
    )


@defop("corrcoef")
def corrcoef(x, rowvar=True, name=None):
    return apply_jfn(
        "corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), ensure_tensor(x)
    )


@defop("cov")
def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_jfn(
        "cov",
        lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0),
        ensure_tensor(x),
    )
