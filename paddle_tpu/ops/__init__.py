"""Functional op namespace + Tensor method installation.

Mirrors how the reference monkey-patches generated ops onto the Tensor type
(reference: python/paddle/fluid/dygraph/math_op_patch.py,
paddle/fluid/pybind/eager_method.cc). All ops funnel through the autograd
tape in ..autograd.engine.
"""
from . import activation, creation, linalg, manipulation, math  # noqa: F401
from ._helpers import get_op, list_ops, register_op  # noqa: F401
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import (  # noqa: F401
    broadcast_tensors,
    broadcast_to,
    bucketize,
    cast,
    chunk,
    concat,
    expand,
    expand_as,
    flatten,
    flip,
    gather,
    gather_nd,
    index_sample,
    index_select,
    masked_fill,
    masked_select,
    mode,
    moveaxis,
    nonzero,
    pad,
    put_along_axis,
    repeat_interleave,
    reshape,
    reshape_,
    roll,
    rot90,
    scatter,
    scatter_nd,
    scatter_nd_add,
    searchsorted,
    slice,
    sort,
    split,
    squeeze,
    stack,
    strided_slice,
    swapaxes,
    take_along_axis,
    tensordot,
    tile,
    topk,
    transpose,
    unbind,
    unique,
    unique_consecutive,
    unsqueeze,
    where,
    argsort,
    kthvalue,
)
from .math import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
from .control_flow import *  # noqa: F401,F403
from .api_misc import *  # noqa: F401,F403


def _install_tensor_methods():
    from ..tensor_core import Tensor

    from . import activation as _act
    from . import api_misc as _misc
    from . import creation as _cre
    from . import extras as _ext
    from . import linalg as _lin
    from . import manipulation as _man
    from . import math as _math

    method_sources = {}
    for m in (_math, _man, _lin, _act, _ext, _misc):
        for name in dir(m):
            fn = getattr(m, name)
            if callable(fn) and not name.startswith("_"):
                method_sources.setdefault(name, fn)

    skip = {"to_tensor", "meshgrid", "einsum", "iinfo",
            "set_printoptions", "create_parameter", "set_grad_enabled",
            "disable_signal_handler", "get_cuda_rng_state",
            "set_cuda_rng_state", "check_shape", "tril_indices",
            "triu_indices"}
    for name, fn in method_sources.items():
        if name in skip or hasattr(Tensor, name):
            continue
        setattr(Tensor, name, fn)

    # extras under different method names
    Tensor.mean = _math.mean
    Tensor.sum = _math.sum
    Tensor.max = _math.max
    Tensor.min = _math.min
    Tensor.prod = _math.prod
    Tensor.abs = _math.abs
    Tensor.matmul = _lin.matmul
    Tensor.mm = _lin.mm
    Tensor.dot = _lin.dot
    Tensor.norm = _lin.norm
    Tensor.zero_like = _cre.zeros_like

    # in-place-suffixed aliases used by user code (functional under the hood).
    # The tape must reference a snapshot of the pre-mutation tensor, never
    # `self` (a node whose input is its own output tensor deadlocks backward).
    def _inplace(opname):
        fn = method_sources[opname]

        def method(self, *args, **kwargs):
            old = _snapshot_for_inplace(self, opname)
            out = fn(old, *args, **kwargs)
            self._inplace_version += 1
            self._value = out._value
            self._grad_node = out._grad_node
            self._out_index = out._out_index
            self.stop_gradient = out.stop_gradient
            return self

        return method

    for nm in ("add", "subtract", "multiply", "scale", "clip", "floor",
               "ceil", "exp", "sqrt", "rsqrt", "reciprocal", "round",
               "tanh", "squeeze", "unsqueeze", "flatten", "scatter",
               "remainder", "index_add", "erfinv", "lerp",
               "put_along_axis"):
        setattr(Tensor, nm + "_", _inplace(nm))

    # Tensor.cond is the linalg condition number (the registry name `cond`
    # belongs to control flow)
    Tensor.cond = _lin.cond_number

    # in-place RANDOM fills: fresh draws, shape/dtype from self — no
    # dependence on prior value, so no tape node (matches reference:
    # uniform_/exponential_ are VarBase mutations without grad)
    def _uniform_(self, min=-1.0, max=1.0, seed=0, name=None):
        import jax as _jax

        from ..core import rng as _rng

        self._inplace_version += 1
        self._value = _jax.random.uniform(
            _rng.next_key(), tuple(self.shape), self._value.dtype,
            minval=min, maxval=max)
        return self

    def _exponential_(self, lam=1.0, name=None):
        import jax as _jax

        from ..core import rng as _rng

        self._inplace_version += 1
        import jax.numpy as _jnp

        u = _jax.random.uniform(_rng.next_key(), tuple(self.shape),
                                self._value.dtype, minval=1e-12, maxval=1.0)
        self._value = -(1.0 / lam) * _jnp.log(u)
        return self

    Tensor.uniform_ = _uniform_
    Tensor.exponential_ = _exponential_

    # operator overloads
    Tensor.__add__ = lambda s, o: _math.add(s, o)
    Tensor.__radd__ = lambda s, o: _math.add(s, o)
    Tensor.__sub__ = lambda s, o: _math.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: _math.subtract(_to(o, s), s)
    Tensor.__mul__ = lambda s, o: _math.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: _math.multiply(s, o)
    Tensor.__truediv__ = lambda s, o: _math.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: _math.divide(_to(o, s), s)
    Tensor.__floordiv__ = lambda s, o: _math.floor_divide(s, o)
    Tensor.__mod__ = lambda s, o: _math.mod(s, o)
    Tensor.__pow__ = lambda s, o: _math.pow(s, o)
    Tensor.__rpow__ = lambda s, o: _math.pow(_to(o, s), s)
    Tensor.__neg__ = lambda s: _math.neg(s)
    Tensor.__abs__ = lambda s: _math.abs(s)
    Tensor.__matmul__ = lambda s, o: _lin.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: _lin.matmul(_to(o, s), s)
    Tensor.__eq__ = lambda s, o: _math.equal(s, o)
    Tensor.__ne__ = lambda s, o: _math.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: _math.less_than(s, o)
    Tensor.__le__ = lambda s, o: _math.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: _math.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: _math.greater_equal(s, o)
    Tensor.__invert__ = lambda s: _math.logical_not(s)
    Tensor.__and__ = lambda s, o: _math.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: _math.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: _math.bitwise_xor(s, o)
    Tensor.__hash__ = lambda s: id(s)


def _to(obj, like):
    from ._helpers import ensure_tensor

    return ensure_tensor(obj)


def _snapshot_for_inplace(t, opname):
    """Pre-mutation view of `t` for in-place ops so the recorded GradNode's
    input is not the op's own output (reference semantics: eager inplace
    version counting, paddle/fluid/eager/tensor_wrapper.h)."""
    from ..autograd import engine as _engine
    from ..tensor_core import Tensor

    if (
        _engine.is_grad_enabled()
        and not t.stop_gradient
        and t._grad_node is None
    ):
        raise RuntimeError(
            f"{opname}_: in-place modification of a leaf Tensor that "
            "requires grad is not supported; use paddle.no_grad() or the "
            "out-of-place op"
        )
    old = Tensor(t._value, stop_gradient=t.stop_gradient)
    old._grad_node = t._grad_node
    old._out_index = t._out_index
    return old


_install_tensor_methods()
