"""Top-level API parity gap-closers.

Small ops and utility symbols the reference exports from `paddle.*`
(reference: python/paddle/__init__.py; op sources
python/paddle/tensor/{math,manipulation,creation,search}.py,
python/paddle/framework/dtype.py iinfo, fluid/framework.py create_parameter).
Each funnels through the autograd tape where a gradient makes sense.
"""
import numpy as np

import jax.numpy as jnp

from ..autograd import engine
from ..core import dtype as dtype_mod
from ..tensor_core import Parameter, Tensor
from ._helpers import apply_jfn, defop, ensure_tensor, value_of

__all__ = [
    "add_n", "logit", "multiplex", "complex", "crop", "shard_index",
    "tril_indices", "triu_indices", "randint_like", "reverse",
    "broadcast_shape", "is_tensor", "is_complex", "is_floating_point",
    "is_integer", "is_empty", "rank", "shape", "tolist", "iinfo",
    "set_printoptions", "create_parameter", "set_grad_enabled",
    "disable_signal_handler", "get_cuda_rng_state", "set_cuda_rng_state",
    "squeeze_", "unsqueeze_", "tanh_", "scatter_", "remainder_",
    "index_add_", "check_shape",
]


@defop("add_n")
def add_n(inputs, name=None):
    """Elementwise sum of a list of same-shaped tensors."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    ts = tuple(ensure_tensor(t) for t in inputs)
    return engine.apply("add_n", lambda *vs: sum(vs[1:], vs[0]), ts)


@defop("logit")
def logit(x, eps=None, name=None):
    def jfn(v):
        if eps is not None:
            v = jnp.clip(v, eps, 1.0 - eps)
        return jnp.log(v) - jnp.log1p(-v)

    return apply_jfn("logit", jfn, x)


@defop("multiplex")
def multiplex(inputs, index, name=None):
    """Row-wise select: out[i] = inputs[index[i]][i]
    (reference: python/paddle/tensor/math.py multiplex)."""
    ts = tuple(ensure_tensor(t) for t in inputs)
    idx = value_of(ensure_tensor(index)).reshape(-1)

    def jfn(*vs):
        stacked = jnp.stack(vs)  # [n_candidates, rows, ...]
        return jnp.take_along_axis(
            stacked,
            idx.reshape((1, -1) + (1,) * (stacked.ndim - 2)).astype(jnp.int32),
            axis=0,
        )[0]

    return engine.apply("multiplex", jfn, ts)


def complex(real, imag, name=None):
    """Build a complex tensor from real and imaginary parts."""
    return engine.apply(
        "complex", lambda r, i: jnp.asarray(r) + 1j * jnp.asarray(i),
        (ensure_tensor(real), ensure_tensor(imag)))


@defop("crop")
def crop(x, shape=None, offsets=None, name=None):
    """Crop `x` to `shape` starting at `offsets` (-1 in shape = keep rest,
    None offsets = 0s). Reference: python/paddle/tensor/creation.py crop."""
    xt = ensure_tensor(x)
    nd = len(xt.shape)
    full = list(xt.shape)
    if shape is None:
        shape = full
    shape = [int(value_of(ensure_tensor(s)).item()) if isinstance(s, Tensor)
             else int(s) for s in (shape.tolist() if isinstance(shape, Tensor)
                                   else list(shape))]
    if offsets is None:
        offsets = [0] * nd
    offsets = [int(value_of(ensure_tensor(o)).item())
               if isinstance(o, Tensor) else int(o)
               for o in (offsets.tolist() if isinstance(offsets, Tensor)
                         else list(offsets))]
    shape = [full[i] - offsets[i] if shape[i] == -1 else shape[i]
             for i in range(nd)]

    def jfn(v):
        idx = tuple(builtins_slice(offsets[i], offsets[i] + shape[i])
                    for i in range(nd))
        return v[idx]

    return apply_jfn("crop", jfn, xt)


builtins_slice = slice  # ops.manipulation exports a `slice` op; keep py slice


@defop("shard_index")
def shard_index(input, index_num, nshards, shard_id, ignore_value=-1,
                name=None):
    """Recompute a global index to a shard-local index
    (reference: python/paddle/tensor/manipulation.py shard_index)."""
    if not 0 <= shard_id < nshards:
        raise ValueError(
            f"shard_id {shard_id} out of range for nshards {nshards}")
    shard_size = (index_num + nshards - 1) // nshards

    def jfn(v):
        in_shard = v // shard_size == shard_id
        return jnp.where(in_shard, v % shard_size, ignore_value)

    return apply_jfn("shard_index", jfn, input)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    if col is None:
        col = row
    d = dtype_mod.convert_dtype(dtype)
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), d), stop_gradient=True)


def triu_indices(row, col=None, offset=0, dtype="int64"):
    if col is None:
        col = row
    d = dtype_mod.convert_dtype(dtype)
    r, c = np.triu_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), d), stop_gradient=True)


def randint_like(x, low=0, high=None, dtype=None, name=None):
    """Like randint but shaped/typed after `x`; float dtypes get integral
    values cast to float (reference: tensor/random.py randint_like)."""
    import jax

    from ..core import rng

    xt = ensure_tensor(x)
    d = dtype_mod.convert_dtype(dtype) if dtype else xt._value.dtype
    if high is None:
        low, high = 0, low
    ints = jax.random.randint(rng.next_key(), tuple(xt.shape), low, high,
                              jnp.int32)
    return Tensor(ints.astype(d), stop_gradient=True)


def reverse(x, axis, name=None):
    """Deprecated alias of flip (reference keeps both)."""
    from .manipulation import flip

    return flip(x, axis)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


# ----------------------------------------------------------- predicates

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return dtype_mod.is_complex(
        x._value.dtype if isinstance(x, Tensor) else x)


def is_floating_point(x):
    return dtype_mod.is_floating_point(
        x._value.dtype if isinstance(x, Tensor) else x)


def is_integer(x):
    return dtype_mod.is_integer(
        x._value.dtype if isinstance(x, Tensor) else x)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(value_of(ensure_tensor(x)).size == 0),
                  stop_gradient=True)


def rank(input, name=None):
    return Tensor(jnp.asarray(value_of(ensure_tensor(input)).ndim),
                  stop_gradient=True)


def shape(input, name=None):
    """Shape as a 1-D int32 tensor (reference returns a tensor, not a list)."""
    return Tensor(
        jnp.asarray(value_of(ensure_tensor(input)).shape, jnp.int32),
        stop_gradient=True)


def tolist(x):
    return ensure_tensor(x).tolist()


def check_shape(shape):
    """Validate a shape argument (reference: tensor/random.py check_shape)."""
    if isinstance(shape, Tensor):
        return
    for s in shape:
        if not isinstance(s, Tensor) and int(s) < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")


# ----------------------------------------------------------- utilities

class iinfo:
    """Integer dtype limits (reference: python/paddle/framework/dtype.py)."""

    def __init__(self, dtype):
        info = np.iinfo(np.dtype(str(dtype_mod.convert_dtype(dtype))))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)

    def __repr__(self):
        return (f"paddle.iinfo(min={self.min}, max={self.max}, "
                f"bits={self.bits}, dtype={self.dtype})")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Tensor.__repr__ prints via numpy; route the knobs there."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def create_parameter(shape, dtype=None, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Standalone Parameter factory (reference:
    python/paddle/fluid/layers/tensor.py create_parameter)."""
    from ..nn import ParamAttr
    from ..nn import initializer as init_mod

    attr = ParamAttr._to_attr(attr)
    d = dtype_mod.convert_dtype(dtype or "float32")
    initializer = attr.initializer or default_initializer
    if initializer is None:
        initializer = (init_mod.Constant(0.0) if is_bias
                       else init_mod.XavierUniform())
    value = initializer._init(tuple(int(s) for s in shape), d)
    p = Parameter(value, trainable=attr.trainable, name=attr.name or name)
    p.optimize_attr["learning_rate"] = attr.learning_rate
    p.regularizer = attr.regularizer
    p.need_clip = attr.need_clip
    return p


def set_grad_enabled(mode):
    """Context manager enabling/disabling autograd recording."""
    return engine.enable_grad_guard() if mode else engine.no_grad_guard()


def disable_signal_handler():
    """No-op: the XLA runtime installs no catchable signal handlers here."""


def get_cuda_rng_state():
    """Alias onto the global RNG state (no CUDA; kept for API parity)."""
    from ..core.rng import _default_generator

    return [_default_generator.get_state()]


def set_cuda_rng_state(state_list):
    from ..core.rng import _default_generator

    if state_list:
        _default_generator.set_state(state_list[0])


# ------------------------------------------------- top-level inplace ops

def squeeze_(x, axis=None, name=None):
    return ensure_tensor(x).squeeze_(axis)


def unsqueeze_(x, axis, name=None):
    return ensure_tensor(x).unsqueeze_(axis)


def tanh_(x, name=None):
    return ensure_tensor(x).tanh_()


def scatter_(x, index, updates, overwrite=True, name=None):
    return ensure_tensor(x).scatter_(index, updates, overwrite)


def remainder_(x, y, name=None):
    return ensure_tensor(x).remainder_(y)


def index_add_(x, index, axis, value, name=None):
    return ensure_tensor(x).index_add_(index, axis, value)
