"""Math ops (paddle.tensor.math equivalents).

reference: python/paddle/tensor/math.py (dispatching to phi kernels
paddle/phi/kernels/elementwise_*.h, reduce_*.h, activation kernels). Here each
op is one jnp/lax expression lowered by XLA; fusion is the compiler's job.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..autograd import engine
from ..core.dtype import convert_dtype as _cd


def _i64():
    return _cd("int64")

from ._helpers import (
    apply_jfn,
    binary_op,
    defop,
    ensure_tensor,
    reduce_op,
    unary_op,
)

# ---- elementwise binary ----
add = binary_op("add", jnp.add)
subtract = binary_op("subtract", jnp.subtract)
multiply = binary_op("multiply", jnp.multiply)
divide = binary_op("divide", jnp.true_divide)
floor_divide = binary_op("floor_divide", jnp.floor_divide)
mod = binary_op("mod", jnp.mod)
remainder = mod
floor_mod = mod
pow = binary_op("pow", jnp.power)
maximum = binary_op("maximum", jnp.maximum)
minimum = binary_op("minimum", jnp.minimum)
fmax = binary_op("fmax", jnp.fmax)
fmin = binary_op("fmin", jnp.fmin)
atan2 = binary_op("atan2", jnp.arctan2)
hypot = binary_op("hypot", jnp.hypot)
copysign = binary_op("copysign", jnp.copysign)
nextafter = binary_op("nextafter", jnp.nextafter)
ldexp = binary_op("ldexp", jnp.ldexp)
heaviside = binary_op("heaviside", jnp.heaviside)
gcd = binary_op("gcd", jnp.gcd)
lcm = binary_op("lcm", jnp.lcm)
logaddexp = binary_op("logaddexp", jnp.logaddexp)

# ---- elementwise unary ----
abs = unary_op("abs", jnp.abs)
neg = unary_op("neg", jnp.negative)
exp = unary_op("exp", jnp.exp)
expm1 = unary_op("expm1", jnp.expm1)
log = unary_op("log", jnp.log)
log2 = unary_op("log2", jnp.log2)
log10 = unary_op("log10", jnp.log10)
log1p = unary_op("log1p", jnp.log1p)
sqrt = unary_op("sqrt", jnp.sqrt)
rsqrt = unary_op("rsqrt", jax.lax.rsqrt)
square = unary_op("square", jnp.square)
reciprocal = unary_op("reciprocal", jnp.reciprocal)
sin = unary_op("sin", jnp.sin)
cos = unary_op("cos", jnp.cos)
tan = unary_op("tan", jnp.tan)
asin = unary_op("asin", jnp.arcsin)
acos = unary_op("acos", jnp.arccos)
atan = unary_op("atan", jnp.arctan)
sinh = unary_op("sinh", jnp.sinh)
cosh = unary_op("cosh", jnp.cosh)
tanh = unary_op("tanh", jnp.tanh)
asinh = unary_op("asinh", jnp.arcsinh)
acosh = unary_op("acosh", jnp.arccosh)
atanh = unary_op("atanh", jnp.arctanh)
floor = unary_op("floor", jnp.floor)
ceil = unary_op("ceil", jnp.ceil)
round = unary_op("round", jnp.round)
trunc = unary_op("trunc", jnp.trunc)
frac = unary_op("frac", lambda a: a - jnp.trunc(a))
sign = unary_op("sign", jnp.sign)
sgn = sign
erf = unary_op("erf", jax.scipy.special.erf)
erfinv = unary_op("erfinv", jax.scipy.special.erfinv)
lgamma = unary_op("lgamma", jax.scipy.special.gammaln)
digamma = unary_op("digamma", jax.scipy.special.digamma)
i0 = unary_op("i0", jax.scipy.special.i0)
i0e = unary_op("i0e", jax.scipy.special.i0e)
i1 = unary_op("i1", jax.scipy.special.i1)
i1e = unary_op("i1e", jax.scipy.special.i1e)
angle = unary_op("angle", jnp.angle)
conj = unary_op("conj", jnp.conj)
real = unary_op("real", jnp.real)
imag = unary_op("imag", jnp.imag)
deg2rad = unary_op("deg2rad", jnp.deg2rad)
rad2deg = unary_op("rad2deg", jnp.rad2deg)


@defop("_identity")
def _identity(x, name=None):
    return apply_jfn("identity", lambda a: a, x)


@defop("scale")
def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    x = ensure_tensor(x)
    if bias_after_scale:
        out = apply_jfn("scale", lambda a: a * scale + bias, x)
    else:
        out = apply_jfn("scale", lambda a: (a + bias) * scale, x)
    if act:
        from . import activation

        out = getattr(activation, act)(out)
    return out


@defop("increment")
def increment(x, value=1.0, name=None):
    # non-differentiable in-place (used by counters/schedulers)
    x = ensure_tensor(x)
    x._value = x._value + value
    x._grad_node = None
    return x


@defop("clip")
def clip(x, min=None, max=None, name=None):
    from ..tensor_core import Tensor

    x = ensure_tensor(x)
    mn = min._value if isinstance(min, Tensor) else min
    mx = max._value if isinstance(max, Tensor) else max
    return apply_jfn("clip", lambda a: jnp.clip(a, mn, mx), x)


@defop("lerp")
def lerp(x, y, weight, name=None):
    from ..tensor_core import Tensor

    x, y = ensure_tensor(x), ensure_tensor(y)
    if isinstance(weight, Tensor):
        return engine.apply(
            "lerp", lambda a, b, w: a + w * (b - a), (x, y, weight)
        )
    return engine.apply("lerp", lambda a, b: a + weight * (b - a), (x, y))


@defop("addmm")
def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return engine.apply(
        "addmm",
        lambda i, a, b: beta * i + alpha * (a @ b),
        (ensure_tensor(input), ensure_tensor(x), ensure_tensor(y)),
    )


@defop("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_jfn("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


@defop("rsqrt_")
def rsqrt_(x, name=None):
    from . import _snapshot_for_inplace

    x = ensure_tensor(x)
    out = rsqrt(_snapshot_for_inplace(x, "rsqrt"))
    x._value = out._value
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    x.stop_gradient = out.stop_gradient
    return x


# ---- reductions ----
sum = reduce_op("sum", jnp.sum)
mean = reduce_op("mean", jnp.mean)
prod = reduce_op("prod", jnp.prod)
max = reduce_op("max", jnp.max)
min = reduce_op("min", jnp.min)
amax = max
amin = min
nansum = reduce_op("nansum", jnp.nansum)
nanmean = reduce_op("nanmean", jnp.nanmean)


@defop("std")
def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ddof = 1 if unbiased else 0
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "std", lambda a: jnp.std(a, axis=ax, ddof=ddof, keepdims=keepdim), x
    )


@defop("var")
def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    x = ensure_tensor(x)
    ddof = 1 if unbiased else 0
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "var", lambda a: jnp.var(a, axis=ax, ddof=ddof, keepdims=keepdim), x
    )


@defop("median")
def median(x, axis=None, keepdim=False, name=None):
    ax = axis
    return apply_jfn(
        "median", lambda a: jnp.median(a, axis=ax, keepdims=keepdim), ensure_tensor(x)
    )


@defop("quantile")
def quantile(x, q, axis=None, keepdim=False, name=None):
    return apply_jfn(
        "quantile",
        lambda a: jnp.quantile(a, q, axis=axis, keepdims=keepdim),
        ensure_tensor(x),
    )


@defop("logsumexp")
def logsumexp(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "logsumexp",
        lambda a: jax.scipy.special.logsumexp(a, axis=ax, keepdims=keepdim),
        ensure_tensor(x),
    )


@defop("argmax")
def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype)
    return apply_jfn(
        "argmax",
        lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(d),
        ensure_tensor(x),
    )


@defop("argmin")
def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    from ..core.dtype import convert_dtype

    d = convert_dtype(dtype)
    return apply_jfn(
        "argmin",
        lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(d),
        ensure_tensor(x),
    )


@defop("all")
def all(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "all", lambda a: jnp.all(a, axis=ax, keepdims=keepdim), ensure_tensor(x)
    )


@defop("any")
def any(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "any", lambda a: jnp.any(a, axis=ax, keepdims=keepdim), ensure_tensor(x)
    )


@defop("count_nonzero")
def count_nonzero(x, axis=None, keepdim=False, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return apply_jfn(
        "count_nonzero",
        lambda a: jnp.count_nonzero(a, axis=ax, keepdims=keepdim).astype(_i64()),
        ensure_tensor(x),
    )


# ---- cumulative ----
@defop("cumsum")
def cumsum(x, axis=None, dtype=None, name=None):
    x = ensure_tensor(x)
    if axis is None:
        return apply_jfn("cumsum", lambda a: jnp.cumsum(a.reshape(-1)), x)
    return apply_jfn("cumsum", lambda a: jnp.cumsum(a, axis=axis), x)


@defop("cumprod")
def cumprod(x, dim=None, dtype=None, name=None):
    return apply_jfn("cumprod", lambda a: jnp.cumprod(a, axis=dim), ensure_tensor(x))


def _cum_extreme(x, axis, dtype, pick_right, opname):
    """cummax/cummin returning (values, indices) like the reference
    (paddle/phi/kernels/cum_maxmin_kernel.h). Pair-valued associative scan:
    first-occurrence index wins ties."""
    from ..core.dtype import convert_dtype

    x = ensure_tensor(x)
    d = convert_dtype(dtype)
    ax = axis if axis is not None else 0

    def jfn(a):
        if axis is None:
            a = a.reshape(-1)
        n = a.shape[ax]
        shape = [1] * a.ndim
        shape[ax] = n
        iota = jnp.arange(n).reshape(shape)
        iota = jnp.broadcast_to(iota, a.shape)

        def combine(l, r):
            lv, li = l
            rv, ri = r
            take_r = pick_right(lv, rv)
            return (
                jnp.where(take_r, rv, lv),
                jnp.where(take_r, ri, li),
            )

        v, i = jax.lax.associative_scan(combine, (a, iota), axis=ax)
        return v, i.astype(d)

    return engine.apply(opname, jfn, (x,))


@defop("cummax")
def cummax(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, lambda lv, rv: rv > lv, "cummax")


@defop("cummin")
def cummin(x, axis=None, dtype="int64", name=None):
    return _cum_extreme(x, axis, dtype, lambda lv, rv: rv < lv, "cummin")


@defop("diff")
def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = None if prepend is None else ensure_tensor(prepend)._value
    app = None if append is None else ensure_tensor(append)._value
    return apply_jfn(
        "diff",
        lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app),
        ensure_tensor(x),
    )


@defop("trace")
def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_jfn(
        "trace", lambda a: jnp.trace(a, offset, axis1, axis2), ensure_tensor(x)
    )


@defop("kron")
def kron(x, y, name=None):
    return engine.apply("kron", jnp.kron, (ensure_tensor(x), ensure_tensor(y)))


@defop("inner")
def inner(x, y, name=None):
    return engine.apply("inner", jnp.inner, (ensure_tensor(x), ensure_tensor(y)))


@defop("outer")
def outer(x, y, name=None):
    return engine.apply("outer", jnp.outer, (ensure_tensor(x), ensure_tensor(y)))


# ---- comparison (non-differentiable outputs) ----
equal = binary_op("equal", jnp.equal)
not_equal = binary_op("not_equal", jnp.not_equal)
greater_than = binary_op("greater_than", jnp.greater)
greater_equal = binary_op("greater_equal", jnp.greater_equal)
less_than = binary_op("less_than", jnp.less)
less_equal = binary_op("less_equal", jnp.less_equal)


@defop("equal_all")
def equal_all(x, y, name=None):
    return engine.apply(
        "equal_all",
        lambda a, b: jnp.array_equal(a, b),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("allclose")
def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return engine.apply(
        "allclose",
        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (ensure_tensor(x), ensure_tensor(y)),
    )


@defop("isclose")
def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return engine.apply(
        "isclose",
        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan),
        (ensure_tensor(x), ensure_tensor(y)),
    )


isnan = unary_op("isnan", jnp.isnan)
isinf = unary_op("isinf", jnp.isinf)
isfinite = unary_op("isfinite", jnp.isfinite)

# ---- logical / bitwise ----
logical_and = binary_op("logical_and", jnp.logical_and)
logical_or = binary_op("logical_or", jnp.logical_or)
logical_xor = binary_op("logical_xor", jnp.logical_xor)
logical_not = unary_op("logical_not", jnp.logical_not)
bitwise_and = binary_op("bitwise_and", jnp.bitwise_and)
bitwise_or = binary_op("bitwise_or", jnp.bitwise_or)
bitwise_xor = binary_op("bitwise_xor", jnp.bitwise_xor)
bitwise_not = unary_op("bitwise_not", jnp.bitwise_not)
