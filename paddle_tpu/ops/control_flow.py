"""Control-flow ops: cond / while_loop / case / switch_case / scan.

TPU-native re-design of the reference control-flow operator family
(reference: python/paddle/fluid/layers/control_flow.py — cond:2352,
while_loop:1065, case:2983, switch_case:3212; C++ ops
paddle/fluid/operators/controlflow/conditional_block_op.cc,
while_op.cc).

The reference builds sub-blocks in a static program. Here the rule is
the dy2static rule the rest of the framework follows:

- **Eager** (concrete predicate): execute pythonically — run the taken
  branch / loop in Python. The tape sees exactly the ops that ran, so
  gradients work with zero extra machinery.
- **Traced** (predicate is a jax Tracer, i.e. inside paddle.jit): lower
  to `lax.cond` / `lax.while_loop` / `lax.switch` so the compiled
  program has real XLA control flow (single compilation, no unrolling,
  MXU-friendly static shapes). Reverse-mode gradient through a traced
  while_loop is undefined in XLA — use `scan` (which carries its
  residuals) for differentiable loops, as jax itself does.

`scan` has no reference counterpart: it is the TPU-first way to express
a differentiable fixed-length loop (reference RNN-style unrolled loops
map to it; see nn/layer/rnn.py which already scans).
"""
import jax
import jax.numpy as jnp

from ..tensor_core import Tensor
from ._helpers import defop, ensure_tensor, value_of

__all__ = ["cond", "while_loop", "case", "switch_case", "scan"]


def _is_tracer(v):
    return isinstance(v, jax.core.Tracer)


def _wrap_tree(vals, like=None):
    """jax values (possibly nested tuple/list) → Tensors, preserving
    structure."""
    if isinstance(vals, (tuple, list)):
        return type(vals)(_wrap_tree(v) for v in vals)
    if isinstance(vals, (jax.Array, jnp.ndarray)) or _is_tracer(vals):
        return Tensor(vals, stop_gradient=True)
    return vals


def _unwrap_tree(t):
    if isinstance(t, (tuple, list)):
        return type(t)(_unwrap_tree(v) for v in t)
    return t._value if isinstance(t, Tensor) else t


@defop("cond")
def cond(pred, true_fn=None, false_fn=None, name=None):
    """Run true_fn() or false_fn() depending on pred
    (reference control_flow.py:2352). Branch callables take no args and
    close over outer tensors, as in the reference."""
    pv = value_of(ensure_tensor(pred))
    if not _is_tracer(pv):
        if bool(pv):
            return true_fn() if true_fn is not None else None
        return false_fn() if false_fn is not None else None
    # traced: both branches staged into ONE program via lax.cond
    t_out = [None]

    def _t(_):
        out = true_fn() if true_fn is not None else ()
        t_out[0] = out
        return _unwrap_tree(out) if out is not None else ()

    def _f(_):
        out = false_fn() if false_fn is not None else ()
        return _unwrap_tree(out) if out is not None else ()

    res = jax.lax.cond(pv, _t, _f, operand=None)
    # restore the branch's python structure
    return _wrap_tree(res) if t_out[0] is not None else None


@defop("while_loop")
def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """while cond_fn(*vars): vars = body_fn(*vars)
    (reference control_flow.py:1065). Eager runs the python loop (tape
    gradients work); traced lowers to lax.while_loop (forward-only, as
    in XLA)."""
    probe = cond_fn(*loop_vars)
    pv = value_of(ensure_tensor(probe))
    if not _is_tracer(pv):
        vars_ = list(loop_vars)
        while bool(value_of(ensure_tensor(cond_fn(*vars_)))):
            out = body_fn(*vars_)
            vars_ = list(out) if isinstance(out, (tuple, list)) else [out]
        return vars_

    def _c(vals):
        return value_of(ensure_tensor(cond_fn(*_wrap_tree(tuple(vals)))))

    def _b(vals):
        out = body_fn(*_wrap_tree(tuple(vals)))
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return tuple(_unwrap_tree(o) for o in out)

    res = jax.lax.while_loop(_c, _b, tuple(_unwrap_tree(v)
                                           for v in loop_vars))
    return [Tensor(v, stop_gradient=True) for v in res]


@defop("case")
def case(pred_fn_pairs, default=None, name=None):
    """First predicate that holds wins (reference control_flow.py:2983).
    Eager: python scan over pairs. Traced: nested lax.cond chain."""
    # reference semantics: when default is None, the LAST pair's fn is the
    # fallback (control_flow.py:2983)
    if default is None:
        default = pred_fn_pairs[-1][1]
        pred_fn_pairs = pred_fn_pairs[:-1]
        if not pred_fn_pairs:
            return default()
    preds = [value_of(ensure_tensor(p)) for p, _ in pred_fn_pairs]
    if not any(_is_tracer(p) for p in preds):
        for pv, fn in zip(preds, (f for _, f in pred_fn_pairs)):
            if bool(pv):
                return fn()
        return default()

    def build(i):
        if i == len(pred_fn_pairs):
            return lambda: default()
        p, fn = pred_fn_pairs[i]
        rest = build(i + 1)
        return lambda: cond(p, fn, rest)

    return build(0)()


@defop("switch_case")
def switch_case(branch_index, branch_fns, default=None, name=None):
    """Select a branch by integer index (reference control_flow.py:3212).
    branch_fns: dict {index: fn} or list of (index, fn) / fns."""
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = sorted((int(i), f) for i, f in branch_fns)
    else:
        pairs = list(enumerate(branch_fns))
    iv = value_of(ensure_tensor(branch_index))
    if not _is_tracer(iv):
        i = int(iv)
        for idx, fn in pairs:
            if idx == i:
                return fn()
        # reference: unmatched index falls to default, else the LAST branch
        return default() if default is not None else pairs[-1][1]()
    fns = [f for _, f in pairs]
    if default is not None:
        fns.append(default)
    keys = jnp.asarray([i for i, _ in pairs])
    # map branch_index → position (unknown index → default = last)
    pos = jnp.argmax(keys == iv)
    pos = jnp.where(jnp.any(keys == iv), pos, len(fns) - 1)
    res = jax.lax.switch(
        pos, [(lambda f: lambda _: _unwrap_tree(f()))(f) for f in fns],
        None)
    return _wrap_tree(res)


def _closure_tensors(fn):
    """Trainable Tensors the body closes over — they must become explicit
    tape operands or their gradients are silently lost."""
    out = []
    f = getattr(fn, "__func__", fn)
    for cell in getattr(f, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, Tensor) and not v.stop_gradient:
            out.append(v)
    return out


@defop("scan")
def scan(fn, init, xs=None, length=None, reverse=False, params=None,
         name=None):
    """Differentiable loop with carried state: TPU-first replacement for
    unrolled python loops. fn(carry, x) -> (carry, y); returns
    (final_carry, stacked_ys). Funnels through the tape so backward
    works eagerly and under jit — including gradients for weights the
    body closes over (direct closure cells are captured automatically;
    pass `params=[...]` for tensors reached through nested structures)."""
    from ..autograd import engine

    init_t = ensure_tensor(init)
    tensors = [init_t]
    if xs is not None:
        tensors.append(ensure_tensor(xs))
    clos = list(params) if params is not None else _closure_tensors(fn)
    tensors += clos

    def jfn(*vals):
        n_fixed = 2 if xs is not None else 1
        clos_vals = vals[n_fixed:]
        originals = [t._value for t in clos]

        def body(c, x):
            # thread closure weights as traced values for the body's ops
            for t, v in zip(clos, clos_vals):
                t._value = v
            try:
                c_out, y = fn(Tensor(c, stop_gradient=True),
                              None if x is None else Tensor(x, True))
            finally:
                for t, v in zip(clos, originals):
                    t._value = v
            return _unwrap_tree(c_out), _unwrap_tree(y)

        if xs is None:
            c, ys = jax.lax.scan(lambda c, _: body(c, None), vals[0],
                                 None, length=length, reverse=reverse)
        else:
            c, ys = jax.lax.scan(body, vals[0], vals[1], reverse=reverse)
        return c, ys

    return engine.apply("scan", jfn, tuple(tensors))
