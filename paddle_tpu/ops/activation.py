"""Activation ops (paddle.nn.functional activations).

reference: paddle/fluid/operators/activation_op.cc + phi activation kernels
(paddle/phi/kernels/activation_kernel.h). One jax.nn call each; XLA fuses
them into surrounding matmuls.
"""
import jax
import jax.numpy as jnp

from ..autograd import engine
from ._helpers import apply_jfn, defop, ensure_tensor, unary_op

relu = unary_op("relu", jax.nn.relu)
relu6 = unary_op("relu6", jax.nn.relu6)
sigmoid = unary_op("sigmoid", jax.nn.sigmoid)
silu = unary_op("silu", jax.nn.silu)
swish = unary_op("swish", jax.nn.silu)
tanh = unary_op("tanh_act", jnp.tanh)
softplus_default = None
mish = unary_op("mish", lambda a: a * jnp.tanh(jax.nn.softplus(a)))
tanhshrink = unary_op("tanhshrink", lambda a: a - jnp.tanh(a))
softsign = unary_op("softsign", jax.nn.soft_sign)
log_sigmoid = unary_op("log_sigmoid", jax.nn.log_sigmoid)


@defop("gelu")
def gelu(x, approximate=False, name=None):
    return apply_jfn(
        "gelu", lambda a: jax.nn.gelu(a, approximate=approximate), ensure_tensor(x)
    )


@defop("leaky_relu")
def leaky_relu(x, negative_slope=0.01, name=None):
    return apply_jfn(
        "leaky_relu",
        lambda a: jax.nn.leaky_relu(a, negative_slope),
        ensure_tensor(x),
    )


@defop("elu")
def elu(x, alpha=1.0, name=None):
    return apply_jfn("elu", lambda a: jax.nn.elu(a, alpha), ensure_tensor(x))


@defop("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return apply_jfn(
        "selu",
        lambda a: scale * jnp.where(a > 0, a, alpha * jnp.expm1(a)),
        ensure_tensor(x),
    )


@defop("celu")
def celu(x, alpha=1.0, name=None):
    return apply_jfn("celu", lambda a: jax.nn.celu(a, alpha), ensure_tensor(x))


@defop("hardtanh")
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return apply_jfn(
        "hardtanh", lambda a: jnp.clip(a, min, max), ensure_tensor(x)
    )


@defop("hardsigmoid")
def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return apply_jfn(
        "hardsigmoid",
        lambda a: jnp.clip(slope * a + offset, 0.0, 1.0),
        ensure_tensor(x),
    )


@defop("hardswish")
def hardswish(x, name=None):
    return apply_jfn(
        "hardswish",
        lambda a: a * jnp.clip(a + 3.0, 0.0, 6.0) / 6.0,
        ensure_tensor(x),
    )


@defop("hardshrink")
def hardshrink(x, threshold=0.5, name=None):
    return apply_jfn(
        "hardshrink",
        lambda a: jnp.where(jnp.abs(a) > threshold, a, 0.0).astype(a.dtype),
        ensure_tensor(x),
    )


@defop("softshrink")
def softshrink(x, threshold=0.5, name=None):
    return apply_jfn(
        "softshrink",
        lambda a: jnp.sign(a) * jnp.maximum(jnp.abs(a) - threshold, 0.0),
        ensure_tensor(x),
    )


@defop("thresholded_relu")
def thresholded_relu(x, threshold=1.0, name=None):
    return apply_jfn(
        "thresholded_relu",
        lambda a: jnp.where(a > threshold, a, 0.0).astype(a.dtype),
        ensure_tensor(x),
    )


@defop("softplus")
def softplus(x, beta=1.0, threshold=20.0, name=None):
    def jfn(a):
        # double-where keeps the unselected exp branch finite so its vjp
        # contributes 0, not 0*inf=NaN
        big = beta * a > threshold
        safe = jnp.where(big, 0.0, beta * a)
        return jnp.where(big, a, (1.0 / beta) * jnp.log1p(jnp.exp(safe)))

    return apply_jfn("softplus", jfn, ensure_tensor(x))


@defop("prelu")
def prelu(x, weight, data_format="NCHW", name=None):
    x, weight = ensure_tensor(x), ensure_tensor(weight)

    def jfn(a, w):
        if w.size > 1 and a.ndim > 1:
            shape = [1] * a.ndim
            ch_axis = 1 if data_format == "NCHW" else a.ndim - 1
            shape[ch_axis] = w.size
            w = w.reshape(shape)
        return jnp.where(a > 0, a, w * a)

    return engine.apply("prelu", jfn, (x, weight))


@defop("rrelu")
def rrelu(x, lower=0.125, upper=0.3333333, training=True, name=None):
    from ..core import rng

    x = ensure_tensor(x)
    if training:
        k = rng.next_key()

        def jfn(a):
            r = jax.random.uniform(k, a.shape, a.dtype, lower, upper)
            return jnp.where(a >= 0, a, r * a)

        return apply_jfn("rrelu", jfn, x)
    mid = (lower + upper) / 2.0
    return apply_jfn("rrelu", lambda a: jnp.where(a >= 0, a, mid * a), x)


@defop("softmax")
def softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply_jfn("softmax", lambda a: jax.nn.softmax(a, axis=axis), x)


@defop("log_softmax")
def log_softmax(x, axis=-1, dtype=None, name=None):
    x = ensure_tensor(x)
    if dtype is not None:
        x = x.astype(dtype)
    return apply_jfn(
        "log_softmax", lambda a: jax.nn.log_softmax(a, axis=axis), x
    )


@defop("gumbel_softmax")
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ..core import rng

    x = ensure_tensor(x)
    k = rng.next_key()

    def jfn(a):
        g = -jnp.log(-jnp.log(
            jax.random.uniform(k, a.shape, a.dtype, 1e-20, 1.0)
        ))
        y = jax.nn.softmax((a + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = jnp.zeros_like(y)
            onehot = jnp.put_along_axis(
                onehot, idx, 1.0, axis=axis, inplace=False
            ) if hasattr(jnp, "put_along_axis") else jnp.take_along_axis(
                jnp.eye(y.shape[axis], dtype=y.dtype), idx, 0
            )
            y = jax.lax.stop_gradient(onehot - y) + y
        return y

    return apply_jfn("gumbel_softmax", jfn, x)


@defop("maxout")
def maxout(x, groups, axis=1, name=None):
    x = ensure_tensor(x)

    def jfn(a):
        shp = list(a.shape)
        c = shp[axis]
        shp[axis:axis + 1] = [c // groups, groups]
        return jnp.max(a.reshape(shp), axis=axis + 1)

    return apply_jfn("maxout", jfn, x)


@defop("glu")
def glu(x, axis=-1, name=None):
    return apply_jfn("glu", lambda a: jax.nn.glu(a, axis=axis), ensure_tensor(x))
