"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

NEW capability (SURVEY.md §5.7: the reference has NO sequence parallelism —
its long-context levers are recompute + fused attention). Designed
TPU-first per SURVEY.md §7: the sequence axis is sharded over the 'sp'
mesh axis; ring attention rotates K/V blocks around the ring with
`lax.ppermute` (neighbor exchange rides ICI) while each step's partial
attention merges via streaming log-sum-exp (the flash-attention recurrence
across devices). Ulysses instead all-to-alls heads↔sequence so each device
runs full-sequence attention on a head slice.

Both functions are pure jax, written to run INSIDE an SPMD program
(shard_map over 'sp', e.g. from DistributedTrainStep with a seq-sharded
batch spec) — collectives compile into the step.
"""
import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["ring_attention", "ring_flash_attention", "ulysses_attention",
           "RingAttention"]


def _online_merge(acc, m, l, scores, v_blk):
    """Streaming-softmax block merge (flash recurrence).

    acc: [b,h,sq,d] weighted value accumulator
    m:   [b,h,sq]  running max
    l:   [b,h,sq]  running sum of exp
    scores: [b,h,sq,sk] this block's logits
    """
    blk_max = scores.max(axis=-1)
    new_m = jnp.maximum(m, blk_max)
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m[..., None])
    new_l = l * correction + p.sum(axis=-1)
    new_acc = acc * correction[..., None] + jnp.einsum(
        "bhqk,bkhd->bhqd", p, v_blk)
    return new_acc, new_m, new_l


def ring_attention(q, k, v, causal=False, axis_name="sp"):
    """Attention over a sequence sharded along `axis_name`.

    q, k, v: [batch, seq_local, heads, head_dim] (local shard).
    Returns [batch, seq_local, heads, head_dim].
    """
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    scale = 1.0 / math.sqrt(d)

    qt = jnp.swapaxes(q, 1, 2)  # b,h,sq,d
    acc = jnp.zeros((b, h, s_loc, d), jnp.float32)
    # running max starts at the finite mask floor, NOT -inf: -inf
    # intermediates make exp(m - new_m) an inf-minus-inf shape that
    # XLA's algebraic simplifier can rewrite into 0·inf NaNs under some
    # fusion layouts (observed on XLA:CPU with traced label operands —
    # the de-optimized program was NaN-free while the jitted one NaN'd).
    # The ring starts on the diagonal block, where every causal row has
    # at least one valid key, so the -1e30 floor never wins a max it
    # shouldn't.
    m = jnp.full((b, h, s_loc), -1e30, jnp.float32)
    l = jnp.zeros((b, h, s_loc), jnp.float32)

    q_pos = idx * s_loc + jnp.arange(s_loc)

    k_blk, v_blk = k, v
    # static ring loop (sp is a compile-time mesh size)
    for r in range(sp):
        src = (idx - r) % sp  # whose K/V block we currently hold
        scores = jnp.einsum("bhqd,bkhd->bhqk", qt, k_blk).astype(
            jnp.float32) * scale
        if causal:
            # mask directly to the finite floor (never -inf; see the
            # running-max init note above): exp underflows to 0 for
            # masked keys once any valid key sets the row max
            k_pos = src * s_loc + jnp.arange(s_loc)
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -1e30)
        acc, m, l = _online_merge(acc, m, l, scores, v_blk)
        if r != sp - 1:
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_flash_attention(q, k, v, causal=False, axis_name="sp",
                         block_q=None, block_k=None, interpret=False):
    """Ring attention with the Pallas flash kernel per K/V block.

    Unlike `ring_attention` (dense per-block scores in HBM, all blocks
    computed then masked), each ring step runs the flash kernel on the
    resident K/V shard — scores never touch HBM — and returns
    (out, lse); blocks are merged by streaming-softmax over lse. Under
    causal masking, blocks strictly above the diagonal are SKIPPED via
    lax.cond (the dense version burned ~half the FLOPs computing them):
    src == idx runs the kernel causal, src < idx runs it full, src > idx
    contributes nothing. Differentiable end-to-end: the kernel's lse
    output carries a custom-vjp cotangent (flash_attention_lse_bhd), the
    merge is plain jnp.

    q, k, v: [batch, seq_local, heads, head_dim]. Same contract as
    ring_attention.
    """
    from ..ops.pallas_kernels.flash_attention import (
        DEFAULT_BLOCK_K, DEFAULT_BLOCK_Q, flash_attention_lse_bhd)

    import jax

    block_q = block_q or DEFAULT_BLOCK_Q
    block_k = block_k or DEFAULT_BLOCK_K
    if not interpret and jax.default_backend() != "tpu":
        interpret = True  # CPU test tier runs the Pallas interpreter
    sp = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape

    def to_bhd(t):
        return jnp.swapaxes(t, 1, 2).reshape(b * h, s_loc, d)

    qb = to_bhd(q)
    k_blk, v_blk = to_bhd(k), to_bhd(v)
    m = jnp.full((b * h, s_loc), -1e30, jnp.float32)   # running lse max
    num = jnp.zeros((b * h, s_loc, d), jnp.float32)
    den = jnp.zeros((b * h, s_loc), jnp.float32)

    def _blk(is_causal):
        def run(qq, kk, vv):
            o, l = flash_attention_lse_bhd(qq, kk, vv, is_causal,
                                           block_q, block_k, interpret)
            return o.astype(jnp.float32), l[:, 0, :]

        return run

    def _skip(qq, kk, vv):
        return (jnp.zeros((b * h, s_loc, d), jnp.float32),
                jnp.full((b * h, s_loc), -1e30, jnp.float32))

    for r in range(sp):
        src = (idx - r) % sp   # whose K/V block we currently hold
        if causal:
            o_blk, lse_blk = lax.cond(
                src == idx, _blk(True),
                lambda qq, kk, vv: lax.cond(
                    src < idx, _blk(False), _skip, qq, kk, vv),
                qb, k_blk, v_blk)
        else:
            o_blk, lse_blk = _blk(False)(qb, k_blk, v_blk)
        m_new = jnp.maximum(m, lse_blk)
        scale_old = jnp.exp(m - m_new)
        scale_blk = jnp.exp(lse_blk - m_new)
        num = num * scale_old[..., None] + o_blk * scale_blk[..., None]
        den = den * scale_old + scale_blk
        m = m_new
        if r != sp - 1:
            perm = [(i, (i + 1) % sp) for i in range(sp)]
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)

    out = num / jnp.maximum(den, 1e-30)[..., None]
    return jnp.swapaxes(out.reshape(b, h, s_loc, d), 1, 2).astype(q.dtype)


def ulysses_attention(q, k, v, causal=False, axis_name="sp"):
    """DeepSpeed-Ulysses style: all-to-all so each device holds ALL the
    sequence for heads/sp heads, runs dense attention, then scatters back.
    Requires heads % sp == 0."""
    sp = lax.axis_size(axis_name)
    b, s_loc, h, d = q.shape
    if h % sp != 0:
        raise ValueError(f"heads {h} not divisible by sp degree {sp}")

    def seq2head(x):
        # [b, s_loc, h, d] -> [b, s_loc*sp, h/sp, d]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(x):
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    s_full = qg.shape[1]
    scale = 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", qg, kg).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((s_full, s_full), bool))
        scores = jnp.where(mask[None, None], scores, -1e30)
    w = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", w.astype(vg.dtype), vg)
    return head2seq(out)


class RingAttention:
    """Layer-ish wrapper selecting ring vs ulysses (API surface for model
    code; call inside SPMD programs)."""

    def __init__(self, mode="ring", causal=True, axis_name="sp"):
        self.mode = mode
        self.causal = causal
        self.axis_name = axis_name

    def __call__(self, q, k, v):
        fn = {"ring": ring_attention,
              "ring_flash": ring_flash_attention,
              "ulysses": ulysses_attention}[self.mode]
        return fn(q, k, v, causal=self.causal, axis_name=self.axis_name)
