"""Sharded, asynchronous, atomic checkpointing.

TPU-native re-design of the reference checkpoint stack (reference:
python/paddle/framework/io.py:574 `paddle.save`, :791 `paddle.load`;
sharded gathering in fleet/meta_parallel/sharding/group_sharded_stage3.py:60
state_dict; auto-checkpoint fleet/utils/fs.py + incubate checkpoint).

Key differences from the reference design:

- **No gather on save.** The reference's stage-3 `state_dict()` all-gathers
  full params onto rank 0 before writing. Here every process writes only
  its *addressable* array shards (`Array.addressable_shards`), so a ZeRO-3
  / TP-sharded model checkpoints with zero cross-device traffic.
- **Async by construction.** A save is two phases. The synchronous
  SNAPSHOT phase runs on the step path: per-shard `copy_to_host_async`,
  host-buffer materialization, and ALL cross-rank coordination
  (barriers) — so the step can keep donating its buffers the moment it
  returns. The COMMIT phase (durable write → fsync → atomic rename) runs
  on a background thread with `async_save=True` and issues ZERO
  collectives: cross-rank completion is coordinated through per-rank
  ``DONE.<rank>`` marker files in the tmp dir (the `fleet/elastic`
  heartbeat file-protocol style), never through the coordination KV or
  XLA collectives — a writer-thread collective would race whatever the
  main thread dispatches meanwhile (mismatched programs → hang), which
  is exactly why the old design force-downgraded multi-process saves to
  synchronous. A second save issued while a commit is in flight
  back-pressures (joins the in-flight commit, journaled
  ``ckpt_backpressure``, counted into ``pt_ckpt_step_stall_seconds``).
- **Atomic commit.** Everything is written into `<dir>.tmp`; each rank
  drops its ``DONE.<rank>`` marker only after its shards + meta fragment
  are durable, and the rename into place happens exactly once (leader
  elected by ``COMMIT_LEADER`` O_EXCL) only after EVERY rank's marker is
  present — a killed job, or one killed rank, never leaves a
  half-checkpoint that `load_latest` would pick up. `is_complete`
  re-verifies the marker set against the ``commit.world`` recorded in
  meta.json, so even a hand-mutilated directory missing one rank's
  marker stays invisible (`pt_ckpt_incomplete_discarded_total`).

Layout::

    ckpt-000042/
      meta.json            # commit record: leaf table + commit.world
      DONE.<r>             # per-rank commit markers (all present by
                           # construction once meta.json is visible)
      shards/<leaf>#<k>.npy

Multi-controller jobs: each process writes its own shard files plus a
``meta.rank<r>.json`` fragment into the SHARED checkpoint filesystem;
the elected leader merges fragments and renames. Chaos scopes
``ckpt.snapshot`` / ``ckpt.commit`` / ``ckpt.commit.<rank>`` /
``ckpt.kill_window`` target the phases deterministically
(docs/RESILIENCE.md).
"""
import hashlib
import json
import os
import shutil
import threading
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from ..observability import metrics as _obs
from ..observability import steptrace as _steptrace
from ..observability.tracing import trace_span as _trace_span
from ..tensor_core import Tensor
from . import chaos
from .resilience import RetryPolicy, record

# telemetry (docs/OBSERVABILITY.md): durations, bytes moved, and the
# torn-checkpoint fallbacks that tell an operator a filesystem is
# eating commits
_SAVE_SECONDS = _obs.histogram("pt_ckpt_save_seconds",
                               "save_state_dict wall time")
_LOAD_SECONDS = _obs.histogram("pt_ckpt_load_seconds",
                               "load_state_dict wall time")
_BYTES_TOTAL = _obs.counter("pt_ckpt_bytes_total",
                            "checkpoint bytes, by direction",
                            labelnames=("direction",))
_OPS_TOTAL = _obs.counter("pt_ckpt_ops_total",
                          "completed checkpoint operations",
                          labelnames=("op",))
_TORN_FALLBACKS = _obs.counter(
    "pt_ckpt_torn_fallbacks_total",
    "torn checkpoints skipped by load_latest's older-checkpoint "
    "fallback")
_STALL_SECONDS = _obs.histogram(
    "pt_ckpt_step_stall_seconds",
    "time the training step path actually blocked on a save "
    "(back-pressure + snapshot phase; the commit runs off the step "
    "path under async_save)")
_COMMIT_SECONDS = _obs.histogram(
    "pt_ckpt_commit_seconds",
    "background COMMIT phase wall time (durable shard write -> rename "
    "visible)")
_INFLIGHT = _obs.gauge(
    "pt_ckpt_inflight", "checkpoint commits currently in flight")
_INCOMPLETE_DISCARDED = _obs.counter(
    "pt_ckpt_incomplete_discarded_total",
    "checkpoint dirs rejected because a rank's DONE commit marker is "
    "missing (counted once per directory per process)")

__all__ = ["save_state_dict", "load_state_dict", "Checkpointer",
           "verify_integrity", "TornCheckpointError"]


class TornCheckpointError(ValueError):
    """A checkpoint failed its meta.json integrity check (truncated or
    missing shards behind a committed meta). Distinct from the plain
    ValueError a model/optimizer structure mismatch raises, so
    load_latest's older-checkpoint fallback can never swallow the
    latter and silently restart a run from step 0."""


_META = "meta.json"

# two-phase commit protocol files (inside <path>.tmp): per-rank DONE
# markers + the leader-election lock for the final rename
_DONE_PREFIX = "DONE."
_LEADER = "COMMIT_LEADER"
# commit-phase marker-wait budget: bounded so a rank SIGKILLed before
# its marker can never wedge a surviving writer thread forever (the
# elastic layer restarts the pod long before this fires in practice)
_COMMIT_TIMEOUT_S = float(os.environ.get("PT_CKPT_COMMIT_TIMEOUT_S",
                                         "600"))
_POLL_S = 0.01

# Durability: fsync shard files, meta.json and the directories before the
# .tmp rename — without it a host crash right AFTER the rename can still
# lose the commit record (data in the page cache, rename journaled
# first). PT_CKPT_FSYNC=0 opts out (e.g. throwaway tmpfs test runs).
_FSYNC = os.environ.get("PT_CKPT_FSYNC", "1") != "0"


def _fsync_dir(path):
    if not _FSYNC:
        return
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


# ---------------------------------------------------------------- flatten

def _flatten(obj, path=(), list_paths=None):
    """Nested dict/list → [(path_tuple, leaf)]. Leaves: Tensor/jax/np
    arrays or JSON-able scalars. `list_paths` (a set, when given) records
    paths of list/tuple nodes so load can restore them as lists."""
    if isinstance(obj, dict):
        if not obj:
            return [(path, _EMPTY_DICT)]
        out = []
        for k, v in obj.items():
            out += _flatten(v, path + (str(k),), list_paths)
        return out
    if isinstance(obj, (list, tuple)) and not _is_leaf(obj):
        if list_paths is not None:
            list_paths.add("/".join(path))
        if not obj:
            return [(path, _EMPTY_LIST)]
        out = []
        for i, v in enumerate(obj):
            out += _flatten(v, path + (str(i),), list_paths)
        return out
    return [(path, obj)]


class _Sentinel:
    def __init__(self, tag):
        self.tag = tag


_EMPTY_DICT = _Sentinel("__empty_dict__")
_EMPTY_LIST = _Sentinel("__empty_list__")


def _is_leaf(obj):
    return isinstance(obj, (Tensor, jax.Array, np.ndarray, str, bytes,
                            int, float, bool, type(None)))


def _leaf_name(path):
    tail = "_".join(path[-2:]) if path else "leaf"
    safe = "".join(c if c.isalnum() or c in "._-" else "-" for c in tail)
    return f"{safe}.{hashlib.sha1('/'.join(path).encode()).hexdigest()[:10]}"


def _nest(flat, list_paths=()):
    """[(path, value)] → nested dicts; nodes recorded in `list_paths`
    (saved-side list/tuple containers, e.g. an LR scheduler's milestones)
    come back as lists ordered by integer key."""
    root = {}
    for path, v in flat:
        d = root
        for k in path[:-1]:
            d = d.setdefault(k, {})
        d[path[-1]] = v

    def _relist(node, path):
        if not isinstance(node, dict):
            return node
        out = {k: _relist(v, path + (k,)) for k, v in node.items()}
        if "/".join(path) in list_paths:
            return [out[k] for k in sorted(out, key=int)]
        return out

    return _relist(root, ())


_SAFE_NPY = {"float64", "float32", "float16", "int64", "int32", "int16",
             "int8", "uint8", "uint16", "uint32", "uint64", "bool"}
_VIEW_FOR_SIZE = {1: np.uint8, 2: np.uint16, 4: np.uint32, 8: np.uint64}


def _to_storage(nparr):
    """(storage_array, logical_dtype_str). bf16/fp8 etc. are stored as
    same-itemsize uints — npy would silently degrade them to void."""
    dt = str(nparr.dtype)
    if dt in _SAFE_NPY:
        return nparr, dt
    view = _VIEW_FOR_SIZE[nparr.dtype.itemsize]
    return nparr.view(view), dt


def _from_storage(nparr, logical_dtype):
    if str(nparr.dtype) == logical_dtype:
        return nparr
    return nparr.view(np.dtype(logical_dtype))  # ml_dtypes registers bf16 etc.


# ------------------------------------------------------------------- save

def _proc_index():
    try:
        return jax.process_index(), jax.process_count()
    except Exception:
        return 0, 1


# back-pressure: commits in flight in this process. A new save joins
# them before snapshotting (two concurrent commits to sibling dirs are
# safe, but unbounded pile-up under a slow filesystem would eat host
# RAM one full host-snapshot per lap) — the join time is step-path
# stall and is counted into pt_ckpt_step_stall_seconds.
_inflight_lock = threading.Lock()
_inflight = []


def _join_inflight():
    with _inflight_lock:
        handles = [h for h in _inflight if h.is_alive()]
    for h in handles:
        h.join()
    return bool(handles)


def save_state_dict(state, path, async_save=False, _stall_start=None):
    """Write `state` (nested dict of Tensors / arrays / scalars) to
    directory `path`. Every process saves only its addressable shards.

    The SNAPSHOT phase (everything up to the returned handle: D2H
    copies, host materialization, cross-rank barriers) is synchronous —
    after it, the caller may donate/overwrite every saved buffer. With
    async_save=True the COMMIT phase (durable write + marker protocol +
    rename) runs on a background thread and issues no collectives; this
    is safe at any process count. Returns a handle with .result()
    (joins the committer; re-raises errors); with async_save=False the
    checkpoint is complete and visible on return."""
    t_stall0 = _time.perf_counter() if _stall_start is None \
        else _stall_start
    if _join_inflight():
        record("ckpt_backpressure", path=path)
    rank, nproc = _proc_index()
    tmp = path + ".tmp"
    if rank == 0:
        if os.path.isdir(tmp):
            shutil.rmtree(tmp)
        os.makedirs(os.path.join(tmp, "shards"), exist_ok=True)
    if nproc > 1:
        from . import xproc

        xproc.barrier()  # tmp dir exists before anyone writes
        os.makedirs(os.path.join(tmp, "shards"), exist_ok=True)

    leaves, scalars, pending = [], {}, []
    list_paths, bytes_paths = set(), []
    empties = {}
    for p, leaf in _flatten(state, list_paths=list_paths):
        if any("/" in comp for comp in p):
            raise ValueError(
                f"state dict key {p!r} contains '/', which is the path "
                "separator — rename the key")
        key = "/".join(p)
        if isinstance(leaf, Tensor):
            leaf = leaf._value
        if isinstance(leaf, _Sentinel):
            empties[key] = leaf.tag
            continue
        if isinstance(leaf, np.generic):  # numpy scalar → python scalar
            leaf = leaf.item()
        if isinstance(leaf, (jax.Array, np.ndarray)):
            arr = leaf if isinstance(leaf, jax.Array) else jnp.asarray(leaf)
            entry = {"path": key, "shape": list(arr.shape),
                     "dtype": str(arr.dtype), "shards": []}
            base = _leaf_name(p)
            for k, sh in enumerate(arr.addressable_shards):
                if sh.replica_id != 0:
                    continue
                idx = [[(s.start or 0),
                        (s.stop if s.stop is not None else dim)]
                       for s, dim in zip(sh.index, arr.shape)]
                fname = f"{base}#r{rank}s{k}.npy"
                entry["shards"].append({"index": idx, "file": fname})
                try:
                    sh.data.copy_to_host_async()
                except Exception:  # ptlint: disable=PTL804 (prefetch hint; the sync copy path follows)
                    pass
                pending.append((os.path.join(tmp, "shards", fname), sh.data))
            leaves.append(entry)
        else:
            if isinstance(leaf, bytes):
                bytes_paths.append(key)
                leaf = leaf.decode("latin1")
            scalars[key] = leaf

    # Snapshot to host NOW, on the step path: compiled steps donate
    # param/opt buffers, so a device array held past this call may be
    # deleted — or updated IN PLACE — under the committer thread.
    # copy_to_host_async above pipelined the D2H transfers; this loop
    # mostly just collects them. On CPU backends np.asarray of a device
    # array is ZERO-COPY (the ISSUE-11 aliasing lesson): the "snapshot"
    # would be a live view of a donated buffer, and the overlapped
    # commit would serialize bytes the next train step is mutating —
    # force an owned host copy whenever the array aliases foreign
    # memory (`base is not None`; a real D2H transfer owns its buffer
    # and costs nothing extra here). Only durable file I/O is deferred
    # to the commit phase.
    def _own(dev_arr):
        host = np.asarray(dev_arr)
        return host.copy() if host.base is not None else host

    pending = [(fpath, _own(dev_arr)) for fpath, dev_arr in pending]
    # scope contract (chaos.py table): fires AFTER host materialization,
    # BEFORE the commit hand-off — the captured-but-uncommitted window
    chaos.fire("ckpt.snapshot")
    if nproc > 1:
        from . import xproc

        # every rank snapshotted — the LAST collective of this save;
        # the commit phase coordinates through marker files only
        xproc.barrier()

    committer = _Committer(
        tmp=tmp, path=path, rank=rank, nproc=nproc, pending=pending,
        leaves=leaves, scalars=scalars, lists=sorted(list_paths),
        bytes_paths=bytes_paths, empties=empties, t_start=t_stall0)
    if async_save:
        h = _AsyncHandle(committer.run)
        with _inflight_lock:
            _inflight.append(h)
        h.start()
        _STALL_SECONDS.observe(_time.perf_counter() - t_stall0)
        return h
    committer.run()
    # synchronous saves stall the step path for the whole commit — that
    # asymmetry IS the overlapped-checkpointing win the bench
    # ckpt_overlap_ab stamp measures. Observed on SUCCESS only: under
    # the Checkpointer retry policy each attempt re-enters with the
    # original _stall_start, so a per-attempt (finally) observation
    # would double-count the same logical save
    _STALL_SECONDS.observe(_time.perf_counter() - t_stall0)
    return _DoneHandle()


class _Committer:  # ptlint: thread-shared
    """The background COMMIT phase of one save: durable shard writes,
    the per-rank DONE marker protocol, and the leader-elected atomic
    rename. Runs on the caller thread (sync) or an _AsyncHandle thread
    (async). INVARIANT: no collectives and no coordination-KV traffic
    here, ever — a commit-thread collective would interleave with
    whatever program the main thread dispatches concurrently and hang
    the pod (the documented race that used to force multi-process
    saves synchronous). Cross-rank coordination is marker files on the
    shared checkpoint filesystem only."""

    def __init__(self, tmp, path, rank, nproc, pending, leaves, scalars,
                 lists, bytes_paths, empties, t_start):
        self.tmp = tmp
        self.path = path
        self.rank = rank
        self.nproc = nproc
        self.pending = pending
        self.leaves = leaves
        self.scalars = scalars
        self.lists = lists
        self.bytes_paths = bytes_paths
        self.empties = empties
        self.t_start = t_start

    def run(self):
        t0 = _time.perf_counter()
        _INFLIGHT.inc()
        try:
            with _trace_span("ckpt.save", path=self.path):
                self._commit_phase()
            _OPS_TOTAL.labels(op="save").inc()
            # duration from the CALLER's save start: includes snapshot
            # + any back-pressure, so async and sync report comparably
            _SAVE_SECONDS.observe(_time.perf_counter() - self.t_start)
        finally:
            _INFLIGHT.dec()
            _COMMIT_SECONDS.observe(_time.perf_counter() - t0)

    def _commit_phase(self):
        # deterministic chaos targets for the new phase (counted like
        # every scope: nth call of this scope on this rank)
        chaos.fire("ckpt.commit")
        chaos.fire(f"ckpt.commit.{self.rank}")
        n_bytes = 0
        for fpath, host_arr in self.pending:
            storage, _ = _to_storage(host_arr)
            n_bytes += storage.nbytes
            with open(fpath, "wb") as f:
                np.save(f, storage)
                if _FSYNC:
                    f.flush()
                    os.fsync(f.fileno())
        if self.nproc > 1:
            frag = {"leaves": self.leaves, "scalars": self.scalars,
                    "lists": self.lists, "bytes": self.bytes_paths,
                    "empties": self.empties}
            with open(os.path.join(self.tmp,
                                   f"meta.rank{self.rank}.json"),
                      "w") as f:
                json.dump(frag, f)
                if _FSYNC:
                    f.flush()
                    os.fsync(f.fileno())
        # THE torn-commit window: this rank's shards are on disk, its
        # commit marker is not — a kill here leaves the marker set
        # incomplete, so no rank can ever rename the tmp visible
        chaos.fire("ckpt.kill_window")
        marker = os.path.join(self.tmp, f"{_DONE_PREFIX}{self.rank}")
        with open(marker, "w") as f:
            f.write(str(_time.time()))
            if _FSYNC:
                f.flush()
                os.fsync(f.fileno())
        _fsync_dir(os.path.join(self.tmp, "shards"))
        _fsync_dir(self.tmp)
        self._await_markers()
        if self._claim_leader():
            self._finalize()
        self._await_visible()
        _BYTES_TOTAL.labels(direction="saved").inc(n_bytes)

    def _deadline(self):
        return _time.monotonic() + _COMMIT_TIMEOUT_S

    def _visible(self):
        """The rename happened: tmp is gone (a peer — or this rank —
        published the checkpoint)."""
        return not os.path.isdir(self.tmp)

    def _await_markers(self):
        deadline = self._deadline()
        while True:
            if self._visible():
                return
            if all(os.path.exists(
                    os.path.join(self.tmp, f"{_DONE_PREFIX}{r}"))
                    for r in range(self.nproc)):
                return
            if _time.monotonic() > deadline:
                record("ckpt_commit_timeout", path=self.path,
                       phase="markers", rank=self.rank)
                raise TimeoutError(
                    f"ckpt commit {self.path}: not every rank's "
                    f"{_DONE_PREFIX}<r> marker appeared within "
                    f"{_COMMIT_TIMEOUT_S:.0f}s — a peer likely died "
                    "mid-commit; this checkpoint stays invisible and "
                    "load_latest falls back to the previous one")
            _time.sleep(_POLL_S)

    def _claim_leader(self):
        """Exactly-once rename election: O_CREAT|O_EXCL on the shared
        lock file. Claimed only after every marker is present, so the
        leader is guaranteed to see all fragments."""
        try:
            fd = os.open(os.path.join(self.tmp, _LEADER),
                         os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except FileNotFoundError:
            return False        # a peer already renamed tmp away
        os.close(fd)
        return True

    def _finalize(self):
        if self.nproc > 1:
            seen_scalars, by_path, empt = {}, {}, {}
            lists, byts = set(), set()
            for r in range(self.nproc):
                with open(os.path.join(
                        self.tmp, f"meta.rank{r}.json")) as f:
                    fr = json.load(f)
                seen_scalars.update(fr["scalars"])
                lists.update(fr["lists"])
                byts.update(fr["bytes"])
                empt.update(fr.get("empties", {}))
                for e in fr["leaves"]:
                    tgt = by_path.setdefault(e["path"], e)
                    if tgt is not e:
                        tgt["shards"] += e["shards"]
            _commit(self.tmp, self.path, list(by_path.values()),
                    seen_scalars, sorted(lists), sorted(byts), empt,
                    world=self.nproc)
        else:
            _commit(self.tmp, self.path, self.leaves, self.scalars,
                    self.lists, self.bytes_paths, self.empties,
                    world=1)

    def _await_visible(self):
        deadline = self._deadline()
        while not self._visible():
            if _time.monotonic() > deadline:
                record("ckpt_commit_timeout", path=self.path,
                       phase="rename", rank=self.rank)
                raise TimeoutError(
                    f"ckpt commit {self.path}: the elected leader never "
                    f"published the rename within "
                    f"{_COMMIT_TIMEOUT_S:.0f}s")
            _time.sleep(_POLL_S)


def _commit(tmp, path, leaves, scalars, list_paths=(), bytes_paths=(),
            empties=None, world=1):
    # integrity record: leaf count + per-shard byte size, so load can
    # reject a torn checkpoint (shard truncated/missing despite a
    # committed meta.json) instead of half-loading it. `commit.world`
    # records how many DONE.<r> markers is_complete must re-verify.
    shard_sizes = {}
    for e in leaves:
        for srec in e["shards"]:
            shard_sizes[srec["file"]] = os.path.getsize(
                os.path.join(tmp, "shards", srec["file"]))
    with open(os.path.join(tmp, _META), "w") as f:
        json.dump({"leaves": leaves, "scalars": scalars,
                   "lists": list(list_paths),
                   "bytes": list(bytes_paths),
                   "empties": empties or {},
                   "commit": {"world": int(world)},
                   "integrity": {"leaf_count": len(leaves),
                                 "shards": shard_sizes}}, f)
        if _FSYNC:
            f.flush()
            os.fsync(f.fileno())
    # directory entries (shard files + meta.json) durable BEFORE the
    # rename publishes them
    _fsync_dir(os.path.join(tmp, "shards"))
    _fsync_dir(tmp)
    if os.path.isdir(path):
        shutil.rmtree(path)
    os.replace(tmp, path)
    # the rename itself durable: fsync the parent directory
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def verify_integrity(path):
    """Validate a checkpoint directory against its meta.json integrity
    record (leaf count + per-shard byte sizes). Raises
    TornCheckpointError on a torn checkpoint; checkpoints written before
    the integrity record pass (nothing to check). Returns the parsed
    meta."""
    try:
        with open(os.path.join(path, _META)) as f:
            meta = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        # a truncated/garbled meta.json (host crash with PT_CKPT_FSYNC=0,
        # or a pre-fsync checkpoint) is a torn checkpoint, not a caller
        # bug — classify it so load_latest falls back to the next-older
        # complete checkpoint instead of crashing the resume
        raise TornCheckpointError(
            f"torn checkpoint {path}: unreadable {_META}: {e}") from e
    integ = meta.get("integrity")
    if integ is None:
        return meta
    if len(meta["leaves"]) != integ["leaf_count"]:
        raise TornCheckpointError(
            f"torn checkpoint {path}: meta lists {len(meta['leaves'])} "
            f"leaves, integrity record expects {integ['leaf_count']}")
    sizes = integ["shards"]
    for e in meta["leaves"]:
        for srec in e["shards"]:
            fname = srec["file"]
            if fname not in sizes:
                raise TornCheckpointError(
                    f"torn checkpoint {path}: shard {fname} missing "
                    "from integrity record")
            fpath = os.path.join(path, "shards", fname)
            try:
                actual = os.path.getsize(fpath)
            except OSError:
                raise TornCheckpointError(
                    f"torn checkpoint {path}: shard {fname} missing")
            if actual != sizes[fname]:
                raise TornCheckpointError(
                    f"torn checkpoint {path}: shard {fname} is {actual} "
                    f"bytes, committed as {sizes[fname]}")
    return meta


class _AsyncHandle(threading.Thread):
    def __init__(self, fn):
        super().__init__(daemon=True)
        self._fn = fn
        self._err = None

    def run(self):
        try:
            self._fn()
        except BaseException as e:  # surfaced in result()
            self._err = e
        finally:
            with _inflight_lock:
                try:
                    _inflight.remove(self)
                except ValueError:
                    pass

    def result(self):
        self.join()
        if self._err is not None:
            raise self._err


class _DoneHandle:
    def result(self):
        return None


# ------------------------------------------------------------------- load

# incomplete dirs counted once per path per process (is_complete runs
# on every steps() scan — a raw per-call count would just measure scan
# frequency); completeness VERDICTS are cached the same way, because a
# published checkpoint dir is immutable and meta.json embeds the full
# per-shard index — re-parsing it on every _prune/load_latest scan
# would put keep× full-JSON parses on the step path
_incomplete_seen_lock = threading.Lock()
_incomplete_seen = set()
_complete_seen = set()


def is_complete(path):
    """A committed checkpoint: meta.json present AND every rank's
    DONE.<r> commit marker (per meta's commit.world) present. By
    construction the rename that publishes meta.json only happens after
    all markers exist, so a missing marker means tampering or a
    pre-marker-protocol bug — either way the directory is invisible
    (pt_ckpt_incomplete_discarded_total), never half-trusted.
    Checkpoints written before the commit record pass on meta.json
    alone; an unreadable meta.json is left for verify_integrity to
    classify as torn."""
    meta_p = os.path.join(path, _META)
    if not os.path.isfile(meta_p):
        return False
    with _incomplete_seen_lock:
        if path in _complete_seen:
            return True
    try:
        with open(meta_p) as f:
            world = int((json.load(f).get("commit") or {})
                        .get("world", 0))
    except (json.JSONDecodeError, UnicodeDecodeError, OSError,
            TypeError, ValueError):
        return True    # torn meta: load_latest's fallback handles it
    missing = [r for r in range(world)
               if not os.path.isfile(
                   os.path.join(path, f"{_DONE_PREFIX}{r}"))]
    if not missing:
        with _incomplete_seen_lock:
            _complete_seen.add(path)
        return True
    with _incomplete_seen_lock:
        if path not in _incomplete_seen:
            _incomplete_seen.add(path)
            _INCOMPLETE_DISCARDED.inc()
            record("ckpt_incomplete", path=path, missing_ranks=missing)
    return False


def load_state_dict(path, shardings=None, return_numpy=False):
    """Load a checkpoint directory into a nested dict. Array leaves come
    back as Tensors (or numpy with return_numpy=True). `shardings` maps
    leaf path ("a/b/c") → jax.sharding.Sharding to place a leaf sharded
    (only the locally-needed regions are copied to each device; shard
    files are memory-mapped, so an N-way-sharded leaf never materializes
    fully per-host).

    The meta.json integrity record (leaf count + per-shard byte sizes)
    is verified first: a torn checkpoint is rejected with ValueError,
    never half-loaded."""
    t_start = _time.perf_counter()
    meta = verify_integrity(path)
    flat = []
    for e in meta["leaves"]:
        shape = tuple(e["shape"])
        dtype = e["dtype"]
        mmaps = []
        for srec in e["shards"]:
            m = np.load(os.path.join(path, "shards", srec["file"]),
                        mmap_mode="r")
            mmaps.append((tuple((a, b) for a, b in srec["index"]), m))

        def _region(idx, _mm=mmaps, _shape=shape, _dt=dtype):
            """Assemble the region `idx` (tuple of slices) from shards."""
            starts = [s.start or 0 for s in idx]
            stops = [s.stop if s.stop is not None else d
                     for s, d in zip(idx, _shape)]
            out = np.empty([b - a for a, b in zip(starts, stops)],
                           dtype=np.dtype(_mm[0][1].dtype))
            for bounds, m in _mm:
                inter = [(max(a, s), min(b, e))
                         for (a, b), s, e in zip(bounds, starts, stops)]
                if any(lo >= hi for lo, hi in inter):
                    continue
                src = tuple(slice(lo - a, hi - a)
                            for (a, _), (lo, hi) in zip(bounds, inter))
                dst = tuple(slice(lo - s, hi - s)
                            for s, (lo, hi) in zip(starts, inter))
                out[dst] = m[src]
            return _from_storage(out, _dt)

        key = e["path"]
        sh = (shardings or {}).get(key)
        if sh is not None:
            arr = jax.make_array_from_callback(shape, sh, _region)
        else:
            full = _region(tuple(slice(0, d) for d in shape))
            arr = np.asarray(full) if return_numpy else jnp.asarray(full)
        flat.append((tuple(key.split("/")),
                     arr if return_numpy else Tensor(arr)))
    byts = set(meta.get("bytes", ()))
    for key, v in meta["scalars"].items():
        if key in byts:
            v = v.encode("latin1")
        flat.append((tuple(key.split("/")), v))
    for key, tag in meta.get("empties", {}).items():
        flat.append((tuple(key.split("/")),
                     {} if tag == "__empty_dict__" else []))
    out = _nest(flat, set(meta.get("lists", ())))
    integ = meta.get("integrity") or {}
    _BYTES_TOTAL.labels(direction="loaded").inc(
        sum(integ.get("shards", {}).values()))
    _OPS_TOTAL.labels(op="load").inc()
    _LOAD_SECONDS.observe(_time.perf_counter() - t_start)
    return out


def _xla_owned(arr):
    """Re-ingest a restored leaf through a trivial on-device program so
    the result's buffer is ALLOCATED AND OWNED BY XLA, preserving
    sharding and commitment (elementwise ops keep both; verified for
    this jax build).

    Root-caused this session: `jax.make_array_from_callback` ALIASES
    the callback's numpy buffers on CPU (np↔jnp zero-copy is the same
    family), so a checkpoint-restored sharded param/accumulator entered
    the DONATING train-step executable backed by numpy-owned memory —
    and when the persistent compile cache serves the executable with
    true in-place donation, XLA reuses/frees host memory numpy still
    owns: heap corruption ('corrupted double-linked list' at the second
    post-restore dispatch or at exit, ~2-in-3 runs on the hybrid3d
    restore path). This is the PTL201 'zero-copy route into a donated
    pytree' signature (docs/RESILIENCE.md 'Buffer aliasing'), at the
    checkpoint-restore ingest boundary. One device-local memcpy per
    restored leaf buys ownership."""
    if not isinstance(arr, jax.Array):
        return arr
    if arr.dtype == jnp.bool_:
        return jnp.logical_or(arr, False)
    return arr + jnp.zeros((), arr.dtype)


# ----------------------------------------------------------- Checkpointer

class Checkpointer:
    """Train-loop checkpoint manager (reference auto-checkpoint /
    fleet.utils fs checkpoint + hapi callbacks ModelCheckpoint).

    save(step) captures model params, optimizer accumulators + LR-scheduler
    state, and a compiled train step's device-side opt states; keeps the
    newest `keep` checkpoints; `async_save` overlaps file writes with
    training. load_latest() restores everything and returns the step (or
    None if no complete checkpoint exists)."""

    def __init__(self, root, model=None, optimizer=None, train_step=None,
                 keep=3, async_save=False, retry=None):
        self.root = root
        self.model = model
        self.train_step = train_step
        self.optimizer = optimizer or (
            train_step.optimizer if train_step is not None else None)
        self.keep = keep
        self.async_save = async_save
        self._last = None
        # transient-FS hardening (flaky NFS/GCS-fuse mounts): loads are
        # always retried; saves only single-process + synchronous, where
        # re-running is idempotent (a multi-controller save re-run on one
        # rank alone would re-enter the snapshot barrier without its
        # peers and desync the pod — that path relies on the marker
        # protocol's invisible-until-complete guarantee plus the elastic
        # restart layer instead)
        # give_up_on FileNotFoundError: a missing shard behind a
        # committed meta is a TORN checkpoint (load_latest's fallback
        # signal), never a transient — don't burn backoff sleeps on it
        self.retry = retry or RetryPolicy(
            max_attempts=3, base_s=0.2, max_backoff_s=2.0,
            retry_on=(OSError,), give_up_on=(FileNotFoundError,),
            name="ckpt.io")

    def _dir(self, step):
        return os.path.join(self.root, f"ckpt-{step:08d}")

    def _name_maps(self):
        """param.name ↔ structural-key maps. Parameter.name comes from a
        process-global counter, so it differs across re-instantiation;
        checkpoints must be keyed by the structural state_dict key."""
        by_pname, by_struct = {}, {}
        if self.model is not None:
            for sname, p in self.model.state_dict().items():
                by_pname[p.name] = sname
                by_struct[sname] = p.name
        return by_pname, by_struct

    @staticmethod
    def _remap_opt_keys(sd, mapping):
        """optimizer.state_dict keys look like f'{param.name}_{acc}';
        rewrite the param.name prefix via mapping (longest-prefix match).
        Non-param keys (@step, LR_Scheduler) pass through."""
        pnames = sorted(mapping, key=len, reverse=True)
        out = {}
        for k, v in sd.items():
            nk = k
            for pn in pnames:
                if k.startswith(pn + "_"):
                    nk = mapping[pn] + k[len(pn):]
                    break
            out[nk] = v
        return out

    def save(self, step):
        # back-pressure: a still-running commit of the previous save is
        # joined HERE (error-propagating), and the wait counts into the
        # step-path stall this save reports
        t_stall0 = _time.perf_counter()
        if isinstance(self._last, _AsyncHandle) and self._last.is_alive():
            record("ckpt_backpressure", step=int(step))
        self.wait()
        state = {"step": int(step)}
        if self.model is not None:
            state["model"] = dict(self.model.state_dict())
        if self.optimizer is not None:
            by_pname, _ = self._name_maps()
            state["optimizer"] = self._remap_opt_keys(
                self.optimizer.state_dict(), by_pname)
        if self.train_step is not None:
            opt_sd = _train_step_opt_states(self.train_step)
            if opt_sd:
                state["train_step_opt"] = opt_sd
        _, nproc = _proc_index()
        t_wall0 = _steptrace.now()
        if nproc == 1 and not self.async_save:
            self._last = self.retry.run(
                save_state_dict, state, self._dir(step),
                name=f"ckpt.save:{step}", _stall_start=t_stall0)
        else:
            self._last = save_state_dict(state, self._dir(step),
                                         async_save=self.async_save,
                                         _stall_start=t_stall0)
        # the synchronous slice of this save (snapshot + commit
        # hand-off; async commits run off the step path) becomes the
        # next step's ckpt_snapshot phase segment — the wall-time the
        # training loop actually lost to checkpointing
        _steptrace.note_ckpt_snapshot(t_wall0, _steptrace.now())
        self._prune()
        return self._last

    def wait(self):
        if self._last is not None:
            self._last.result()
            self._last = None

    def _prune(self):
        if not self.keep:
            return
        rank, _ = _proc_index()
        if rank != 0:
            return
        steps = sorted(self.steps())
        for s in steps[: max(0, len(steps) - self.keep)]:
            shutil.rmtree(self._dir(s), ignore_errors=True)

    def steps(self):
        if not os.path.isdir(self.root):
            return []
        out = []
        for d in os.listdir(self.root):
            if d.startswith("ckpt-") and is_complete(
                    os.path.join(self.root, d)):
                try:
                    out.append(int(d.split("-")[1]))
                except ValueError:
                    pass
        return sorted(out)

    def load_latest(self):
        """Restore from the newest COMPLETE checkpoint. A checkpoint
        that fails its integrity check (torn shards despite a committed
        meta.json — pre-fsync checkpoints could do this after a host
        crash) is journaled and skipped, falling back to the next-older
        one instead of half-loading. ONLY torn-checkpoint shapes
        (TornCheckpointError, missing shard files) fall back — a
        transient I/O failure that survives the retry budget, or a
        model/optimizer structure mismatch, propagates, so neither a
        flaky filesystem nor a changed model can masquerade as "no
        checkpoints" and silently restart a long run from step 0."""
        from .resilience import RetryError

        for step in reversed(self.steps()):
            try:
                return self.load(step)
            except TornCheckpointError as e:
                _TORN_FALLBACKS.inc()
                record("ckpt_rejected", step=step, error=str(e))
                continue
            except RetryError as e:
                if isinstance(e.last, FileNotFoundError):
                    _TORN_FALLBACKS.inc()
                    record("ckpt_rejected", step=step, error=str(e))
                    continue
                raise
        return None

    def load(self, step):
        # Place param leaves straight onto their current shardings
        # (ZeRO/TP) — but ONLY for leaves whose live array is committed.
        # make_array_from_callback yields committed arrays, and a
        # committed leaf where the live one was uncommitted lowers the
        # compiled TrainStep differently; with the persistent compile
        # cache the two variants collide on one cache entry and the
        # mismatched donation/aliasing map silently reverts the first
        # post-restore update (flaky resume-divergence, see
        # test_train_kill_resume_matches_uninterrupted). Committed live
        # arrays (device_put with an explicit NamedSharding — the real
        # ZeRO/TP case) keep the shard-for-shard mmap load.
        shardings = {}
        if self.model is not None:
            for name, p in self.model.state_dict().items():
                if isinstance(p._value, jax.Array) and p._value.committed:
                    shardings[f"model/{name}"] = p._value.sharding
        ts = self.train_step
        if ts is not None and getattr(ts, "_opt_states", None):
            # accumulators of a live compiled step load shard-for-shard
            # too (they are 2x param bytes under Adam — never assemble
            # them fully per host)
            for n, st in zip(_train_names(ts), ts._opt_states):
                for k, v in st.items():
                    if isinstance(v, jax.Array) and v.committed:
                        shardings[f"train_step_opt/{n}/{k}"] = v.sharding
        # sharded restore compiles reshard programs (make_array_from_
        # callback / device_put onto NamedShardings) — keep those out of
        # the persistent compile cache too: a cache-served reshard can
        # hand back subtly-wrong restored state on this jax build (same
        # aliasing hazard as the donating step executables, see
        # core.jax_compat.no_persistent_cache)
        from ..core.jax_compat import no_persistent_cache

        with no_persistent_cache(), _trace_span("ckpt.load", step=step):
            state = self.retry.run(load_state_dict, self._dir(step),
                                   shardings=shardings,
                                   name=f"ckpt.load:{step}")
        if self.model is not None and "model" in state:
            sd = self.model.state_dict()
            missing = [n for n in sd if n not in state["model"]]
            if missing:
                raise ValueError(
                    f"checkpoint is missing model params {missing}; "
                    "model structure differs from the one checkpointed")
            for name, p in sd.items():
                # _xla_owned: the restored array may alias numpy-owned
                # region buffers (make_array_from_callback) — donated
                # in place by the compiled step, that memory corrupts
                # the host heap; re-ingest to an XLA-owned buffer
                p._value = _xla_owned(
                    state["model"][name]._value.astype(p._value.dtype))
        if self.optimizer is not None and "optimizer" in state:
            _, by_struct = self._name_maps()
            self.optimizer.set_state_dict(self._remap_opt_keys(
                state["optimizer"], by_struct))
        if self.train_step is not None and "train_step_opt" in state:
            _restore_train_step_opt(self.train_step,
                                    state["train_step_opt"])
        return int(state["step"])


def _train_names(ts):
    """Structural (state_dict-key) names of trainable params — stable
    across model re-instantiation, unlike global Parameter.name counters."""
    return [n for n, t in zip(ts._names, ts._trainable) if t]


def _train_step_opt_states(ts):
    """Device-side accumulator tree of a compiled TrainStep /
    DistributedTrainStep, keyed structural-param-name → accumulator."""
    if getattr(ts, "_opt_states", None) is None:
        return {}
    if all(not st for st in ts._opt_states):
        return {}  # stateless optimizer (SGD) — nothing to record
    return {n: dict(st)
            for n, st in zip(_train_names(ts), ts._opt_states)}


def _restore_train_step_opt(ts, opt_sd):
    names = _train_names(ts)
    missing = [n for n in names if n not in opt_sd]
    if missing:
        raise ValueError(
            f"checkpoint is missing optimizer state for params {missing}; "
            "model structure differs from the one checkpointed")
    old = ts._opt_states
    states = []
    for i, n in enumerate(names):
        st = opt_sd[n]
        d = {}
        for k, v in st.items():
            val = v._value if isinstance(v, Tensor) else jnp.asarray(v)
            if (old is not None and isinstance(old[i].get(k), jax.Array)
                    and old[i][k].committed):
                # step already ran on COMMITTED accumulators (mesh
                # placement): re-place onto the live sharding (the
                # _build-time device_put won't run again)
                val = jax.device_put(val, old[i][k].sharding)
            elif not isinstance(val, jax.Array) or val.committed:
                # live accumulators are UNCOMMITTED (the single-device
                # _build path) — restore them uncommitted too.
                # device_put here yielded committed arrays, flipped the
                # step's jit signature, and the post-restore recompile
                # could be served from the persistent cache with a
                # mismatched donation/aliasing map (jax-0.4.x platform
                # bug, docs/RESILIENCE.md): the first resumed update
                # silently diverged ~1-in-3 full-suite runs
                # (test_fault_tolerant_resume_matches_uninterrupted).
                val = jnp.asarray(np.asarray(val))
            # donated next step — must be XLA-owned (see _xla_owned)
            d[k] = _xla_owned(val)
        states.append(d)
    if getattr(ts, "_compiled", None) is None:
        # restored BEFORE the step's first compile: flag it so the
        # first post-restore dispatch compiles OUTSIDE the persistent
        # compilation cache (jit.TrainStep.__call__ honors this; the
        # DistributedTrainStep _build(restored) AOT path has its own
        # guard) — a cache-served donating executable is the known
        # jax-0.4.x aliasing-corruption window (docs/RESILIENCE.md)
        ts._restored_pre_build = True
    ts._opt_states = states
