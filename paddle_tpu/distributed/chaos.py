"""Deterministic, seeded fault injection — the adversarial half of the
elastic subsystem.

The reference stack earns resilience from etcd-backed membership and
restart controllers (reference: fleet/elastic/manager.py), and this repo
reproduces the *recovery* half (launch --max_restart, Checkpointer atomic
commit, fleet.elastic.run_with_fault_tolerance).  What real outages
taught (tools/tpu_retry.sh header) is that every transient-fault path is
untested until one can *provoke* faults on demand.  This module is that
provocation layer: a :class:`FaultPlan` of scoped injectors, activated by
the ``PT_CHAOS_PLAN`` environment variable (a JSON object) so subprocess
pods launched by ``paddle_tpu.distributed.launch`` inherit the plan, or
programmatically via :func:`install`.

Design rules:

- **Deterministic.** Whether call *n* of scope *s* fires is a pure
  function of ``(seed, s, n)`` (sha256-derived uniform against ``p``, or
  an explicit ``at`` index list) — the same plan yields the identical
  fault schedule on every run, so a chaos failure reproduces.
- **Zero overhead when off.** ``fire()`` is a single ``is None`` check
  when no plan is installed; no env read after the first call.
- **Crash-once across restarts.** An injector with ``once: true`` claims
  a marker file in ``state_dir`` (or ``$PT_CHAOS_STATE``) *before*
  executing, so a crash injector that killed the pod does not re-kill
  the restarted pod at the same call index forever.

Scopes wired through the stack (see docs/RESILIENCE.md):

==================  =====================================================
scope               injection point
==================  =====================================================
``kv.get``          coordination-KV blocking gets (xproc._kv_get)
``kv.set``          coordination-KV sets (endpoint publication, kv p2p)
``sock.connect``    p2p transport connection establishment
``sock.send``       p2p frame send (stall or pre-write drop)
``sock.recv``       p2p frame receive (stall)
``ckpt.snapshot``   checkpoint SNAPSHOT phase, on the step path (one
                    call per save, after host materialization, before
                    the commit is handed off)
``ckpt.commit``     checkpoint COMMIT phase entry (background thread
                    under async_save) — call n = this rank's nth commit
``ckpt.commit.<r>`` same tick, but only rank r fires its own scope —
                    the way a FaultPlan SIGKILLs exactly one rank
                    mid-commit (busy-tick counting like
                    ``replica.kill.<name>``)
``ckpt.kill_window``between this rank's shard write and its DONE.<rank>
                    commit marker (THE torn-commit window)
``step``            train-step entry (crash/hang at step N; fired by
                    StepGuard.check AND DivergenceSentinel.check)
``step.dispatch``   inside the instrumented train step, between its
                    ``h2d`` and ``dispatch`` phase stamps — a
                    rank-scoped delay here is how the steptrace
                    straggler chaos test makes ONE rank slow in ONE
                    attributable phase (observability.steptrace)
``step.nan``        StepGuard/DivergenceSentinel loss poisoning
                    (NaN/Inf grad shape)
``replica.kill``    fleet-replica serve-loop tick (fleet_serving
                    .replica): a fired injector stops that replica's
                    loop DEAD — no drain, no future resolution — and
                    the router's failover requeues its in-flight work.
                    ``replica.kill.<name>`` targets one replica.
==================  =====================================================

Injector spec (JSON object inside the plan's ``injectors`` list)::

    {"scope": "kv.get",       # required
     "kind": "error",         # error | delay | crash | hang | nan
     "p": 0.0,                # per-call fire probability (seeded hash)
     "at": [0, 3],            # explicit 0-based call indices (OR with p)
     "ranks": [1],            # restrict to these ranks (default: all)
     "max_fires": 2,          # per-process cap (default: unlimited)
     "once": true,            # at most once per JOB (marker in state_dir)
     "delay_s": 0.25}         # sleep length for delay/hang kinds
                              # (unset: delay=0.1s, hang=wedge 1h)
"""
import hashlib
import json
import os
import signal
import threading
import time

__all__ = ["FaultPlan", "Injector", "InjectedFault", "fire", "poison",
           "install", "clear", "get_plan", "active",
           "ENV_PLAN", "ENV_STATE"]

ENV_PLAN = "PT_CHAOS_PLAN"
ENV_STATE = "PT_CHAOS_STATE"

KINDS = ("error", "delay", "crash", "hang", "nan")


class InjectedFault(OSError):
    """A chaos-injected failure. Subclasses OSError so the generic
    transient-fault handlers (resilience.RetryPolicy default retry_on)
    treat it exactly like a real I/O fault."""

    def __init__(self, scope, n, kind="error"):
        super().__init__(f"chaos: injected {kind} (scope={scope} call={n})")
        self.scope = scope
        self.n = n
        self.kind = kind


_rank_cache = None


def _rank():
    """Worker rank for rank-scoped injectors. The launcher env contract
    (PADDLE_TRAINER_ID) is authoritative and cheap; in-process tests and
    single-process jobs are rank 0."""
    global _rank_cache
    if _rank_cache is None:
        _rank_cache = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    return _rank_cache


def _hash01(seed, scope, n):
    """Uniform [0,1) from (seed, scope, call-index) — the deterministic
    coin every probabilistic injector flips."""
    h = hashlib.sha256(f"{seed}/{scope}/{n}".encode()).digest()
    return int.from_bytes(h[:8], "big") / 2.0 ** 64


class Injector:
    def __init__(self, scope, kind="error", p=0.0, at=(), ranks=None,
                 max_fires=None, once=False, delay_s=None, index=0):
        if kind not in KINDS:
            raise ValueError(f"unknown injector kind {kind!r}; "
                             f"expected one of {KINDS}")
        self.scope = scope
        self.kind = kind
        self.p = float(p)
        self.at = frozenset(int(i) for i in at)
        self.ranks = None if ranks is None else frozenset(
            int(r) for r in ranks)
        self.max_fires = max_fires
        self.once = bool(once)
        # None = unset: 'delay' defaults to a 0.1s stall, 'hang' to a
        # wedge (1h). An EXPLICIT delay_s is always honored verbatim —
        # a requested 50ms hang must not silently become an hour.
        self.delay_s = None if delay_s is None else float(delay_s)
        self.index = index          # position in the plan (marker naming)
        self.fires = 0              # per-process fire count

    def matches(self, seed, n, rank):
        """Pure decision: would call `n` of this scope on `rank` fire?
        (Ignores per-process max_fires and cross-restart once-markers —
        those are stateful filters applied by FaultPlan.fire.)"""
        if self.ranks is not None and rank not in self.ranks:
            return False
        if n in self.at:
            return True
        return self.p > 0.0 and _hash01(seed, self.scope, n) < self.p

    def spec(self):
        d = {"scope": self.scope, "kind": self.kind}
        if self.p:
            d["p"] = self.p
        if self.at:
            d["at"] = sorted(self.at)
        if self.ranks is not None:
            d["ranks"] = sorted(self.ranks)
        if self.max_fires is not None:
            d["max_fires"] = self.max_fires
        if self.once:
            d["once"] = True
        if self.kind in ("delay", "hang") and self.delay_s is not None:
            d["delay_s"] = self.delay_s
        return d


class FaultPlan:
    """A seeded set of scoped injectors. ``fire(scope)`` counts the call
    and executes the matching injector's action (raise / sleep / die);
    ``schedule`` exposes the pure decision function for determinism
    tests and pre-flight inspection."""

    def __init__(self, injectors=(), seed=0, state_dir=None):
        self.seed = int(seed)
        self.state_dir = state_dir or os.environ.get(ENV_STATE) or None
        self.injectors = []
        for i, spec in enumerate(injectors):
            if isinstance(spec, Injector):
                spec.index = i
                self.injectors.append(spec)
            else:
                self.injectors.append(Injector(index=i, **spec))
        self._counts = {}
        # scopes fire from concurrent threads (io-pool sends, the
        # heartbeat) — the counter read-modify-write must be atomic or
        # call indices get double-assigned and the deterministic
        # schedule silently diverges between runs
        self._lock = threading.Lock()
        self._by_scope = {}
        for inj in self.injectors:
            self._by_scope.setdefault(inj.scope, []).append(inj)
        self.injected = {}          # scope -> executed-injection count

    # ---- (de)serialization --------------------------------------------
    @classmethod
    def from_json(cls, text):
        spec = json.loads(text)
        return cls(injectors=spec.get("injectors", ()),
                   seed=spec.get("seed", 0),
                   state_dir=spec.get("state_dir"))

    def to_json(self):
        d = {"seed": self.seed,
             "injectors": [inj.spec() for inj in self.injectors]}
        if self.state_dir:
            d["state_dir"] = self.state_dir
        return json.dumps(d)

    # ---- pure schedule view -------------------------------------------
    def schedule(self, scope, n_calls, rank=None):
        """Call indices in [0, n_calls) that would fire for `scope` —
        the deterministic fault schedule (same seed → same list)."""
        rank = _rank() if rank is None else rank
        out = []
        for n in range(n_calls):
            if any(inj.matches(self.seed, n, rank)
                   for inj in self._by_scope.get(scope, ())):
                out.append(n)
        return out

    # ---- stateful firing ----------------------------------------------
    def _claim_once(self, inj):
        """Cross-restart at-most-once: atomically create the injector's
        marker file. False means some incarnation already fired it."""
        if not self.state_dir:
            # no durable state: degrade to per-process at-most-once
            return inj.fires == 0
        os.makedirs(self.state_dir, exist_ok=True)
        marker = os.path.join(
            self.state_dir, f"chaos_fired.{inj.index}."
            f"{''.join(c if c.isalnum() else '-' for c in inj.scope)}")
        try:
            fd = os.open(marker, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        os.close(fd)
        return True

    def fire(self, scope):
        """Count one call of `scope`; execute the matching injector's
        action if the schedule says this call fires. Returns the
        Injector executed (kind 'nan' is returned, not executed — the
        caller poisons its own value) or None."""
        chosen = None
        with self._lock:
            n = self._counts.get(scope, 0)
            self._counts[scope] = n + 1
            rank = _rank()
            for inj in self._by_scope.get(scope, ()):
                if not inj.matches(self.seed, n, rank):
                    continue
                if (inj.max_fires is not None
                        and inj.fires >= inj.max_fires):
                    continue
                if inj.once and not self._claim_once(inj):
                    continue
                inj.fires += 1
                self.injected[scope] = self.injected.get(scope, 0) + 1
                chosen = inj
                break
        if chosen is None:
            return None
        # execute OUTSIDE the lock: a delay/hang injector sleeping with
        # it held would stall every other scope's call accounting
        self._journal(chosen, n)
        return self._execute(chosen, scope, n)

    def _journal(self, inj, n):
        try:    # journaling must never break the injection itself
            from . import resilience

            resilience.record("chaos_injected", scope=inj.scope,
                              fault=inj.kind, call=n)
        except Exception:  # ptlint: disable=PTL804 (the guard wraps the journal call itself)
            pass

    def _execute(self, inj, scope, n):
        if inj.kind == "delay":
            time.sleep(0.1 if inj.delay_s is None else inj.delay_s)
            return inj
        if inj.kind == "error":
            raise InjectedFault(scope, n, "error")
        if inj.kind == "crash":
            # SIGKILL, the most faithful preemption/OOM shape: no atexit,
            # no finally blocks, no flushing — exactly what the atomic
            # checkpoint commit must survive
            os.kill(os.getpid(), signal.SIGKILL)
            time.sleep(60)          # unreachable; parachute for signals
            return inj
        if inj.kind == "hang":
            time.sleep(3600.0 if inj.delay_s is None else inj.delay_s)
            return inj
        return inj                  # "nan": caller poisons its value


# ---------------------------------------------------------------- module

_PLAN = None
_LOADED = False


def get_plan():
    """The active plan: an installed one, else PT_CHAOS_PLAN from the
    environment (read once), else None."""
    global _PLAN, _LOADED
    if not _LOADED:
        _LOADED = True
        spec = os.environ.get(ENV_PLAN)
        if spec:
            _PLAN = FaultPlan.from_json(spec)
    return _PLAN


def install(plan):
    """Install `plan` (a FaultPlan, JSON text, or dict) for this process."""
    global _PLAN, _LOADED
    if isinstance(plan, str):
        plan = FaultPlan.from_json(plan)
    elif isinstance(plan, dict):
        plan = FaultPlan(injectors=plan.get("injectors", ()),
                         seed=plan.get("seed", 0),
                         state_dir=plan.get("state_dir"))
    _PLAN = plan
    _LOADED = True
    return plan


def clear():
    """Deactivate chaos (and forget the env read, so tests that set
    PT_CHAOS_PLAN afterwards are re-read)."""
    global _PLAN, _LOADED, _rank_cache
    _PLAN = None
    _LOADED = False
    _rank_cache = None


def active():
    return get_plan() is not None


def fire(scope):
    """The hook fault paths call. No plan → a single attribute check."""
    plan = _PLAN if _LOADED else get_plan()
    if plan is None:
        return None
    return plan.fire(scope)


def poison(value, scope="step.nan"):
    """NaN/Inf poisoning hook (grad/loss shape): returns NaN when the
    scope's injector fires for this call, else `value` unchanged."""
    plan = _PLAN if _LOADED else get_plan()
    if plan is None:
        return value
    if plan.fire(scope) is not None:
        return float("nan")
    return value
