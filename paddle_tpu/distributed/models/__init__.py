"""distributed.models (reference: python/paddle/distributed/models/moe) —
MoE helper namespace; canonical implementation in distributed/moe.py."""
from . import moe  # noqa: F401
