"""distributed.models.moe (reference:
python/paddle/distributed/models/moe/) — grad-clip and utils for MoE."""
from ..moe import (  # noqa: F401
    GShardGate, MoELayer, NaiveGate, SwitchGate, moe_dispatch_combine)

__all__ = ["MoELayer", "NaiveGate", "GShardGate", "SwitchGate",
           "moe_dispatch_combine"]
