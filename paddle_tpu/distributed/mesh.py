"""Global device-mesh management.

TPU-native replacement for the reference's communicator bootstrap
(reference: paddle/fluid/platform/collective_helper.h:70 NCCLCommContext
ring registry, paddle/fluid/distributed/collective/ProcessGroupNCCL.h:49).
There are no rings and no ncclUniqueId exchange: parallelism axes are
dimensions of ONE `jax.sharding.Mesh`, and "communicators" are mesh axis
names referenced by compiled collectives. Axis order follows the
reference's fixed hybrid topology [dp, pp, sharding, mp] (fleet topology.py:52)
extended with TPU-first axes sp (sequence/context) and ep (expert).
"""
import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = [
    "init_mesh", "global_mesh", "has_mesh", "axis_size", "mesh_axes",
    "named_sharding", "PartitionSpec", "reset_mesh",
]

_AXIS_ORDER = ("dp", "pp", "sharding", "mp", "sp", "ep")

_mesh = None


def init_mesh(dp=1, pp=1, sharding=1, mp=1, sp=1, ep=1, devices=None):
    """Build the global mesh. Product of axis sizes must equal device count
    (axes of size 1 are kept — they make PartitionSpecs uniform)."""
    global _mesh
    sizes = {"dp": dp, "pp": pp, "sharding": sharding, "mp": mp, "sp": sp,
             "ep": ep}
    devs = np.asarray(devices if devices is not None else jax.devices())
    need = int(np.prod(list(sizes.values())))
    if devs.size != need:
        raise ValueError(
            f"mesh {sizes} needs {need} devices, have {devs.size}"
        )
    shape = tuple(sizes[a] for a in _AXIS_ORDER)
    _mesh = Mesh(devs.reshape(shape), _AXIS_ORDER)
    return _mesh


def reset_mesh():
    global _mesh
    _mesh = None


def global_mesh():
    global _mesh
    if _mesh is None:
        # single-device default mesh
        _mesh = Mesh(
            np.asarray(jax.devices()[:1]).reshape((1,) * len(_AXIS_ORDER)),
            _AXIS_ORDER,
        )
    return _mesh


def has_mesh():
    return _mesh is not None


def mesh_axes():
    return _AXIS_ORDER


def axis_size(axis):
    m = global_mesh()
    return m.shape[axis]


def named_sharding(*spec):
    return NamedSharding(global_mesh(), PartitionSpec(*spec))
