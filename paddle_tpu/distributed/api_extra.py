"""Remaining paddle.distributed surface: spawn, ParallelMode, TP split,
gloo facade, PS dataset facades and sparse-entry configs.

Reference: python/paddle/distributed/{spawn.py, parallel.py,
collective.py split:?, fleet/dataset/, entry_attr}.
"""
import os
import sys

import numpy as np

__all__ = [
    "ParallelMode", "spawn", "split", "destroy_process_group",
    "gloo_init_parallel_env", "gloo_barrier", "gloo_release",
    "InMemoryDataset", "QueueDataset", "BoxPSDataset",
    "ProbabilityEntry", "CountFilterEntry", "ShowClickEntry",
]


class ParallelMode:
    """Hybrid-parallel mode ids (reference:
    python/paddle/distributed/parallel.py ParallelMode)."""

    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """Run `func(*args)` in nprocs worker processes under the PADDLE_*
    env contract (reference: distributed/spawn.py). Each worker calls
    init_parallel_env itself (as in the reference examples)."""
    import multiprocessing as mp
    import socket

    if nprocs == -1:
        nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    if nprocs <= 1:
        func(*args)
        return None
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        master = f"127.0.0.1:{s.getsockname()[1]}"
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {
            "PADDLE_MASTER": master,
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_LOCAL_RANK": str(rank),
            "PADDLE_LOCAL_SIZE": str(nprocs),
            "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", "cpu"),
        }
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        bad = [p.exitcode for p in procs if p.exitcode != 0]
        if bad:
            raise RuntimeError(f"spawn workers failed with codes {bad}")
        return None
    return procs


def _spawn_entry(func, args, env):
    os.environ.update(env)
    func(*args)


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Megatron-style single-op model parallelism (reference:
    python/paddle/distributed/collective.py split): build the matching
    mpu layer over the mp mesh axis and apply it. Prefer the
    fleet.meta_parallel layers for real models — they own their
    parameters across steps; this op-level facade constructs the layer
    per call (same as the reference's LayerHelper-created vars)."""
    from .fleet.meta_parallel import mp_layers as mpu

    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = mpu.ColumnParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                gather_output=gather_out)
        else:
            layer = mpu.RowParallelLinear(
                in_f, out_f, weight_attr=weight_attr,
                has_bias=bias_attr is not False,
                input_is_parallel=not gather_out)
        return layer(x)
    if operation == "embedding":
        vocab, dim = size
        layer = mpu.VocabParallelEmbedding(vocab, dim,
                                           weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unsupported split operation {operation!r}")


def destroy_process_group(group=None):
    """Tear down group state (reference: collective.py
    destroy_process_group)."""
    from . import collective

    if group is None:
        collective._groups.clear()
        return
    collective._groups.pop(getattr(group, "id", group), None)


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """CPU-barrier rendezvous (reference: parallel.py gloo_init_parallel_env).
    The jax.distributed coordination service subsumes gloo: ensure it is
    up for this process set."""
    from . import env as env_mod

    os.environ.setdefault("PADDLE_TRAINER_ID", str(rank_id))
    os.environ.setdefault("PADDLE_TRAINERS_NUM", str(rank_num))
    os.environ.setdefault("PADDLE_MASTER", server_endpoint)
    env_mod.ensure_multihost_initialized()


def gloo_barrier():
    from . import xproc

    if xproc.is_multiprocess():
        xproc.barrier()


def gloo_release():
    """No resources to free: the coordination service dies with the
    process set."""


# ---------------------------------------------------------- PS datasets

class _SlotDataset:
    """Slot-based dataset facade for PS training (reference:
    python/paddle/distributed/fleet/dataset/dataset.py InMemoryDataset /
    QueueDataset over C++ data_feed.cc). Files hold one sample per line;
    `pipe_command` is replaced by a python `parse_fn` (no subprocess feed
    on the TPU host path)."""

    def __init__(self):
        self._filelist = []
        self._samples = []
        self._batch_size = 1
        self._use_var = []
        self._parse_fn = None
        self._thread_num = 1

    def init(self, batch_size=1, thread_num=1, use_var=None, pipe_command=None,
             parse_fn=None, **kwargs):
        self._batch_size = batch_size
        self._thread_num = thread_num
        self._use_var = use_var or []
        self._parse_fn = parse_fn

    update_settings = init

    def set_filelist(self, filelist):
        self._filelist = list(filelist)

    def _iter_lines(self):
        if self._parse_fn == "numeric":
            # native fast path: C strtof loop over newline-aligned chunks
            # (reference data_feed.cc MultiSlotDataFeed), GIL released.
            # Chunked so QueueDataset stays streaming on huge files.
            from .. import native

            n_slots = len(self._use_var) if self._use_var else None
            chunk_size = 4 << 20
            for path in self._filelist:
                with open(path, "rb") as f:
                    pending = b""
                    while True:
                        chunk = f.read(chunk_size)
                        if chunk:
                            data = pending + chunk
                            nl = data.rfind(b"\n")
                            if nl < 0:
                                pending = data
                                continue
                            pending, data = data[nl + 1:], data[: nl + 1]
                        else:
                            data, pending = pending, b""
                        if n_slots is None:
                            for line in data.split(b"\n"):
                                if line.strip():
                                    n_slots = len(line.split())
                                    break
                        if data.strip() and n_slots:
                            for row in native.parse_slots(data, n_slots):
                                yield row.tolist()
                        if not chunk:
                            break
            return
        for path in self._filelist:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    yield (self._parse_fn(line) if self._parse_fn
                           else line.split())

    def __iter__(self):
        buf = []
        for sample in self._iter_lines():
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield buf
                buf = []
        if buf:
            yield buf


class InMemoryDataset(_SlotDataset):
    def __init__(self):
        super().__init__()
        self._loaded = False

    def load_into_memory(self):
        self._samples = list(self._iter_lines())
        self._loaded = True

    def get_memory_data_size(self, fleet=None):
        return len(self._samples)

    def local_shuffle(self):
        np.random.default_rng().shuffle(self._samples)

    _shuffle_calls = 0

    def global_shuffle(self, fleet=None, thread_num=12):
        """Cross-trainer shuffle (reference: data_set.cc distributed
        shuffle — samples are re-partitioned across all trainers by
        random owner, then shuffled locally). Buckets travel POINT TO
        POINT over the coordination-service KV store (each pair
        exchanges only its bucket — O(N) total, not an O(N·world)
        padded all-gather). The owner draw mixes in a per-call counter
        so each epoch re-draws the partition. Single-process: local."""
        import pickle

        from . import xproc

        self._shuffle_calls += 1
        if not xproc.is_multiprocess():
            self.local_shuffle()
            return
        import jax

        world = jax.process_count()
        me = jax.process_index()
        rng = np.random.default_rng([me, self._shuffle_calls])
        owners = rng.integers(0, world, len(self._samples))
        outgoing = [[] for _ in range(world)]
        for s, o in zip(self._samples, owners):
            outgoing[int(o)].append(s)
        mine = list(outgoing[me])
        tag = 7000 + (self._shuffle_calls % 1000)
        for peer in range(world):
            if peer != me:
                xproc.send_bytes(pickle.dumps(
                    outgoing[peer], protocol=pickle.HIGHEST_PROTOCOL),
                    dst=peer, tag=tag)
        for peer in range(world):
            if peer != me:
                mine.extend(pickle.loads(
                    xproc.recv_bytes(src=peer, tag=tag)))
        self._samples = mine
        self.local_shuffle()

    def release_memory(self):
        self._samples = []
        self._loaded = False

    def get_shuffle_data_size(self, fleet=None):
        return len(self._samples)

    def __iter__(self):
        src = self._samples if self._loaded else self._iter_lines()
        buf = []
        for sample in src:
            buf.append(sample)
            if len(buf) == self._batch_size:
                yield buf
                buf = []
        if buf:
            yield buf


class QueueDataset(_SlotDataset):
    """Streaming variant — never materializes the file set."""


class BoxPSDataset(InMemoryDataset):
    """BoxPS (ads) dataset facade; behaviorally InMemoryDataset here
    (reference dataset.py BoxPSDataset adds PS-server preload hooks)."""

    def begin_pass(self):
        pass

    def end_pass(self, need_save_delta=False):
        pass

    def preload_into_memory(self):
        self.load_into_memory()

    def wait_preload_done(self):
        pass


# ----------------------------------------------- sparse entry policies

class ProbabilityEntry:
    """Random-admission policy for sparse features (reference:
    python/paddle/distributed/entry_attr.py ProbabilityEntry)."""

    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self._probability = float(probability)

    def _to_attr(self):
        return f"probability_entry:{self._probability}"


class CountFilterEntry:
    """Admit a feature only after `count_filter` occurrences (reference:
    entry_attr.py CountFilterEntry)."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self._count_filter = int(count_filter)

    def _to_attr(self):
        return f"count_filter_entry:{self._count_filter}"


class ShowClickEntry:
    """Show/click-weighted entry (reference: entry_attr.py ShowClickEntry)."""

    def __init__(self, show_name, click_name):
        self._show = str(show_name)
        self._click = str(click_name)

    def _to_attr(self):
        return f"show_click_entry:{self._show}:{self._click}"
