"""distributed.communication.stream — stream-variant collectives.

Reference: python/paddle/distributed/communication/stream/ — the same
collectives with `sync_op` / `use_calc_stream` knobs controlling which
CUDA stream runs the op and whether the call blocks.

TPU-native semantics: XLA owns scheduling — there are no user-visible
streams, and in-graph collectives are ordered by data flow. These
wrappers accept and IGNORE `use_calc_stream` (documented once here, not
per call) and pass `sync_op` through to the eager implementations.
Signatures keep the reference's POSITIONAL parameter order so legacy
positional calls work.
"""
from .. import collective as _c
from ..collective import ReduceOp  # noqa: F401

__all__ = ["all_reduce", "all_gather", "broadcast", "reduce", "scatter",
           "alltoall", "alltoall_single", "reduce_scatter", "send",
           "recv"]


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_reduce(tensor, op=op, group=group, sync_op=sync_op)


def all_gather(tensor_list, tensor, group=None, sync_op=True,
               use_calc_stream=False):
    return _c.all_gather(tensor_list, tensor, group=group, sync_op=sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True,
              use_calc_stream=False):
    return _c.broadcast(tensor, src=src, group=group, sync_op=sync_op)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True,
           use_calc_stream=False):
    return _c.reduce(tensor, dst=dst, op=op, group=group, sync_op=sync_op)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True,
            use_calc_stream=False):
    return _c.scatter(tensor, tensor_list, src=src, group=group,
                      sync_op=sync_op)


def alltoall(out_tensor_list, in_tensor_list=None, group=None,
             sync_op=True, use_calc_stream=False):
    # reference stream.alltoall takes (out, in); collective.alltoall
    # takes (in, out) — each module stays faithful to its own reference
    return _c.alltoall(in_tensor_list, out_tensor_list, group=group,
                       sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor=None, out_split_sizes=None,
                    in_split_sizes=None, group=None, sync_op=True,
                    use_calc_stream=False):
    return _c.alltoall_single(in_tensor, out_tensor,
                              in_split_sizes=in_split_sizes,
                              out_split_sizes=out_split_sizes,
                              group=group, sync_op=sync_op)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True, use_calc_stream=False):
    return _c.reduce_scatter(tensor, tensor_list, op=op, group=group,
                             sync_op=sync_op)


def send(tensor, dst=0, group=None, sync_op=True, use_calc_stream=False):
    return _c.send(tensor, dst=dst, group=group, sync_op=sync_op)


def recv(tensor, src=0, group=None, sync_op=True, use_calc_stream=False):
    return _c.recv(tensor, src=src, group=group, sync_op=sync_op)
