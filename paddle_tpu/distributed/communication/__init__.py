"""distributed.communication — new-style collective wrappers.

Reference: python/paddle/distributed/communication/ (thin new-namespace
re-exports of the collective API plus `stream` variants). The canonical
implementations live in `distributed.collective`; this package keeps the
reference import paths working.
"""
# import from .collective directly: this package loads DURING
# distributed/__init__, before the parent re-exports exist
from ..collective import (  # noqa: F401
    P2POp, ReduceOp, all_gather, all_gather_object, all_reduce, alltoall,
    alltoall_single, batch_isend_irecv, broadcast, reduce, reduce_scatter,
    scatter, scatter_object_list)
from . import stream  # noqa: F401

__all__ = ["ReduceOp", "stream", "all_reduce", "all_gather",
           "all_gather_object", "broadcast", "reduce", "scatter",
           "scatter_object_list", "alltoall", "alltoall_single",
           "reduce_scatter", "batch_isend_irecv", "P2POp"]
