"""distributed.parallel_with_gloo (reference:
python/paddle/distributed/parallel_with_gloo.py:40) — CPU-only
process-group bring-up. One implementation: re-exported from api_extra
(the coordination service plays gloo's role)."""
from .api_extra import (  # noqa: F401
    gloo_barrier, gloo_init_parallel_env, gloo_release)

__all__ = ["gloo_init_parallel_env", "gloo_barrier", "gloo_release"]
