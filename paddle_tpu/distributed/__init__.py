"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Collectives = XLA programs over one jax.sharding.Mesh; fleet topology
names mesh axes; parallelism = placement (see SURVEY.md §7 design map).
"""
from . import auto_parallel  # noqa: F401
from . import chaos  # noqa: F401
from . import checkpoint  # noqa: F401
from . import resilience  # noqa: F401
from . import collective  # noqa: F401
from . import cloud_utils  # noqa: F401
from . import communication  # noqa: F401
from . import coordinator  # noqa: F401
from . import entry_attr  # noqa: F401
from . import models  # noqa: F401
from . import parallel_with_gloo  # noqa: F401
from . import passes  # noqa: F401
from .communication import stream  # noqa: F401
from . import metric  # noqa: F401
from . import env  # noqa: F401
from . import mesh  # noqa: F401
from . import graph_table  # noqa: F401
from . import moe  # noqa: F401
from . import ps  # noqa: F401
from . import sequence_parallel  # noqa: F401
from . import sharding  # noqa: F401
# after ps (whose jit import fully populates that namespace first):
# the mesh-native DP × TP × PP subsystem
from . import hybrid3d  # noqa: F401
from .collective import (  # noqa: F401
    Group,
    ReduceOp,
    all_gather,
    all_gather_object,
    all_reduce,
    alltoall,
    alltoall_single,
    barrier,
    broadcast,
    get_group,
    init_parallel_env,
    is_initialized,
    new_group,
    p2p_shift,
    reduce,
    reduce_scatter,
    scatter,
    scatter_object_list,
    spmd,
    wait,
)
from .collective import (  # noqa: F401
    P2POp,
    batch_isend_irecv,
    irecv,
    isend,
    recv,
    send,
)
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
from .resilience import (  # noqa: F401
    RetryPolicy,
    StepAbort,
    StepGuard,
    install_preemption_handler,
)
from .chaos import FaultPlan  # noqa: F401
from .mesh import init_mesh, global_mesh  # noqa: F401
from .parallel_step import DistributedTrainStep  # noqa: F401
from .sequence_parallel import (  # noqa: F401
    ring_attention,
    ring_flash_attention,
    ulysses_attention,
)
from .auto_parallel import shard_op, shard_tensor  # noqa: F401
from .api_extra import (  # noqa: F401
    BoxPSDataset,
    CountFilterEntry,
    InMemoryDataset,
    ParallelMode,
    ProbabilityEntry,
    QueueDataset,
    ShowClickEntry,
    destroy_process_group,
    gloo_barrier,
    gloo_init_parallel_env,
    gloo_release,
    spawn,
    split,
)

from . import fleet  # noqa: F401
from . import launch  # noqa: F401


def DataParallel(layers, **kwargs):
    """(reference: python/paddle/fluid/dygraph/parallel.py:437.) Under
    GSPMD, gradient sync is compiled into the step when the batch is
    dp-sharded — the wrapper is the identity, kept for API parity."""
    return layers


from .fleet.recompute import recompute, recompute_sequential  # noqa: F401
