"""paddle_tpu.distributed (reference: python/paddle/distributed/).

Collective API, fleet facade, topology, and meta-parallel wrappers over
jax.sharding / shard_map. Built out module-by-module; env is the rank
contract.
"""
from . import env  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
