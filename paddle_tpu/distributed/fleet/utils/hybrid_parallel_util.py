"""fleet.utils.hybrid_parallel_util (reference:
python/paddle/distributed/fleet/utils/hybrid_parallel_util.py —
fused param broadcast / gradient allreduce helpers used by the hybrid
wrappers).

TPU-native: inside `DistributedTrainStep` gradient sync is
compiler-emitted from shardings and these helpers are unnecessary; they
serve the EAGER multi-process path (xproc collectives), where fusing
many small grads into one flat buffer saves per-call latency exactly as
the reference's coalesced allreduce does. The eager path implements only
the WORLD group (xproc contract) — hybrid topologies whose target group
is a strict subset of the processes must use the compiled SPMD path, and
these helpers raise rather than silently reducing over the wrong ranks.
"""
import numpy as np

__all__ = ["fused_allreduce_gradients", "broadcast_mp_parameters",
           "broadcast_dp_parameters"]


def _group_is_world(hcg, axis):
    """True when the hcg's `axis` group spans every process (the only
    group the eager xproc path implements)."""
    if hcg is None:
        return True
    sizes = {
        "dp": hcg.get_data_parallel_world_size(),
        "mp": hcg.get_model_parallel_world_size(),
        "pp": hcg.get_pipe_parallel_world_size(),
    }
    others = [v for k, v in sizes.items() if k != axis]
    return all(v in (None, 1) for v in others)


def _grad_tensors(parameters):
    return [p for p in parameters
            if getattr(p, "grad", None) is not None]


def fused_allreduce_gradients(parameter_list, hcg=None):
    """All-reduce every parameter's `.grad` in ONE flat buffer per dtype
    (reference hybrid_parallel_util.py fused_allreduce_gradients, which
    coalesces per-dtype groups the same way)."""
    from ...xproc import all_reduce_np, is_multiprocess

    if not is_multiprocess():
        return  # single process: the reduce is the identity
    if not _group_is_world(hcg, "dp"):
        raise NotImplementedError(
            "eager fused allreduce only supports a dp group spanning all "
            "processes; hybrid dp×mp/pp jobs sync grads inside the "
            "compiled SPMD step (DistributedTrainStep)")
    params = _grad_tensors(parameter_list)
    if not params:
        return
    import jax.numpy as jnp

    from ....tensor_core import Tensor

    # bf16 master-copy guard: under the PT_QUANT_ALLREDUCE int8 wire
    # the codec only understands fp32/fp64 — a bf16/f16 grad group is
    # upcast to fp32 for the wire and the REDUCED result handed back in
    # fp32 (the tape already accumulates f32 grads for low-precision
    # params). Only p.grad is ever rewritten: the params themselves —
    # the bf16 master copies — and the optimizer's fp32 moments never
    # touch the quantized path.
    def _quant_wire_on():
        try:
            from ....quantization import runtime as qrt

            return qrt.quant_allreduce_enabled()
        except Exception:
            return False

    upcast_low_precision = _quant_wire_on()
    by_dtype = {}
    for p in params:
        g = np.asarray(p.grad._value if hasattr(p.grad, "_value")
                       else p.grad.numpy())
        if upcast_low_precision and g.dtype.itemsize < 4 and \
                jnp.issubdtype(g.dtype, jnp.floating):
            g = g.astype(np.float32)
        by_dtype.setdefault(g.dtype.str, []).append((p, g))
    for _, group in sorted(by_dtype.items()):
        flat = np.concatenate([g.reshape(-1) for _, g in group])
        flat = np.asarray(all_reduce_np(flat))
        off = 0
        for p, g in group:
            p.grad = Tensor(jnp.asarray(
                flat[off:off + g.size].reshape(g.shape)),
                stop_gradient=True)
            off += g.size


def _broadcast_params(parameters, src=0):
    from ...xproc import broadcast_np, is_multiprocess

    if not is_multiprocess():
        return
    import jax.numpy as jnp

    for p in parameters:
        arr = np.asarray(p._value)
        p._value = jnp.asarray(np.asarray(broadcast_np(arr, src=src)))


def broadcast_mp_parameters(model, hcg=None):
    """Sync params across the mp group (reference
    broadcast_mp_parameters syncs the NON-sliced ones; in this design
    sliced params never exist as divergent eager copies — TP slicing is
    a sharding over the mesh — so every eager param is shared)."""
    if not _group_is_world(hcg, "mp"):
        raise NotImplementedError(
            "eager mp broadcast only supports an mp group spanning all "
            "processes; hybrid topologies hold TP shards as mesh "
            "placements, which need no eager sync")
    _broadcast_params(list(model.parameters()))


def broadcast_dp_parameters(model, hcg=None):
    """Sync params across the dp group at start-up (reference
    broadcast_dp_parameters)."""
    if not _group_is_world(hcg, "dp"):
        raise NotImplementedError(
            "eager dp broadcast only supports a dp group spanning all "
            "processes; use the compiled SPMD path for hybrid meshes")
    _broadcast_params(list(model.parameters()))
