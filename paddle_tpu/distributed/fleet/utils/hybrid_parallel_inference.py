"""Hybrid-parallel (pp × mp) inference helper.

Reference: python/paddle/distributed/fleet/utils/hybrid_parallel_inference.py:23
(`HybridParallelInferenceHelper`) — a static-program rewriter that splits a
while-loop inference program across pipeline stages and inserts
send/recv at stage boundaries so autoregressive decoding runs pipelined.

TPU-native redesign: no program surgery. The stage-decomposed model (a
stacked `block_fn` + head, the same decomposition `pipeline_1f1b` trains)
is laid onto the mesh's ``pp`` axis with `shard_map`; micro-batches flow
through a fill-drain schedule whose stage handoff is `lax.ppermute` over
ICI. One compiled SPMD program per input shape replaces the reference's
while-block send/recv rewriting; the decode loop drives that program
host-side, one step per token (so `prompt_fn` must keep the step input
shape fixed — see `generate`).
"""
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import mesh as mesh_mod

__all__ = ["HybridParallelInferenceHelper"]


class HybridParallelInferenceHelper:
    """Pipelined forward/decoding driver.

    Args:
        block_fn: ``(stage_params, x) -> x`` one pipeline stage.
            `stage_params` keeps the leading per-stage layer axis —
            block_fn typically `lax.scan`s over it, the same contract as
            `pipeline_1f1b` / `PipelinedGPTForCausalLM._block_fn`.
        stacked_params: pytree whose leaves carry a leading
            ``num_layers`` axis sharded over ``pp`` (stage-stacked).
        head_fn: ``(x, post_params) -> logits`` applied on the last stage.
        post_params: head parameters (replicated).
        micro_batches: number of micro-batches the input batch is split
            into (reference `micro_batch_size`).
    """

    def __init__(self, block_fn, stacked_params, head_fn=None,
                 post_params=None, micro_batches=1):
        self._block_fn = block_fn
        self._stacked = stacked_params
        self._head_fn = head_fn or (lambda x, p: x)
        self._post = post_params
        self._M = int(micro_batches)
        self._fwd = None

    # -- single pipelined forward ----------------------------------------
    def _build_forward(self):
        block_fn, head_fn, M = self._block_fn, self._head_fn, self._M
        mesh = mesh_mod.global_mesh()
        pp = mesh.shape["pp"]

        def per_stage(params, post, xs):
            # xs: [M, mb, ...] micro-batched input (replicated)
            stage = lax.axis_index("pp")
            T = M + pp - 1

            def tick(carry, t):
                outs, fwd_recv = carry
                mf = t - stage
                valid = (mf >= 0) & (mf < M)
                mf_c = jnp.clip(mf, 0, M - 1)
                x_in = jnp.where(stage == 0, xs[mf_c], fwd_recv)
                out = block_fn(params, x_in)
                keep = valid & (stage == pp - 1)
                outs = outs.at[mf_c].set(
                    jnp.where(keep, out, outs[mf_c]))
                fwd_recv = lax.ppermute(
                    out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
                return (outs, fwd_recv), None

            outs0 = jnp.zeros(xs.shape, xs.dtype)
            (outs, _), _ = lax.scan(
                tick, (outs0, jnp.zeros(xs.shape[1:], xs.dtype)),
                jnp.arange(T))
            # only the last stage holds real outputs; share them
            outs = lax.psum(
                jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)),
                "pp")
            return jax.vmap(lambda o: head_fn(o, post))(outs)

        if pp == 1:
            def fwd(stacked, post, xs):
                return jax.vmap(
                    lambda x: head_fn(block_fn(stacked, x), post))(xs)
            return jax.jit(fwd)

        stack_spec = jax.tree_util.tree_map(
            lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), self._stacked)
        rep = lambda t: jax.tree_util.tree_map(
            lambda a: P(*([None] * a.ndim)), t)
        def fwd(stacked, post, xs):
            smapped = jax.shard_map(
                per_stage, mesh=mesh,
                in_specs=(stack_spec, rep(post),
                          P(*([None] * xs.ndim))),
                out_specs=P(), check_vma=False)
            return smapped(stacked, post, xs)

        return jax.jit(fwd)

    def forward(self, batch):
        """Run one pipelined forward over `batch`; returns the head
        output, replicated. Batches not divisible by `micro_batches` are
        zero-padded up to the next multiple and the padding stripped (the
        reference's data loader drops ragged tails instead — padding keeps
        the compiled shape count at one per padded size)."""
        if self._fwd is None:
            self._fwd = self._build_forward()
        x = jnp.asarray(batch)
        n = x.shape[0]
        pad = (-n) % self._M
        if pad:
            x = jnp.concatenate(
                [x, jnp.zeros((pad,) + x.shape[1:], x.dtype)])
        xs = x.reshape((self._M, x.shape[0] // self._M) + x.shape[1:])
        out = self._fwd(self._stacked, self._post, xs)
        return out.reshape((x.shape[0],) + out.shape[2:])[:n]

    # -- autoregressive decode (the reference's while-block use case) -----
    def generate(self, prompt_fn, init_tokens, max_new_tokens,
                 sample_fn=None):
        """Greedy/custom autoregressive decode through the pipeline.

        `prompt_fn(tokens) -> x` embeds the current token window into the
        stage-0 input; `sample_fn(logits) -> token` picks the next token
        (argmax default). The loop is host-side (each step is one compiled
        pipelined forward), matching the reference helper's while-block
        semantics without program rewriting.

        `prompt_fn` MUST return a fixed shape across steps (embed the
        last token, a fixed-length window, or maintain a KV cache) —
        the pipelined forward is compiled once per input shape, so a
        growing window recompiles every step."""
        # default: greedy over the last position's logits ([b, v] heads
        # emit one step; [b, s, v] heads emit the whole window)
        sample_fn = sample_fn or (lambda lg: jnp.argmax(
            lg if lg.ndim == 2 else lg[..., -1, :], -1))
        toks = jnp.asarray(init_tokens)
        for _ in range(max_new_tokens):
            logits = self.forward(prompt_fn(toks))
            nxt = sample_fn(logits)
            toks = jnp.concatenate([toks, nxt[:, None].astype(toks.dtype)],
                                   axis=1)
        return toks
