"""fleet.utils — filesystem clients + recompute alias.

TPU-native counterparts of the reference helpers (reference:
python/paddle/distributed/fleet/utils/{fs.py,__init__.py,ps_util.py}).
Checkpoint/export paths take these FS objects so jobs can target local
disk or an HDFS-compatible store with one interface; `recompute` is the
stable alias of the activation-recompute API.
"""
import os
import shutil
import subprocess

__all__ = ["HybridParallelInferenceHelper",
           "LocalFS", "HDFSClient", "recompute", "DistributedInfer",
           "ExecuteError", "FSFileExistsError", "FSFileNotExistsError",
           "FSTimeOut"]


class ExecuteError(Exception):
    pass


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FSTimeOut(Exception):
    pass


class LocalFS:
    """Local filesystem under the reference FS contract (reference
    fs.py:120 LocalFS)."""

    def ls_dir(self, fs_path):
        """Returns (dirs, files) — the reference's two-list shape."""
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, name))
             else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        with open(fs_path, "a"):
            os.utime(fs_path, None)

    def delete(self, fs_path):
        if not self.is_exist(fs_path):
            return
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path)
        else:
            os.unlink(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if self.is_exist(dst_path):
            if not overwrite:
                raise FSFileExistsError(dst_path)
            self.delete(dst_path)
        shutil.move(src_path, dst_path)

    def upload(self, local_path, fs_path):
        self._copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        self._copy(fs_path, local_path)

    @staticmethod
    def _copy(src, dst):
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            shutil.copy2(src, dst)

    def cat(self, fs_path=None):
        with open(fs_path) as f:
            return f.read()

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """HDFS access via the `hadoop fs` CLI (reference fs.py:428 drives
    the same binary through a shell). Raises ExecuteError with the
    command output on failure; needs a hadoop installation on the host
    (TPU pods typically use GCS instead — mount or use LocalFS over a
    FUSE path)."""

    def __init__(self, hadoop_home=None, configs=None,
                 time_out=5 * 60 * 1000, sleep_inter=1000):
        self._hadoop = (os.path.join(hadoop_home, "bin", "hadoop")
                        if hadoop_home else "hadoop")
        self._cfg = []
        for k, v in (configs or {}).items():
            self._cfg += ["-D", f"{k}={v}"]
        self._timeout = time_out / 1000.0  # reference API: milliseconds

    def _run(self, *args):
        cmd = [self._hadoop, "fs"] + self._cfg + list(args)
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=self._timeout)
        except FileNotFoundError:
            raise ExecuteError(
                "hadoop binary not found — HDFSClient needs a hadoop "
                "install (set hadoop_home); on TPU pods prefer GCS")
        except subprocess.TimeoutExpired:
            raise FSTimeOut(" ".join(cmd))
        if r.returncode != 0:
            raise ExecuteError(f"{' '.join(cmd)}: {r.stderr}")
        return r.stdout

    def ls_dir(self, fs_path):
        out = self._run("-ls", fs_path)
        dirs, files = [], []
        for line in out.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except ExecuteError:
            return False

    def is_file(self, fs_path):
        try:
            self._run("-test", "-f", fs_path)
            return True
        except ExecuteError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except ExecuteError:
            return False

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=True):
        # same exception contract as LocalFS.mv — callers handle ONE
        # set of FS errors regardless of backend
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if self.is_exist(fs_dst_path):
            if not overwrite:
                raise FSFileExistsError(fs_dst_path)
            self.delete(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def need_upload_download(self):
        return True

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path) and not exist_ok:
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)


def recompute(function, *args, **kwargs):
    """Stable alias (reference fleet/utils/__init__.py:34 — deprecated
    alias of fleet.recompute)."""
    from ..recompute import recompute as _rc

    return _rc(function, *args, **kwargs)


class DistributedInfer:
    """PS inference helper facade (reference ps_util.py DistributedInfer:
    swaps distributed lookup tables for local ones at inference). In
    this design PS tables already live host-side (`distributed/ps.py`),
    so inference just reads them: init is a no-op and `get_dist_infer_program`
    returns the program unchanged."""

    def __init__(self, main_program=None, startup_program=None):
        self._main = main_program

    def init_distributed_infer_env(self, exe, loss, role_maker=None,
                                   dirname=None):
        return None

    def get_dist_infer_program(self):
        return self._main


from .hybrid_parallel_inference import HybridParallelInferenceHelper  # noqa: E402,F401
from . import hybrid_parallel_util  # noqa: E402,F401
from .hybrid_parallel_util import fused_allreduce_gradients  # noqa: E402,F401
