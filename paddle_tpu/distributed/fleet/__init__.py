"""fleet — the unified distributed facade.

(reference: python/paddle/distributed/fleet/fleet.py:101 `Fleet`,
base/distributed_strategy.py:110 `DistributedStrategy` over protobuf
distributed_strategy.proto:303.) The meta-optimizer pass stack of the
reference (sharding/recompute/amp program rewriting) collapses into
configuration of the ONE compiled SPMD step (parallel_step.py).
"""
from .. import collective as coll
from .. import env as env_mod
from .. import mesh as mesh_mod
from ..parallel_step import DistributedTrainStep, shard_params_and_opt
from . import data_generator  # noqa: F401
from . import dataset  # noqa: F401
from . import elastic  # noqa: F401
from . import meta_optimizers  # noqa: F401
from . import utils  # noqa: F401
from . import topology as topo_mod
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = [
    "init", "is_first_worker", "worker_index", "worker_num",
    "distributed_model", "distributed_optimizer", "DistributedStrategy",
    "HybridCommunicateGroup", "CommunicateTopology", "get_hybrid_communicate_group",
    "DistributedTrainStep", "PipelineParallel", "TensorParallel",
    "ShardingParallel", "fleet",
]


class DistributedStrategy:
    """Dict-backed strategy (reference keeps a protobuf; the knobs kept are
    the ones that exist in the TPU design — hybrid degrees, amp, recompute,
    sharding level, gradient merge)."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sharding_degree": 1, "sp_degree": 1, "ep_degree": 1,
        }
        self.amp = False
        self.amp_configs = {"init_loss_scaling": 32768.0, "use_pure_fp16":
                            False, "use_bf16": True}
        self.recompute = False
        self.recompute_configs = {"checkpoints": []}
        self.sharding = False
        self.sharding_configs = {"stage": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True
        # strategy meta-optimizers (reference meta_optimizers/*)
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001,
                             "lars_weight_decay": 0.0005,
                             "exclude_from_weight_decay": []}
        self.dgc = False
        self.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.01]}
        self.localsgd = False
        self.localsgd_configs = {"k_steps": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = {"init_k_steps": 1}

    def __repr__(self):
        return f"DistributedStrategy(hybrid={self.hybrid_configs})"


class _Fleet:
    def __init__(self):
        self._hcg = None
        self._strategy = None
        self._initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             log_level="INFO"):
        self._strategy = strategy or DistributedStrategy()
        hc = self._strategy.hybrid_configs
        mesh_mod.reset_mesh()
        self._hcg = HybridCommunicateGroup(
            dp_degree=hc.get("dp_degree", 1),
            mp_degree=hc.get("mp_degree", 1),
            pp_degree=hc.get("pp_degree", 1),
            sharding_degree=hc.get("sharding_degree", 1),
            sp_degree=hc.get("sp_degree", 1),
            ep_degree=hc.get("ep_degree", 1),
        )
        self._initialized = True
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def is_first_worker(self):
        return env_mod.get_rank() == 0

    def worker_index(self):
        return env_mod.get_rank()

    def worker_num(self):
        return env_mod.get_world_size()

    def barrier_worker(self):
        coll.barrier()

    def distributed_model(self, model):
        """(reference fleet/model.py:29.) With GSPMD there is nothing to
        wrap for DP/TP — shardings are attached to params/activations; we
        return the model (PipelineParallel wrapping happens in
        meta_parallel when pp_degree>1)."""
        if self._hcg and self._hcg.get_pipe_parallel_world_size() > 1:
            from .meta_parallel.pipeline_parallel import PipelineParallel

            return PipelineParallel(model, self._hcg, self._strategy)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        """(reference fleet.py:996 — runs the meta-optimizer stack.)
        Sharding level from strategy sets the ZeRO placement applied by
        DistributedTrainStep; lars/dgc strategy toggles REPLACE the inner
        optimizer with the corresponding strategy optimizer (the
        reference's LarsOptimizer/DGCOptimizer meta passes), reusing its
        lr and parameter list."""
        st = strategy or self._strategy
        if st is not None and getattr(st, "lars", False):
            from ...optimizer import LarsMomentum

            cfg = st.lars_configs
            optimizer = LarsMomentum(
                optimizer._learning_rate,
                momentum=getattr(optimizer, "_momentum", 0.9),
                lars_coeff=cfg.get("lars_coeff", 0.001),
                lars_weight_decay=cfg.get("lars_weight_decay", 0.0005),
                exclude_from_weight_decay=cfg.get(
                    "exclude_from_weight_decay", []),
                grad_clip=optimizer._grad_clip,
                parameters=optimizer._parameter_list)
        elif st is not None and getattr(st, "dgc", False):
            from .meta_optimizers import DGCMomentum

            sp = st.dgc_configs.get("sparsity", [0.999])
            optimizer = DGCMomentum(
                optimizer._learning_rate,
                momentum=getattr(optimizer, "_momentum", 0.9),
                sparsity=sp[0] if isinstance(sp, (list, tuple)) else sp,
                grad_clip=optimizer._grad_clip,
                parameters=optimizer._parameter_list)
        if st is not None and (getattr(st, "localsgd", False)
                               or getattr(st, "adaptive_localsgd", False)):
            from .meta_optimizers import LocalSGD

            adaptive = getattr(st, "adaptive_localsgd", False)
            cfg = (st.adaptive_localsgd_configs if adaptive
                   else st.localsgd_configs)
            sync = LocalSGD(
                optimizer._parameter_list,
                k_steps=cfg.get("init_k_steps" if adaptive else "k_steps",
                                1),
                adaptive=adaptive)
            optimizer._localsgd = sync
            inner_step = optimizer.step

            def step_with_sync():
                out = inner_step()
                sync.step()
                return out

            optimizer.step = step_with_sync
        optimizer._fleet_strategy = st
        return optimizer

    # -- PS-mode lifecycle (reference fleet.py init_server:~1210,
    # init_worker, run_server, stop_worker; the_one_ps.py runtime). In
    # this design trainers HOST their table shards (id-routed
    # ShardedSparseTable) — there are no separate server processes, so
    # server bring-up reduces to optional checkpoint restore and
    # shutdown to flushing every live table.
    def init_server(self, dirname=None, **kwargs):
        if dirname is not None:
            self.load_model(dirname)

    def run_server(self):
        """No separate server processes: trainers host their shards.
        Kept callable so reference PS scripts run unmodified."""

    def init_worker(self):
        pass  # pull prefetch threads start lazily on first use

    def stop_worker(self):
        from ..ps import live_tables

        for _, t in live_tables():
            if hasattr(t, "flush"):
                t.flush()

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None, mode=0):
        """Save every live PS table's state, keyed by table NAME and
        rank — each rank of a ShardedSparseTable owns a disjoint shard,
        so files must be per-rank or shards clobber each other on a
        shared filesystem (reference fleet.save_persistables →
        server-side per-shard table save)."""
        import os

        import numpy as np

        from .. import env as _env
        from ..ps import live_tables

        if dirname is None:
            raise ValueError(
                "save_persistables needs dirname= (the checkpoint "
                "directory)")
        os.makedirs(dirname, exist_ok=True)
        rank = _env.get_rank()
        for name, t in live_tables():
            if hasattr(t, "flush"):
                t.flush()  # queued async pushes must reach the rows
            sd = t.state_dict()
            np.savez(os.path.join(dirname, f"{name}.rank{rank}.npz"),
                     **{k: np.asarray(v) for k, v in sd.items()})

    def load_model(self, dirname, mode=0):
        import os

        import numpy as np

        from .. import env as _env
        from ..ps import live_tables

        rank = _env.get_rank()
        for name, t in live_tables():
            f = os.path.join(dirname, f"{name}.rank{rank}.npz")
            if os.path.exists(f):
                data = np.load(f)
                t.set_state_dict({k: data[k] for k in data.files})

    @property
    def strategy(self):
        return self._strategy


fleet = _Fleet()

# module-level API mirroring `paddle.distributed.fleet.*`
init = fleet.init
is_first_worker = fleet.is_first_worker
worker_index = fleet.worker_index
worker_num = fleet.worker_num
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
init_server = fleet.init_server
init_worker = fleet.init_worker
run_server = fleet.run_server
stop_worker = fleet.stop_worker
save_persistables = fleet.save_persistables
load_model = fleet.load_model


class TensorParallel:
    """Wrapper parity (reference meta_parallel/tensor_parallel.py:25) —
    GSPMD needs no broadcast: shardings carry placement."""

    def __new__(cls, layers, hcg=None, **kwargs):
        return layers


class ShardingParallel:
    def __new__(cls, layers, hcg=None, **kwargs):
        return layers


def PipelineParallel(layers, hcg=None, strategy=None):
    from .meta_parallel.pipeline_parallel import PipelineParallel as PP

    return PP(layers, hcg, strategy)
