"""fleet.dataset — PS dataset facades + tree index for TDM-style retrieval.

Reference surface: python/paddle/distributed/fleet/dataset/dataset.py
(InMemoryDataset/QueueDataset — re-exported here from
`distributed.api_extra`) and dataset/index_dataset.py:25 (`TreeIndex` over
the C++ index wrapper paddle/fluid/distributed/index_dataset/
index_wrapper.h, layerwise negative sampler index_sampler.h:55
`LayerWiseSampler`).

TPU-native redesign: the reference stores an arbitrary tree in a protobuf
sidecar and walks it with C++ node pointers. Here the tree is a COMPLETE
``branch``-ary array tree in code space — children of code ``c`` are
``c*branch + 1 .. c*branch + branch`` — so every structural query
(ancestor, layer membership, travel path) is O(1) integer arithmetic on
numpy arrays and the layerwise sampler draws distinct negatives per layer
with no pointer chasing. Node embedding ids ARE codes — one consistent id
space for internal nodes and leaves — and ``emb_size()`` is the dense
code-space bound (codes of a complete tree, including unused tail codes),
so the node-embedding table shape is static for XLA regardless of how
many leaves are live. Leaves additionally carry their original
``item_id`` for mapping retrieval scores back to items.
"""
import numpy as np

from ..api_extra import BoxPSDataset, InMemoryDataset, QueueDataset

__all__ = ["InMemoryDataset", "QueueDataset", "BoxPSDataset",
           "Index", "TreeIndex", "IndexNode"]


class Index:
    def __init__(self, name):
        self._name = name


class IndexNode:
    """Lightweight node record (reference: proto IndexNode with
    id/is_leaf/probability). ``id == code`` for every node (the one
    embedding-id space); leaves also carry ``item_id``."""

    __slots__ = ("id", "code", "is_leaf", "item_id", "probability")

    def __init__(self, code, is_leaf, item_id=-1, probability=1.0):
        self.id = int(code)
        self.code = int(code)
        self.is_leaf = bool(is_leaf)
        self.item_id = int(item_id)
        self.probability = float(probability)

    def __repr__(self):
        return (f"IndexNode(code={self.code}, is_leaf={self.is_leaf}, "
                f"item_id={self.item_id})")


class TreeIndex(Index):
    """Complete branch-ary retrieval tree (reference index_dataset.py:25).

    Construct with `TreeIndex(name, path)` where `path` is an ``.npz``
    written by `save()`, or build directly from item ids with
    `TreeIndex.from_items(name, ids, branch=2)`. Leaf order is the order
    of `ids`. Every node's embedding id is its code (`emb_size()` bounds
    them densely); map a scored leaf back to its item via
    `IndexNode.item_id` or `leaf_item_ids()`.
    """

    def __init__(self, name, path=None):
        super().__init__(name)
        self._layerwise_conf = None
        if path is not None:
            data = np.load(path, allow_pickle=False)
            self._init_from(data["ids"], int(data["branch"]))

    @classmethod
    def from_items(cls, name, ids, branch=2):
        t = cls(name)
        t._init_from(np.asarray(ids, np.int64), int(branch))
        return t

    def _init_from(self, ids, branch):
        if branch < 2:
            raise ValueError("branch must be >= 2")
        # own the leaf-id array: np.array copies even when the caller
        # hands us an int64 ndarray it may mutate later (PTL501)
        ids = np.array(ids, np.int64)
        n = len(ids)
        if n == 0:
            raise ValueError("TreeIndex needs at least one item")
        # height = number of levels; leaves live on level height-1
        h = 1
        while branch ** (h - 1) < n:
            h += 1
        self._branch = branch
        self._height = h
        self._leaf_ids = ids
        # code arithmetic: first code of level l
        self._level_first = np.array(
            [(branch ** l - 1) // (branch - 1) for l in range(h + 1)],
            np.int64)
        self._leaf_codes = self._level_first[h - 1] + np.arange(n)
        self._id_to_code = dict(zip(ids.tolist(), self._leaf_codes.tolist()))
        # a code exists iff it is an ancestor-or-self of some leaf
        live = set()
        for c in self._leaf_codes.tolist():
            while c not in live:
                live.add(c)
                if c == 0:
                    break
                c = (c - 1) // branch
        self._live = live
        self._total = len(live)

    # -- structural queries (reference index_dataset.py:38-77) ------------
    def height(self):
        return self._height

    def branch(self):
        return self._branch

    def total_node_nums(self):
        return self._total

    def emb_size(self):
        """Dense embedding-table bound: one row per code of the complete
        tree (live-node ids never reach this, unused tail rows are the
        price of a static table shape)."""
        return int(self._level_first[self._height])

    def leaf_item_ids(self):
        """code -> item id for every leaf, in leaf order."""
        return dict(zip(self._leaf_codes.tolist(), self._leaf_ids.tolist()))

    def _level_of(self, code):
        lvl = int(np.searchsorted(self._level_first, code, side="right")) - 1
        return lvl

    def _node(self, code):
        lvl = self._level_of(code)
        if lvl == self._height - 1:
            idx = code - int(self._level_first[self._height - 1])
            return IndexNode(code, True, item_id=self._leaf_ids[idx])
        return IndexNode(code, False)

    def get_all_leafs(self):
        return [self._node(int(c)) for c in self._leaf_codes]

    def get_nodes(self, codes):
        return [self._node(int(c)) for c in codes]

    def get_layer_codes(self, level):
        lo, hi = int(self._level_first[level]), int(self._level_first[level + 1])
        return [c for c in range(lo, hi) if c in self._live]

    def get_travel_codes(self, id, start_level=0):
        """Leaf-to-`start_level` ancestor chain, leaf first (reference
        TreeIndex::GetTravelCodes)."""
        try:
            c = self._id_to_code[int(id)]
        except KeyError:
            raise ValueError(
                f"unknown item id {id}: not in the tree's leaf set") from None
        res = []
        lvl = self._height - 1
        while lvl >= start_level:
            res.append(c)
            c = (c - 1) // self._branch
            lvl -= 1
        return res

    def get_ancestor_codes(self, ids, level):
        out = []
        for i in ids:
            try:
                c = self._id_to_code[int(i)]
            except KeyError:
                raise ValueError(
                    f"unknown item id {i}: get_ancestor_codes (and "
                    "layerwise_sample with_hierarchy=True) take ITEM ids "
                    "from the tree's leaf set") from None
            for _ in range(self._height - 1 - level):
                c = (c - 1) // self._branch
            out.append(c)
        return out

    def get_children_codes(self, ancestor, level):
        """Descendant codes of `ancestor` at `level` (levels deeper than
        the ancestor's own)."""
        lvl = self._level_of(ancestor)
        lo, hi = ancestor, ancestor
        for _ in range(level - lvl):
            lo = lo * self._branch + 1
            hi = hi * self._branch + self._branch
        return [c for c in range(lo, hi + 1) if c in self._live]

    def get_travel_path(self, child, ancestor):
        res = []
        while child > ancestor:
            res.append(child)
            child = (child - 1) // self._branch
        return res

    def get_pi_relation(self, ids, level):
        return dict(zip(ids, self.get_ancestor_codes(ids, level)))

    # -- persistence ------------------------------------------------------
    def save(self, path):
        np.savez(path, ids=self._leaf_ids, branch=np.int64(self._branch))

    # -- layerwise negative sampling (index_sampler.h:55) -----------------
    def init_layerwise_sampler(self, layer_sample_counts,
                               start_sample_layer=1, seed=0):
        if self._layerwise_conf is not None:
            raise AssertionError("layerwise sampler already initialized")
        if not (0 < start_sample_layer < self._height):
            raise ValueError(
                f"start_sample_layer must be in (0, {self._height})")
        counts, i, cur = [], 0, start_sample_layer
        while cur < self._height:
            counts.append(layer_sample_counts[i]
                          if i < len(layer_sample_counts) else 1)
            cur += 1
            i += 1
        layer_nodes = [np.array(self.get_layer_codes(l), np.int64)
                       for l in range(start_sample_layer, self._height)]
        self._layerwise_conf = (counts, start_sample_layer, layer_nodes,
                                np.random.default_rng(seed))

    def layerwise_sample(self, user_input, index_input, with_hierarchy=False):
        """For each (user features, target item): one positive row per layer
        (the target's ancestor, label 1) + `layer_sample_counts[l]` uniform
        negatives from the same layer (label 0). `with_hierarchy` maps the
        user's item-id features to their ancestors at each layer too.
        Returns rows shaped ``user_feats + [node_id, label]``."""
        if self._layerwise_conf is None:
            raise ValueError("please init layerwise_sampler first.")
        counts, start, layer_nodes, rng = self._layerwise_conf
        out = []
        for feats, target in zip(user_input, index_input):
            travel = self.get_travel_codes(int(target), start)
            if with_hierarchy:
                # one leaf-to-start walk per feature, indexed per layer
                # (at the leaf layer the "ancestor" is the leaf code
                # itself — node ids are codes in EVERY row this emits)
                feat_travel = [self.get_travel_codes(int(f), start)
                               for f in feats]
            # travel is leaf-first; walk top-down over sample layers
            for li, lvl in enumerate(range(start, self._height)):
                pos_code = travel[self._height - 1 - lvl]
                nodes = layer_nodes[li]
                u = feats
                if with_hierarchy:
                    u = [ft[self._height - 1 - lvl] for ft in feat_travel]
                out.append(list(u) + [pos_code, 1])
                k = counts[li]
                if len(nodes) > 1 and k > 0:
                    # distinct negatives; a thin layer yields fewer than k
                    # rather than duplicating (index_sampler.h draws with
                    # replacement — distinct is strictly better here)
                    cand = nodes[nodes != pos_code]
                    neg = (cand if len(cand) <= k
                           else rng.choice(cand, size=k, replace=False))
                    for nc in np.atleast_1d(neg):
                        out.append(list(u) + [int(nc), 0])
        return out
