"""Pipeline-parallel execution.

TPU-native re-design of the reference pipeline runtime
(reference: fleet/meta_parallel/pipeline_parallel.py:31 `PipelineParallel`,
forward_backward_pipeline:105 (1F1B), PipelineParallelWithInterleave:416,
p2p meta handshake pp_utils/p2p_communication.py).

Two layers of function:
1. `PipelineParallel` — API-parity wrapper: micro-batch splitting +
   gradient accumulation around any Layer (`train_batch`). With pp_degree=1
   this is exactly gradient accumulation; stage placement on hardware comes
   from (2).
2. `spmd_pipeline` — the hardware schedule: identical stages' params
   stacked on a leading axis sharded over the 'pp' mesh axis; one
   shard_map program runs the fill-drain (GPipe) rotation with
   `lax.ppermute` moving activations stage→stage over ICI; microbatch loop
   is a `lax.scan`. Differentiating through the scan+ppermute yields the
   reverse pipeline automatically (the reference hand-writes both
   directions). 1F1B's memory profile is recovered with remat
   (jax.checkpoint) instead of schedule interleaving — the compiler
   overlaps the bubble, we trade schedule complexity for rematerialization.
"""
import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ....tensor_core import Tensor
from ... import mesh as mesh_mod

__all__ = ["PipelineParallel", "spmd_pipeline"]


class PipelineParallel:
    """Micro-batched train_batch wrapper (reference train_batch:206)."""

    def __init__(self, layers, hcg=None, strategy=None):
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = getattr(strategy, "pipeline_configs", None) or {}
        self.accumulate_steps = cfg.get("accumulate_steps", 1)
        self.micro_batch_size = cfg.get("micro_batch_size", None)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, tensor, n):
        from ....ops.manipulation import split as t_split

        return t_split(tensor, n, axis=0)

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """Forward+backward over micro-batches with grad accumulation,
        then one optimizer step (matches reference semantics: returns the
        mean loss over micro-batches)."""
        x, y = data
        n = self.accumulate_steps
        xs = self._split_micro(x, n) if n > 1 else [x]
        ys = self._split_micro(y, n) if n > 1 else [y]
        total = 0.0
        loss_fn = getattr(self._layers, "loss_fn", None)
        for xm, ym in zip(xs, ys):
            out = self._layers(xm)
            loss = loss_fn(out, ym) if loss_fn is not None else out.mean()
            from ....ops.math import mean as t_mean

            if loss.ndim > 0:
                loss = t_mean(loss)
            scaled = loss * (1.0 / n)
            if scaler is not None:
                scaler.scale(scaled).backward()
            else:
                scaled.backward()
            total += float(loss.numpy())
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(np.float32(total / n))

    def eval_batch(self, data, compute_loss=True):
        x, y = data
        out = self._layers(x)
        loss_fn = getattr(self._layers, "loss_fn", None)
        if compute_loss and loss_fn is not None:
            return loss_fn(out, y)
        return out


def spmd_pipeline(block_fn, stacked_params, x_micro, *, n_stages=None,
                  remat=True):
    """Fill-drain pipeline over the 'pp' mesh axis as a pure jax function.

    block_fn(stage_params, x) -> y            (one stage's computation)
    stacked_params: pytree whose leaves have leading dim = n_stages
                    (shard leading dim over 'pp' outside via PartitionSpec)
    x_micro: [n_micro, micro_batch, ...] micro-batched input
    returns [n_micro, micro_batch, ...] outputs (from the last stage,
    broadcast to all stages' shards so the caller can continue uniformly).

    Must be called INSIDE jit with stacked_params sharded P('pp', ...).
    The body runs under shard_map over 'pp'.
    """
    mesh = mesh_mod.global_mesh()
    pp = n_stages or mesh.shape["pp"]
    n_micro = x_micro.shape[0]

    if pp == 1:
        def apply_one(x):
            params = jax.tree_util.tree_map(lambda a: a[0], stacked_params)
            return block_fn(params, x)

        return lax.map(apply_one, x_micro)

    blk = jax.checkpoint(block_fn) if remat else block_fn

    def per_stage(params_shard, xs):
        # params_shard leaves: [1, ...] (this stage's slice); xs: all micro
        params = jax.tree_util.tree_map(lambda a: a[0], params_shard)
        stage = lax.axis_index("pp")
        n_ticks = n_micro + pp - 1
        buf = jnp.zeros((n_micro,) + xs.shape[1:], xs.dtype)

        def tick(carry, t):
            out_buf, recv = carry
            # stage 0 feeds microbatch t (while valid); others take recv
            idx = jnp.clip(t, 0, n_micro - 1)
            feed = xs[idx]
            inp = jnp.where(stage == 0, feed, recv)
            out = blk(params, inp)
            # rotate stage s -> s+1 (last stage's output falls off the ring)
            nxt = lax.ppermute(out, "pp",
                               [(i, (i + 1) % pp) for i in range(pp)])
            # last stage stores its tick-(t) output at micro index t-(pp-1)
            store = t - (pp - 1)
            valid = (stage == pp - 1) & (store >= 0)
            out_buf = lax.cond(
                valid,
                lambda b: lax.dynamic_update_index_in_dim(
                    b, out, jnp.maximum(store, 0), 0),
                lambda b: b,
                out_buf,
            )
            return (out_buf, nxt), None

        (outs, _), _ = lax.scan(tick, (buf, jnp.zeros_like(xs[0])),
                                jnp.arange(n_ticks))
        # broadcast last stage's collected outputs to every stage shard
        # (psum of a one-hot-by-stage selection = broadcast over ICI)
        outs = lax.psum(
            jnp.where(stage == pp - 1, outs, jnp.zeros_like(outs)), "pp")
        return outs

    sm = jax.shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(_stack_spec(stacked_params), P(*([None] * x_micro.ndim))),
        out_specs=P(*([None] * x_micro.ndim)),
        check_vma=False,
    )
    return sm(stacked_params, x_micro)


def _stack_spec(tree):
    return jax.tree_util.tree_map(
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), tree)
