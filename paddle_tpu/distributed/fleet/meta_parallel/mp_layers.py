"""Megatron-style tensor-parallel layers, GSPMD-first.

TPU-native re-design of the reference mpu layers
(reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:39, ColumnParallelLinear:155, RowParallelLinear:293,
ParallelCrossEntropy:438; comm primitives mp_ops.py _c_identity/_c_concat/
_mp_allreduce; CUDA ops c_embedding_op, c_softmax_with_cross_entropy_op).

Design difference, by intent: the reference manually slices weights
per-rank and inserts collective ops. Here every layer holds the FULL
logical weight annotated with a PartitionSpec on the 'mp' mesh axis; the
XLA SPMD partitioner materializes per-device shards and inserts the same
all-reduces/all-gathers (over ICI) that Megatron does by hand — and fuses
them with the matmuls. The layer API (gather_output, input_is_parallel)
is preserved so reference model code ports unchanged. Under shard_map
(explicit mode) the same layers lower to lax collectives via the
paddle_tpu.distributed.collective API.
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .... import nn
from ....nn import functional as F
from ....ops._helpers import apply_jfn, ensure_tensor
from ....tensor_core import Tensor
from ... import collective as coll
from ... import mesh as mesh_mod

__all__ = [
    "VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
    "ParallelCrossEntropy", "mark_sharding", "shard_activation",
]


def mark_sharding(param, *spec):
    """Attach a PartitionSpec to a parameter and (eagerly) place it."""
    param._pspec = P(*spec)
    mesh = mesh_mod.global_mesh()
    if any(s is not None for s in param._pspec) and not isinstance(
            param._value, jax.core.Tracer):
        try:
            param._value = jax.device_put(
                param._value, jax.sharding.NamedSharding(mesh, param._pspec))
        except Exception:  # ptlint: disable=PTL804 (placement is advisory; spec kept for jit)
            pass  # single-device or incompatible mesh: spec kept for jit
    return param


def shard_activation(x, *spec):
    """with_sharding_constraint on an activation (no-op on 1-device mesh).

    Axis names whose mesh size does not divide the annotated dim are
    dropped — the spec is a layout hint, and e.g. a 4-head model on an
    mp=8 mesh should fall back to replicating heads, not error."""
    x = ensure_tensor(x)
    mesh = mesh_mod.global_mesh()
    if all(n == 1 for n in mesh.shape.values()):
        return x
    spec = tuple(
        s if (s is None or d % mesh.shape[s] == 0) else None
        for s, d in zip(spec, x.shape)
    )
    sh = jax.sharding.NamedSharding(mesh, P(*spec))

    def jfn(v):
        return jax.lax.with_sharding_constraint(v, sh)

    return apply_jfn("shard_activation", jfn, x)


def split_fused_qkv(qkv, batch, seq, num_heads, head_dim):
    """[b, s, 3·d] fused-qkv (mp-sharded last dim) → (q, k, v) each
    [b, s, nh, hd] with heads riding 'mp' and sequence free to ride
    'sp' — the one attention input layout every transformer here uses."""
    from ....ops import manipulation as manip

    qkv = manip.reshape(qkv, [batch, seq, 3, num_heads, head_dim])
    out = []
    for i in range(3):
        t = manip.squeeze(manip.slice(qkv, [2], [i], [i + 1]), [2])
        out.append(shard_activation(t, "dp", "sp", "mp", None))
    return tuple(out)


class VocabParallelEmbedding(nn.Layer):
    """Embedding with the vocab dimension sharded over 'mp'
    (reference mp_layers.py:39: per-rank vocab range + masked lookup +
    allreduce; here: row-sharded weight, XLA partitions the gather)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._num = num_embeddings
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=nn.initializer.Normal(0.0, 0.02))
        mark_sharding(self.weight, "mp", None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return shard_activation(out, *(["dp"] + [None] * (out.ndim - 1)))


class ColumnParallelLinear(nn.Layer):
    """Linear with the OUTPUT dim sharded over 'mp'
    (reference mp_layers.py:155). gather_output=False leaves activations
    mp-sharded for a following RowParallelLinear."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        mark_sharding(self.weight, None, "mp")
        if has_bias is None or has_bias:
            self.bias = self.create_parameter(
                shape=[out_features], is_bias=True)
            mark_sharding(self.bias, "mp")
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            return shard_activation(out, *(["dp"] + [None] * (out.ndim - 1)))
        # keep last dim sharded on mp for the following RowParallelLinear
        spec = ["dp"] + [None] * (out.ndim - 2) + ["mp"]
        return shard_activation(out, *spec)


class RowParallelLinear(nn.Layer):
    """Linear with the INPUT dim sharded over 'mp'
    (reference mp_layers.py:293: partial matmul + allreduce — XLA inserts
    exactly that reduce when input activations are mp-sharded)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr)
        mark_sharding(self.weight, "mp", None)
        self.bias = self.create_parameter(
            shape=[out_features], is_bias=True) if has_bias else None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        return shard_activation(out, *(["dp"] + [None] * (out.ndim - 1)))


class ParallelCrossEntropy(nn.Layer):
    """Vocab-parallel softmax CE (reference mp_layers.py:438 →
    c_softmax_with_cross_entropy_op). GSPMD: plain CE over mp-sharded
    logits; the partitioner reduces max/sum over the vocab shards."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
