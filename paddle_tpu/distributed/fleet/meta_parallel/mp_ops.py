"""Tensor-parallel communication primitives for explicit-SPMD regions.

TPU-native counterpart of the reference mpu comm ops
(reference: python/paddle/distributed/fleet/layers/mpu/mp_ops.py —
`_c_identity`: identity forward / allreduce backward, and
`_mp_allreduce`: allreduce forward / identity backward). The GSPMD layers
in mp_layers.py don't need these — the partitioner inserts collectives
from sharding annotations. Inside a `shard_map` (the 1F1B pipeline body,
ring attention, …) collectives are explicit, and the VJP pairing matters:

  copy_to_mp(x)      enters an mp-parallel region. Forward is identity
                     (x is replicated over 'mp'); backward psums the
                     per-shard partial cotangents so dx is replicated
                     again. Place on the INPUT of a column-parallel
                     matmul.
  allreduce_mp(x)    leaves an mp-parallel region. Forward psums the
                     partial products of a row-parallel matmul; backward
                     is identity — every shard's downstream computation
                     of the cotangent is replicated, so the cotangent is
                     already the right per-shard value. Place on the
                     OUTPUT of a row-parallel matmul.

Relying on jax's default transpose of `lax.psum` under
`check_vma=False` instead of this explicit pairing silently multiplies
gradients by the axis size (psum transposes to psum); the custom_vjp
forms below pin the Megatron-correct semantics.
"""
import functools

import jax
from jax import lax

__all__ = ["copy_to_mp", "allreduce_mp"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_mp(x, axis="mp"):
    """Identity forward, psum-over-`axis` backward
    (reference mp_ops.py `_c_identity`)."""
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, g):
    return (lax.psum(g, axis),)


copy_to_mp.defvjp(_copy_fwd, _copy_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def allreduce_mp(x, axis="mp"):
    """psum-over-`axis` forward, identity backward
    (reference mp_ops.py `_mp_allreduce`)."""
    return lax.psum(x, axis)


def _ar_fwd(x, axis):
    return lax.psum(x, axis), None


def _ar_bwd(axis, _, g):
    return (g,)


allreduce_mp.defvjp(_ar_fwd, _ar_bwd)
