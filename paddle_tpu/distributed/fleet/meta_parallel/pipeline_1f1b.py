"""1F1B pipeline parallelism as ONE SPMD program.

TPU-native re-design of the reference 1F1B runtime
(reference: fleet/meta_parallel/pipeline_parallel.py:105
`forward_backward_pipeline` — warmup fwd / steady 1F1B / cooldown bwd over
NCCL p2p, with `PipelineParallelWithInterleave:416` for virtual stages).

Design (no per-rank processes, no send/recv ops): the whole fwd+bwd
schedule is a single `lax.scan` inside `shard_map` over the 'pp' mesh axis.
Each tick, every stage does one forward micro-step AND one backward
micro-step (lockstep 1F1B); activations move stage→stage with
`lax.ppermute` over ICI, cotangents move with the reverse permutation.
Backward is hand-scheduled: each stage re-linearizes its block for the
micro-batch leaving flight (remat — only the stage INPUT is kept, in a ring
buffer of 2·pp−1 slots), so peak activation memory is O(pp) per stage,
independent of the number of micro-batches — the 1F1B memory property.
The schedule timing:

    stage s forwards micro m at tick  t = m + s
    stage s backwards micro m at tick t = m + 2(pp−1) − s

(last stage: fwd and bwd of a micro land on the same tick, exactly 1F1B;
total ticks M + 2(pp−1) vs GPipe's 2(M + pp − 1) serialized halves.)

The whole thing is wrapped in jax.custom_vjp so outer autodiff composes:
heterogeneous pre-stages (embedding) differentiate through the returned
input cotangents, and head/loss params (possibly TIED to the embedding)
get grads from the last stage's vjp — weight tying needs no shared-weight
allreduce (reference pp_utils/utils.py FusedAllReduceBuffer): both paths'
grads meet in the outer AD sum.
"""
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import mesh as mesh_mod

__all__ = ["pipeline_1f1b", "pipeline_forward_loss",
           "interleaved_pipeline_loss", "interleaved_stacking_order",
           "schedule_ticks", "PipelineSpecs"]


class PipelineSpecs(NamedTuple):
    """Per-leaf PartitionSpecs for a hybrid (pp × mp × dp) pipeline run.

    Hashable (tuples of PartitionSpec) so it can ride custom_vjp
    nondiff_argnums without retracing. `stacked`/`post` are the specs of
    `tree_leaves(stacked_params)` / `tree_leaves(post_params)` IN LEAF
    ORDER (every stacked spec must lead with 'pp'); `x`/`y` shard the
    micro-batched inputs (e.g. P(None, 'dp', None, None) to data-shard
    the within-micro batch dim); `dp_axis` names the mesh axis to
    pmean losses/grads over (the reference's DP allreduce —
    fleet/meta_parallel/.../pipeline_parallel.py composes pp with the
    dp communicator the same way).
    """
    stacked: Optional[Tuple] = None
    post: Optional[Tuple] = None
    x: Optional[P] = None
    y: Optional[P] = None
    dp_axis: Optional[str] = None
    # axes over which the per-shard loss is a PARTIAL SUM of the global
    # loss (e.g. 'sp': each sequence shard computes masked_sum/global_N):
    # loss and param grads are psum'd; input cotangents need NO scaling
    # (the block's own collective transposes already deliver cross-shard
    # contributions) — contrast dp_axis, whose shards each compute a
    # full mean and therefore pmean + 1/dp-scale.
    sum_axes: Optional[Tuple[str, ...]] = None
    # quantize the dp-axis gradient pmean: the block-scaled int8
    # all-reduce of distributed.quant_collective replaces the fp32
    # pgrads/hgrads pmean (EQuARX in-XLA; loss/aux scalars stay exact).
    # Hashable bool — rides custom_vjp nondiff_argnums like the rest.
    quant_dp: bool = False


def _unflatten_like(tree, leaf_specs, default_fn, require_pp=False):
    """Spec pytree matching `tree`: from `leaf_specs` (tuple in leaf
    order) or `default_fn(leaf)` when leaf_specs is None. With
    `require_pp`, every spec must lead with 'pp' (stage-stacked leaves) —
    checked on BOTH the training and forward-only entry points, since a
    missing 'pp' silently mis-shards instead of erroring."""
    if leaf_specs is None:
        tree = jax.tree_util.tree_map(default_fn, tree)
    else:
        treedef = jax.tree_util.tree_structure(tree)
        if treedef.num_leaves != len(leaf_specs):
            raise ValueError(
                f"PipelineSpecs has {len(leaf_specs)} leaf specs, params "
                f"have {treedef.num_leaves} leaves")
        tree = jax.tree_util.tree_unflatten(treedef, list(leaf_specs))
    if require_pp:
        for leaf in jax.tree_util.tree_leaves(
                tree, is_leaf=lambda s: isinstance(s, P)):
            if len(leaf) == 0 or leaf[0] != "pp":
                raise ValueError(
                    f"stacked spec {leaf} must lead with the 'pp' axis")
    return tree


def _tree_zeros(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def _tree_add_masked(acc, new, valid):
    return jax.tree_util.tree_map(
        lambda a, n: a + jnp.where(valid, n, jnp.zeros_like(n)), acc, new)


def schedule_ticks(M, pp, num_virtual=1):
    """Scan length of the (interleaved) 1F1B lockstep schedule.

    For M divisible by pp this is M·V + (V+1)·pp − 2 — the PROVABLE minimum
    for a barrier-synchronous schedule where every tick runs one forward and
    one backward chunk-step per device: the last work unit's forward cannot
    start before tick M·V−1 (M·V units enter stage 0 one per tick), finishes
    on the last stage at M·V+pp−2, and its cotangent then has to traverse
    all V·pp logical stages, one hop per tick. At V=1 this is the classic
    M + 2(pp−1). (The reference's asynchronous interleave —
    pipeline_parallel.py:488 — quotes a bubble of 2(pp−1)/V in *half*-slot
    units; that relies on per-device free-running progress, which a
    ppermute-synchronized SPMD program cannot express without making every
    slot cost max(fwd, bwd). The lockstep optimum realized here cuts the
    1F1B bubble from 2V(pp−1) — V serial fill-drain passes — to
    (V+1)·pp − 2, and keeps activation memory O(V·pp), independent of M.)
    """
    V = num_virtual
    qh, rh = divmod(M - 1, pp)
    return qh * V * pp + (V - 1) * pp + rh + (V + 1) * pp - 1


def _run_schedule(block_fn, loss_fn, stacked_params, post_params, x_micro,
                  y_micro, pp, remat, num_virtual=1, dp_axis=None,
                  sum_axes=None, aux_weight=None, quant_dp=False):
    """Inside shard_map over 'pp'. Returns (loss, aux, param_grads,
    post_grads, dx_micro).

    aux_weight: when not None, block_fn returns (y, aux_scalar) — an
    auxiliary loss produced INSIDE the stage body (e.g. the MoE
    load-balancing term, reference moe_layer.py gates) — and the total
    loss becomes mean_loss + aux_weight·mean_aux. The aux accumulator
    rides the same carry as loss_sum; its gradient is seeded into each
    backward tick's block vjp (cotangent aux_weight per valid unit), so
    aux grads flow through the identical psum/pmean reductions as the
    loss grads. The aux value follows the loss's partial-sum convention
    under sum_axes (blocks must pre-scale, as loss_fn does).

    Generalized tick-interleaved schedule (reference:
    fleet/meta_parallel/pipeline_parallel.py:416
    PipelineParallelWithInterleave / interleave_pipeline:488). With V
    virtual chunks per stage, micro-batch m = q·pp + r traverses logical
    stage v·pp + s (chunk v on device s) as work unit

        u(m, v) = q·V·pp + v·pp + r          forward at tick u + s .

    Consecutive chunks of a micro are exactly pp units apart, so chunk v+1
    on device 0 consumes the ring value device pp−1 produced for chunk v
    one tick earlier — the SAME single ppermute ring as V=1. Backward
    reverses chunk order within each pp-micro group,

        β(m, v) = q·V·pp + (V−1−v)·pp + r    backward at tick
                                             (V·pp−1) + β + (pp−1) − s ,

    which makes the cotangent of (m, v) arrive on device pp−1 exactly one
    tick after device 0 finishes (m, v+1) — again the unmodified reverse
    ring. Every formula reduces to the V=1 1F1B schedule (fwd t = m + s,
    bwd t = m + 2(pp−1) − s) when V == 1.

    Params: for V == 1 `stacked_params` is this stage's chunk pytree as
    before; for V > 1 its leaves carry a leading [V] axis (chunk v of this
    stage at index v — rows of the global [pp·V] stack ordered by
    `interleaved_stacking_order`), selected per tick with a dynamic slice.

    The head/loss vjp runs under `lax.cond`, only on the device/tick pairs
    that actually need it (last stage, last chunk) — on every other stage
    it previously burned a full head vjp per tick (vocab-sized matmuls).
    """
    V = num_virtual
    params = stacked_params
    stage = lax.axis_index("pp")
    M = x_micro.shape[0]
    Vpp = V * pp
    qh, rh = divmod(M - 1, pp)
    u_max = qh * Vpp + (V - 1) * pp + rh   # last valid work unit / β index
    T = schedule_ticks(M, pp, V)
    # Slots: in-flight units at one device span a u-window < 2·V·pp − 1
    # (forward is u-ordered, backward β-ordered with |u − β| ≤ (V−1)·pp),
    # so slot = u mod S never collides. V=1 → the familiar 2·pp − 1.
    S = 2 * Vpp - 1

    # remat: False -> off, True -> keep nothing, str/callable -> policy
    from ..recompute import checkpoint_policy

    has_aux = aux_weight is not None
    aw = float(aux_weight) if has_aux else 0.0
    # The block's aux is GLOBAL (its statistics are reduced over dp and
    # the sum_axes; value pre-scaled 1/prod(sum_axes)), so each rank's
    # vjp yields only its PARTIAL of d(aux)/dθ on the pre-scaled output.
    # The grads then ride the loss reductions (psum over sum_axes, pmean
    # over dp, ×1/dp on dx) — seeding the cotangent with
    # aw·|sum_axes|·|dp| makes those reductions reassemble exactly
    # aw·d(aux_global).
    aux_seed = aw
    if has_aux:
        if dp_axis is not None:
            aux_seed *= mesh_mod.axis_size(dp_axis)
        for ax in (sum_axes or ()):
            aux_seed *= mesh_mod.axis_size(ax)
    blk0 = (block_fn if has_aux
            else (lambda p, x: (block_fn(p, x), jnp.zeros([], jnp.float32))))
    blk = (jax.checkpoint(blk0, policy=checkpoint_policy(remat))
           if remat else blk0)
    micro_shape = x_micro.shape[1:]

    def chunk_params(v):
        if V == 1:
            return params
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, v, 0, keepdims=False),
            params)

    def decode(idx):
        """idx (clipped to [0, u_max]) → (q, v_or_vr, r)."""
        q, rem = idx // Vpp, idx % Vpp
        return q, rem // pp, rem % pp

    def tick(carry, t):
        (saved, pgrads, hgrads, dxs, loss_sum, aux_sum, fwd_recv,
         bwd_recv) = carry

        # ---------------- forward micro-step ----------------
        u = t - stage
        u_c = jnp.clip(u, 0, u_max)
        qf, vf, rf = decode(u_c)
        mf = qf * pp + rf
        fwd_valid = (u >= 0) & (u <= u_max) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        x_in = jnp.where((stage == 0) & (vf == 0), x_micro[mf_c], fwd_recv)
        out, aux_f = blk(chunk_params(vf), x_in)
        aux_sum = aux_sum + jnp.where(fwd_valid, aux_f,
                                      0.0).astype(jnp.float32)
        # only save valid units: clipped ticks must not overwrite a slot
        # whose unit is still awaiting backward
        saved = lax.cond(
            fwd_valid,
            lambda b: lax.dynamic_update_index_in_dim(b, x_in, u_c % S, 0),
            lambda b: b,
            saved,
        )

        # ---------------- backward micro-step ----------------
        b = t + stage - Vpp - pp + 2
        b_c = jnp.clip(b, 0, u_max)
        qb, vrb, rb = decode(b_c)
        vb = (V - 1) - vrb
        mb = qb * pp + rb
        bwd_valid = (b >= 0) & (b <= u_max) & (mb < M)
        mb_c = jnp.clip(mb, 0, M - 1)
        u_b = qb * Vpp + vb * pp + rb       # forward index of this unit
        x_saved = saved[u_b % S]
        y_mb = y_micro[mb_c]

        # ONE re-linearization of the block per tick; the last stage's
        # boundary cotangent comes from a vjp of just the head+loss on the
        # block output (gated: other stages/chunks skip it entirely),
        # interior logical stages use the received cotangent.
        params_b = chunk_params(vb)
        (out_b, _aux_b), vjp_blk = jax.vjp(blk, params_b, x_saved)
        is_head = (stage == pp - 1) & (vb == V - 1) & bwd_valid

        def head_branch(ob, y):
            loss_val, vjp_head = jax.vjp(
                lambda o, hp: loss_fn(o, y, hp), ob, post_params)
            d_out, dh_l = vjp_head(jnp.ones_like(loss_val))
            return loss_val.astype(jnp.float32), d_out, dh_l

        def skip_branch(ob, y):
            return (jnp.zeros([], jnp.float32), jnp.zeros_like(ob),
                    _tree_zeros(post_params))

        loss_val, d_out, dh_l = lax.cond(
            is_head, head_branch, skip_branch, out_b, y_mb)
        cot = jnp.where(is_head, d_out, bwd_recv)
        # aux cotangent per valid backward unit — aux grads accumulate
        # into pgrads/dx on exactly the loss grads' reduction path (see
        # aux_seed above for the dp/sum_axes scaling)
        aux_cot = jnp.where(bwd_valid, jnp.float32(aux_seed),
                            jnp.float32(0.0))
        dparams, dx = vjp_blk((cot, aux_cot))

        if V == 1:
            pgrads = _tree_add_masked(pgrads, dparams, bwd_valid)
        else:
            g_old = jax.tree_util.tree_map(
                lambda g: lax.dynamic_index_in_dim(g, vb, 0,
                                                   keepdims=False), pgrads)
            g_new = _tree_add_masked(g_old, dparams, bwd_valid)
            pgrads = jax.tree_util.tree_map(
                lambda g, n: lax.dynamic_update_index_in_dim(g, n, vb, 0),
                pgrads, g_new)
        # loss_val / dh_l are exactly zero off the head ticks (cond)
        hgrads = jax.tree_util.tree_map(lambda a, d: a + d, hgrads, dh_l)
        loss_sum = loss_sum + loss_val
        dxs = lax.cond(
            bwd_valid & (stage == 0) & (vb == 0),
            lambda bf: lax.dynamic_update_index_in_dim(bf, dx, mb_c, 0),
            lambda bf: bf,
            dxs,
        )

        # ---------------- ring communication ----------------
        fwd_recv = lax.ppermute(
            out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
        bwd_recv = lax.ppermute(
            dx, "pp", [(i, (i - 1) % pp) for i in range(pp)])
        return (saved, pgrads, hgrads, dxs, loss_sum, aux_sum, fwd_recv,
                bwd_recv), None

    init = (
        jnp.zeros((S,) + micro_shape, x_micro.dtype),       # saved inputs
        _tree_zeros(params),                                # param grads
        _tree_zeros(post_params),                           # head grads
        jnp.zeros_like(x_micro),                            # input cotangents
        jnp.zeros([], jnp.float32),                         # loss sum
        jnp.zeros([], jnp.float32),                         # aux sum
        jnp.zeros(micro_shape, x_micro.dtype),              # fwd ring reg
        jnp.zeros(micro_shape, x_micro.dtype),              # bwd ring reg
    )
    (saved, pgrads, hgrads, dxs, loss_sum, aux_sum, _, _), _ = lax.scan(
        tick, init, jnp.arange(T))

    # replicate stage-local results: loss/head-grads live on the last
    # stage, dx on stage 0 — psum of the masked values broadcasts them.
    # Each micro was seeded with cotangent 1.0, so grads of the MEAN loss
    # need the 1/M factor. Each stage accumulated ITS chunks' aux, so the
    # pp-psum assembles aux across the whole layer stack.
    loss = lax.psum(loss_sum, "pp") / M
    aux = lax.psum(aux_sum, "pp") / M
    inv_m = 1.0 / M
    pgrads = jax.tree_util.tree_map(lambda g: g * inv_m, pgrads)
    hgrads = jax.tree_util.tree_map(
        lambda g: lax.psum(g, "pp") * inv_m, hgrads)
    dxs = lax.psum(dxs, "pp") * inv_m
    if sum_axes:
        # partial-sum shards (sequence parallelism): the global loss is
        # the SUM over shards; grads likewise (standard SPMD AD — each
        # shard holds a partial of dθ). dx needs no touch-up: the
        # block's ring-collective transposes already routed cross-shard
        # cotangent contributions.
        for ax in sum_axes:
            loss = lax.psum(loss, ax)
            aux = lax.psum(aux, ax)
            pgrads = jax.tree_util.tree_map(
                lambda g, _ax=ax: lax.psum(g, _ax), pgrads)
            hgrads = jax.tree_util.tree_map(
                lambda g, _ax=ax: lax.psum(g, _ax), hgrads)
    if dp_axis is not None:
        # data parallel composed into the SAME program: each dp shard ran
        # the schedule on its slice of every micro-batch, so the global
        # loss is the mean over shards and param grads are pmean'd (the
        # reference's DP allreduce, fused here by XLA with the schedule).
        # dx stays dp-sharded — each shard owns its slice's cotangent of
        # the GLOBAL mean loss, hence the 1/dp factor.
        inv_dp = 1.0 / mesh_mod.axis_size(dp_axis)
        loss = lax.pmean(loss, dp_axis)
        aux = lax.pmean(aux, dp_axis)
        if quant_dp:
            # block-scaled int8 all-reduce of the WHOLE grad tree
            # (pgrads + hgrads fused into one payload) — the EQuARX
            # in-XLA path; the scalar loss/aux reductions above stay
            # exact fp32 (distributed.quant_collective, ROADMAP item 2)
            from ...quant_collective import quantized_pmean_tree

            pgrads, hgrads = quantized_pmean_tree(
                (pgrads, hgrads), dp_axis)
        else:
            pgrads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), pgrads)
            hgrads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), hgrads)
        dxs = dxs * inv_dp
    return loss + aw * aux, aux, pgrads, hgrads, dxs


def pipeline_forward_loss(block_fn, loss_fn, stacked_params, post_params,
                          batch, specs=None, aux_weight=None):
    """Forward-only fill-drain pipeline loss (eval path — no gradient
    machinery, M + pp − 1 ticks instead of the 1F1B schedule's fwd+bwd).
    `specs` composes mp/dp exactly as in `pipeline_1f1b`. With
    `aux_weight`, block_fn returns (y, aux) and the result is the pair
    (loss + aux_weight·mean_aux, mean_aux)."""
    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    has_aux = aux_weight is not None
    aw = float(aux_weight) if has_aux else 0.0
    blk = (block_fn if has_aux else
           (lambda p, x: (block_fn(p, x), jnp.zeros([], jnp.float32))))
    x_micro, y_micro = batch
    M = x_micro.shape[0]
    if pp == 1:
        def one(x, y):
            out, a = blk(stacked_params, x)
            return loss_fn(out, y, post_params), a

        losses, auxs = jax.vmap(one)(x_micro, y_micro)
        aux = jnp.mean(auxs)
        loss = jnp.mean(losses) + aw * aux
        return (loss, aux) if has_aux else loss
    sp = specs if specs is not None else PipelineSpecs()

    def per_stage(params, post_params, xs, ys):
        stage = lax.axis_index("pp")
        T = M + pp - 1

        def tick(carry, t):
            loss_sum, aux_sum, fwd_recv = carry
            mf = t - stage
            valid = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            x_in = jnp.where(stage == 0, xs[mf_c], fwd_recv)
            out, aux_f = blk(params, x_in)
            lv = loss_fn(out, ys[mf_c], post_params)
            loss_sum = loss_sum + jnp.where(
                valid & (stage == pp - 1), lv, 0.0).astype(jnp.float32)
            aux_sum = aux_sum + jnp.where(valid, aux_f,
                                          0.0).astype(jnp.float32)
            fwd_recv = lax.ppermute(
                out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (loss_sum, aux_sum, fwd_recv), None

        (loss_sum, aux_sum, _), _ = lax.scan(
            tick, (jnp.zeros([], jnp.float32), jnp.zeros([], jnp.float32),
                   jnp.zeros(xs.shape[1:], xs.dtype)), jnp.arange(T))
        loss = lax.psum(loss_sum, "pp") / M
        aux = lax.psum(aux_sum, "pp") / M
        for ax in (sp.sum_axes or ()):
            loss = lax.psum(loss, ax)
            aux = lax.psum(aux, ax)
        if sp.dp_axis is not None:
            loss = lax.pmean(loss, sp.dp_axis)
            aux = lax.pmean(aux, sp.dp_axis)
        return loss + aw * aux, aux

    stack_spec = _unflatten_like(
        stacked_params, sp.stacked,
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), require_pp=True)
    post_spec = _unflatten_like(
        post_params, sp.post, lambda a: P(*([None] * a.ndim)))
    x_spec = sp.x if sp.x is not None else P(*([None] * x_micro.ndim))
    y_spec = sp.y if sp.y is not None else P(*([None] * y_micro.ndim))
    run = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(stack_spec, post_spec, x_spec, y_spec),
        out_specs=(P(), P()),
        check_vma=False,
    )
    # ALWAYS jit the shard_map: this jax version cannot evaluate a
    # shard_map whose body stages closed_calls (remat/custom_vjp) outside
    # a jit — and the eager eval path reaches here under jax.vjp's trace
    # (engine.apply linearizes), which is equally unsupported. Under an
    # outer jit the nested pjit is inlined by XLA; standalone it compiles
    # the schedule.
    run = jax.jit(run)
    loss, aux = run(stacked_params, post_params, x_micro, y_micro)
    return (loss, aux) if has_aux else loss


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 5, 6, 7, 8))
def pipeline_1f1b(block_fn, loss_fn, stacked_params, post_params, batch,
                  remat=True, num_virtual=1, specs=None, aux_weight=None):
    """Differentiable 1F1B pipeline loss.

    block_fn(stage_params, x) -> y   one stage's pure forward; stage_params
        are `stacked_params` leaves with the leading (stage-sharded) axis
        REMOVED by shard_map slicing... i.e. leaves [L/pp, ...] for leaves
        stacked [L, ...] — block_fn decides how to consume its slice
        (typically lax.scan over the per-stage sub-layers).
    loss_fn(y, labels, post_params) -> scalar   last-stage head + loss.
    stacked_params: pytree, leading dim divisible by pp, sharded P('pp').
    post_params: pytree (head weights — may alias embedding weights in the
        OUTER function for tying).
    batch: (x_micro [M, ...], y_micro [M, ...]) — micro-batched input
        activations and labels.
    specs: optional PipelineSpecs composing tensor parallelism INSIDE the
        stage blocks (mp-sharded weight leaves; block_fn/loss_fn use the
        mp_ops collectives) and data parallelism across the within-micro
        batch dim — the reference's hybrid TP+PP+DP flagship
        (fleet/meta_parallel/pipeline_parallel.py:105 with mp_layers
        ColumnParallel/RowParallel inside each stage) as ONE SPMD program.

    Returns the mean micro-batch loss. Differentiable w.r.t.
    stacked_params, post_params and x_micro (so an embedding stage in the
    caller composes through outer AD).

    aux_weight: when not None, block_fn must return (y, aux) and the
    result is the PAIR (loss + aux_weight·mean_aux, mean_aux). The
    second element is a DETACHED metric — its gradient contribution is
    already inside the first element; differentiate the first only.
    """
    loss, aux, _, _, _ = _pipeline_call(block_fn, loss_fn, stacked_params,
                                        post_params, batch, remat,
                                        num_virtual, specs, aux_weight)
    return loss if aux_weight is None else (loss, aux)


def _pipeline_call(block_fn, loss_fn, stacked_params, post_params, batch,
                   remat, num_virtual=1, specs=None, aux_weight=None):
    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    V = num_virtual
    has_aux = aux_weight is not None
    aw = float(aux_weight) if has_aux else 0.0
    x_micro, y_micro = batch
    if pp == 1:
        # degenerate: straight-line execution, still micro-batched.
        # remat is honored here too — a 1-chip run of a large model
        # (the gpt1p3b bench arm) needs the same activation economy as
        # the pipelined path.
        from ..recompute import checkpoint_policy

        blk0 = (block_fn if has_aux else
                (lambda p, x: (block_fn(p, x),
                               jnp.zeros([], jnp.float32))))
        blk1 = (jax.checkpoint(blk0, policy=checkpoint_policy(remat))
                if remat else blk0)

        def apply_chunks(sp, x):
            aux = jnp.zeros([], jnp.float32)
            if V == 1:
                x, aux = blk1(sp, x)
                return x, aux
            for v in range(V):
                x, a = blk1(
                    jax.tree_util.tree_map(lambda a_, _v=v: a_[_v], sp), x)
                aux = aux + a
            return x, aux

        def full(sp, hp, xm):
            def one(x, y):
                out, a = apply_chunks(sp, x)
                return loss_fn(out, y, hp), a

            losses, auxs = jax.vmap(one)(xm, y_micro)
            aux = jnp.mean(auxs)
            return jnp.mean(losses) + aw * aux, aux

        (loss, aux), vjp = jax.vjp(full, stacked_params, post_params,
                                   x_micro)
        pg, hg, dx = vjp((jnp.ones_like(loss), jnp.zeros_like(aux)))
        return loss, aux, pg, hg, dx

    sp = specs if specs is not None else PipelineSpecs()
    stack_spec = _unflatten_like(
        stacked_params, sp.stacked,
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), require_pp=True)
    post_spec = _unflatten_like(
        post_params, sp.post, lambda a: P(*([None] * a.ndim)))
    x_spec = sp.x if sp.x is not None else P(*([None] * x_micro.ndim))
    y_spec = sp.y if sp.y is not None else P(*([None] * y_micro.ndim))

    # For V > 1 the stage's shard of the [pp·V] stack is its V chunks in
    # order (rows [s·V, (s+1)·V), see interleaved_stacking_order) — exactly
    # the leading-[V] layout _run_schedule selects from per tick.
    run = jax.shard_map(
        functools.partial(_run_schedule, block_fn, loss_fn, pp=pp,
                          remat=remat, num_virtual=V, dp_axis=sp.dp_axis,
                          sum_axes=sp.sum_axes, aux_weight=aux_weight,
                          quant_dp=sp.quant_dp),
        mesh=mesh,
        in_specs=(stack_spec, post_spec, x_spec, y_spec),
        out_specs=(P(), P(), stack_spec, post_spec, x_spec),
        check_vma=False,
    )
    # ALWAYS jit (see pipeline_forward_loss): shard_map bodies with
    # closed_calls (the remat'd blocks / custom_vjp collectives) cannot
    # run outside jit on this jax version, and eager model.loss() calls
    # arrive here under jax.vjp's trace, not a jit. Under TrainStep the
    # nested pjit is inlined at the (cached) outer trace; a PURE-eager
    # loop retraces per call because block_fn/loss_fn are fresh closures
    # — the supported hot path is the compiled step, eager is for eval.
    run = jax.jit(run)
    return run(stacked_params, post_params, x_micro, y_micro)


def _pipeline_fwd(block_fn, loss_fn, stacked_params, post_params, batch,
                  remat, num_virtual=1, specs=None, aux_weight=None):
    loss, aux, pg, hg, dx = _pipeline_call(
        block_fn, loss_fn, stacked_params, post_params, batch, remat,
        num_virtual, specs, aux_weight)
    out = loss if aux_weight is None else (loss, aux)
    return out, (pg, hg, dx, batch[1])


def _pipeline_bwd(block_fn, loss_fn, remat, num_virtual, specs, aux_weight,
                  res, g):
    pg, hg, dx, y = res
    if aux_weight is not None:
        # second output is a detached metric: its cotangent is dropped
        # (the aux gradient is already inside the total-loss grads)
        g, _ = g
    scale = lambda t: jax.tree_util.tree_map(lambda a: a * g, t)
    return (scale(pg), scale(hg),
            (scale(dx), jax.tree_util.tree_map(jnp.zeros_like, y)))


pipeline_1f1b.defvjp(_pipeline_fwd, _pipeline_bwd)


# ---------------------------------------------------------------------
# Interleaved virtual stages
# ---------------------------------------------------------------------

def interleaved_stacking_order(pp, num_virtual):
    """Row order for stacking global blocks into the [pp·V, ...] param
    pytree of `interleaved_pipeline_loss`: stack row r holds global block
    order[r]. Global block g runs in virtual pass v = g // pp on stage
    s = g % pp, and stage s's shard is rows [s·V, (s+1)·V), so
    order[s·V + v] = v·pp + s (the reference's round-robin layer
    assignment, pp_layers.py SegmentLayers with virtual stages)."""
    order = [0] * (pp * num_virtual)
    for g in range(pp * num_virtual):
        v, s = divmod(g, pp)
        order[s * num_virtual + v] = g
    return order


def interleaved_pipeline_loss(block_fn, loss_fn, stacked_params,
                              post_params, batch, num_virtual=1,
                              remat=True, specs=None, aux_weight=None):
    """Tick-interleaved virtual-stage 1F1B loss (reference:
    fleet/meta_parallel/pipeline_parallel.py:416
    PipelineParallelWithInterleave, parallel_layers/pp_layers.py:198).

    Each device owns `num_virtual` NON-contiguous model chunks
    (round-robin layer placement). stacked_params leaves are [pp·V, ...]
    sharded P('pp'), rows ordered by `interleaved_stacking_order` so stage
    s's shard is its V chunks. All V·pp logical stages run in ONE scan —
    per-tick chunk selection on the unified 1F1B schedule (see
    `_run_schedule` / `schedule_ticks`): `schedule_ticks(M, pp, V)` ≈
    M·V + (V+1)·pp − 2 ticks instead of the V·(M + 2(pp−1)) of V serial
    fill-drain passes, with activation memory O(V·pp) per stage
    (independent of M — the 1F1B property).

    Returns mean micro-loss; differentiable w.r.t. params/post/x_micro.
    With `aux_weight`, block_fn returns (y, aux) and the result is the
    (loss + aux_weight·mean_aux, detached mean_aux) pair — same contract
    as `pipeline_1f1b`.
    NOTE: like `pipeline_1f1b`, the custom_vjp treats labels (y_micro) as
    non-differentiable — their cotangent is zero. Losses that need label
    gradients (e.g. soft-label distillation) must route the differentiable
    part through x_micro or post_params instead.
    """
    pp = mesh_mod.global_mesh().shape["pp"]
    lead = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
    if lead != pp * num_virtual:
        raise ValueError(
            f"stacked_params leading dim {lead} != pp*V = {pp}*{num_virtual}")
    return pipeline_1f1b(block_fn, loss_fn, stacked_params, post_params,
                         batch, remat, num_virtual, specs, aux_weight)
