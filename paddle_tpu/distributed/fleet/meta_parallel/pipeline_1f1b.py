"""1F1B pipeline parallelism as ONE SPMD program.

TPU-native re-design of the reference 1F1B runtime
(reference: fleet/meta_parallel/pipeline_parallel.py:105
`forward_backward_pipeline` — warmup fwd / steady 1F1B / cooldown bwd over
NCCL p2p, with `PipelineParallelWithInterleave:416` for virtual stages).

Design (no per-rank processes, no send/recv ops): the whole fwd+bwd
schedule is a single `lax.scan` inside `shard_map` over the 'pp' mesh axis.
Each tick, every stage does one forward micro-step AND one backward
micro-step (lockstep 1F1B); activations move stage→stage with
`lax.ppermute` over ICI, cotangents move with the reverse permutation.
Backward is hand-scheduled: each stage re-linearizes its block for the
micro-batch leaving flight (remat — only the stage INPUT is kept, in a ring
buffer of 2·pp−1 slots), so peak activation memory is O(pp) per stage,
independent of the number of micro-batches — the 1F1B memory property.
The schedule timing:

    stage s forwards micro m at tick  t = m + s
    stage s backwards micro m at tick t = m + 2(pp−1) − s

(last stage: fwd and bwd of a micro land on the same tick, exactly 1F1B;
total ticks M + 2(pp−1) vs GPipe's 2(M + pp − 1) serialized halves.)

The whole thing is wrapped in jax.custom_vjp so outer autodiff composes:
heterogeneous pre-stages (embedding) differentiate through the returned
input cotangents, and head/loss params (possibly TIED to the embedding)
get grads from the last stage's vjp — weight tying needs no shared-weight
allreduce (reference pp_utils/utils.py FusedAllReduceBuffer): both paths'
grads meet in the outer AD sum.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from ... import mesh as mesh_mod

__all__ = ["pipeline_1f1b", "pipeline_forward_loss",
           "interleaved_pipeline_loss", "interleaved_stacking_order"]


def _tree_zeros(tree):
    return jax.tree_util.tree_map(jnp.zeros_like, tree)


def _tree_add_masked(acc, new, valid):
    return jax.tree_util.tree_map(
        lambda a, n: a + jnp.where(valid, n, jnp.zeros_like(n)), acc, new)


def _run_schedule(block_fn, loss_fn, stacked_params, post_params, x_micro,
                  y_micro, pp, remat):
    """Inside shard_map over 'pp'. Returns (loss_sum, param_grads[1,...],
    post_grads, dx_micro)."""
    params = stacked_params  # leaves [L/pp, ...]: this stage's slice
    stage = lax.axis_index("pp")
    M = x_micro.shape[0]
    T = M + 2 * (pp - 1)
    S = 2 * pp - 1  # max in-flight micros per stage (ring-buffer slots)

    blk = jax.checkpoint(block_fn) if remat else block_fn
    micro_shape = x_micro.shape[1:]

    def tick(carry, t):
        saved, pgrads, hgrads, dxs, loss_sum, fwd_recv, bwd_recv = carry

        # ---------------- forward micro-step ----------------
        mf = t - stage
        fwd_valid = (mf >= 0) & (mf < M)
        mf_c = jnp.clip(mf, 0, M - 1)
        x_in = jnp.where(stage == 0, x_micro[mf_c], fwd_recv)
        out = blk(params, x_in)
        # only save valid micros: cooldown ticks clip mf to M-1, which
        # would overwrite a slot whose micro is still awaiting backward
        saved = lax.cond(
            fwd_valid,
            lambda b: lax.dynamic_update_index_in_dim(b, x_in, mf_c % S, 0),
            lambda b: b,
            saved,
        )

        # ---------------- backward micro-step ----------------
        mb = t - 2 * (pp - 1) + stage
        bwd_valid = (mb >= 0) & (mb < M)
        mb_c = jnp.clip(mb, 0, M - 1)
        x_saved = saved[mb_c % S]
        y_mb = y_micro[mb_c]

        # ONE re-linearization of the block per tick; the last stage's
        # boundary cotangent comes from a (cheap) vjp of just the head+loss
        # on the block output, interior stages use the received cotangent
        out_b, vjp_blk = jax.vjp(blk, params, x_saved)
        loss_val, vjp_head = jax.vjp(
            lambda o, hp: loss_fn(o, y_mb, hp), out_b, post_params)
        d_out, dh_l = vjp_head(jnp.ones_like(loss_val))
        is_last = stage == pp - 1
        cot = jnp.where(is_last, d_out, bwd_recv)
        dparams, dx = vjp_blk(cot)

        pgrads = _tree_add_masked(pgrads, dparams, bwd_valid)
        hgrads = _tree_add_masked(hgrads, dh_l, bwd_valid & is_last)
        loss_sum = loss_sum + jnp.where(
            bwd_valid & is_last, loss_val, 0.0).astype(jnp.float32)
        dxs = lax.cond(
            bwd_valid & (stage == 0),
            lambda b: lax.dynamic_update_index_in_dim(b, dx, mb_c, 0),
            lambda b: b,
            dxs,
        )

        # ---------------- ring communication ----------------
        fwd_recv = lax.ppermute(
            out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
        bwd_recv = lax.ppermute(
            dx, "pp", [(i, (i - 1) % pp) for i in range(pp)])
        return (saved, pgrads, hgrads, dxs, loss_sum, fwd_recv,
                bwd_recv), None

    init = (
        jnp.zeros((S,) + micro_shape, x_micro.dtype),       # saved inputs
        _tree_zeros(params),                                # param grads
        _tree_zeros(post_params),                           # head grads
        jnp.zeros_like(x_micro),                            # input cotangents
        jnp.zeros([], jnp.float32),                         # loss sum
        jnp.zeros(micro_shape, x_micro.dtype),              # fwd ring reg
        jnp.zeros(micro_shape, x_micro.dtype),              # bwd ring reg
    )
    (saved, pgrads, hgrads, dxs, loss_sum, _, _), _ = lax.scan(
        tick, init, jnp.arange(T))

    # replicate stage-local results: loss/head-grads live on the last
    # stage, dx on stage 0 — psum of the masked values broadcasts them.
    # Each micro was seeded with cotangent 1.0, so grads of the MEAN loss
    # need the 1/M factor.
    loss = lax.psum(loss_sum, "pp") / M
    inv_m = 1.0 / M
    pgrads = jax.tree_util.tree_map(lambda g: g * inv_m, pgrads)
    hgrads = jax.tree_util.tree_map(
        lambda g: lax.psum(g, "pp") * inv_m, hgrads)
    dxs = lax.psum(dxs, "pp") * inv_m
    return loss, pgrads, hgrads, dxs


def pipeline_forward_loss(block_fn, loss_fn, stacked_params, post_params,
                          batch):
    """Forward-only fill-drain pipeline loss (eval path — no gradient
    machinery, M + pp − 1 ticks instead of the 1F1B schedule's fwd+bwd)."""
    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    x_micro, y_micro = batch
    M = x_micro.shape[0]
    if pp == 1:
        losses = jax.vmap(
            lambda x, y: loss_fn(block_fn(stacked_params, x), y,
                                 post_params))(x_micro, y_micro)
        return jnp.mean(losses)

    def per_stage(params, post_params, xs, ys):
        stage = lax.axis_index("pp")
        T = M + pp - 1

        def tick(carry, t):
            loss_sum, fwd_recv = carry
            mf = t - stage
            valid = (mf >= 0) & (mf < M)
            mf_c = jnp.clip(mf, 0, M - 1)
            x_in = jnp.where(stage == 0, xs[mf_c], fwd_recv)
            out = block_fn(params, x_in)
            lv = loss_fn(out, ys[mf_c], post_params)
            loss_sum = loss_sum + jnp.where(
                valid & (stage == pp - 1), lv, 0.0).astype(jnp.float32)
            fwd_recv = lax.ppermute(
                out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
            return (loss_sum, fwd_recv), None

        (loss_sum, _), _ = lax.scan(
            tick, (jnp.zeros([], jnp.float32),
                   jnp.zeros(xs.shape[1:], xs.dtype)), jnp.arange(T))
        return lax.psum(loss_sum, "pp") / M

    stack_spec = jax.tree_util.tree_map(
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), stacked_params)
    rep = lambda t: jax.tree_util.tree_map(
        lambda a: P(*([None] * a.ndim)), t)
    run = jax.shard_map(
        per_stage, mesh=mesh,
        in_specs=(stack_spec, rep(post_params),
                  P(*([None] * x_micro.ndim)), P(*([None] * y_micro.ndim))),
        out_specs=P(),
        check_vma=False,
    )
    return run(stacked_params, post_params, x_micro, y_micro)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 5))
def pipeline_1f1b(block_fn, loss_fn, stacked_params, post_params, batch,
                  remat=True):
    """Differentiable 1F1B pipeline loss.

    block_fn(stage_params, x) -> y   one stage's pure forward; stage_params
        are `stacked_params` leaves with the leading (stage-sharded) axis
        REMOVED by shard_map slicing... i.e. leaves [L/pp, ...] for leaves
        stacked [L, ...] — block_fn decides how to consume its slice
        (typically lax.scan over the per-stage sub-layers).
    loss_fn(y, labels, post_params) -> scalar   last-stage head + loss.
    stacked_params: pytree, leading dim divisible by pp, sharded P('pp').
    post_params: pytree (head weights — may alias embedding weights in the
        OUTER function for tying).
    batch: (x_micro [M, ...], y_micro [M, ...]) — micro-batched input
        activations and labels.

    Returns the mean micro-batch loss. Differentiable w.r.t.
    stacked_params, post_params and x_micro (so an embedding stage in the
    caller composes through outer AD).
    """
    loss, _, _, _ = _pipeline_call(block_fn, loss_fn, stacked_params,
                                   post_params, batch, remat)
    return loss


def _pipeline_call(block_fn, loss_fn, stacked_params, post_params, batch,
                   remat):
    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    x_micro, y_micro = batch
    if pp == 1:
        # degenerate: straight-line execution, still micro-batched
        def full(sp, hp, xm):
            losses = jax.vmap(
                lambda x, y: loss_fn(block_fn(sp, x), y, hp))(xm, y_micro)
            return jnp.mean(losses)

        loss, vjp = jax.vjp(full, stacked_params, post_params, x_micro)
        pg, hg, dx = vjp(jnp.ones_like(loss))
        return loss, pg, hg, dx

    stack_spec = jax.tree_util.tree_map(
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), stacked_params)
    rep = lambda t: jax.tree_util.tree_map(
        lambda a: P(*([None] * a.ndim)), t)

    run = jax.shard_map(
        functools.partial(_run_schedule, block_fn, loss_fn, pp=pp,
                          remat=remat),
        mesh=mesh,
        in_specs=(stack_spec, rep(post_params), P(*([None] * x_micro.ndim)),
                  P(*([None] * y_micro.ndim))),
        out_specs=(P(), stack_spec, rep(post_params),
                   P(*([None] * x_micro.ndim))),
        check_vma=False,
    )
    return run(stacked_params, post_params, x_micro, y_micro)


def _pipeline_fwd(block_fn, loss_fn, stacked_params, post_params, batch,
                  remat):
    loss, pg, hg, dx = _pipeline_call(block_fn, loss_fn, stacked_params,
                                      post_params, batch, remat)
    return loss, (pg, hg, dx, batch[1])


def _pipeline_bwd(block_fn, loss_fn, remat, res, g):
    pg, hg, dx, y = res
    scale = lambda t: jax.tree_util.tree_map(lambda a: a * g, t)
    return (scale(pg), scale(hg),
            (scale(dx), jax.tree_util.tree_map(jnp.zeros_like, y)))


pipeline_1f1b.defvjp(_pipeline_fwd, _pipeline_bwd)


# ---------------------------------------------------------------------
# Interleaved virtual stages
# ---------------------------------------------------------------------

def interleaved_stacking_order(pp, num_virtual):
    """Row order for stacking global blocks into the [pp·V, ...] param
    pytree of `interleaved_pipeline_loss`: stack row r holds global block
    order[r]. Global block g runs in virtual pass v = g // pp on stage
    s = g % pp, and stage s's shard is rows [s·V, (s+1)·V), so
    order[s·V + v] = v·pp + s (the reference's round-robin layer
    assignment, pp_layers.py SegmentLayers with virtual stages)."""
    order = [0] * (pp * num_virtual)
    for g in range(pp * num_virtual):
        v, s = divmod(g, pp)
        order[s * num_virtual + v] = g
    return order


def interleaved_pipeline_loss(block_fn, loss_fn, stacked_params,
                              post_params, batch, num_virtual=1,
                              remat=True):
    """Virtual-stage pipeline loss (reference:
    fleet/meta_parallel/pipeline_parallel.py:416
    PipelineParallelWithInterleave, parallel_layers/pp_layers.py:198).

    Each device owns `num_virtual` NON-contiguous model chunks
    (round-robin layer placement — the interleave memory/balance
    property). stacked_params leaves are [pp·V, ...] sharded P('pp'),
    rows ordered by `interleaved_stacking_order` so stage s's shard is
    its V chunks. The forward chains V fill-drain passes over the 'pp'
    axis; autodiff runs through the scans (activation memory O(M) per
    stage — the reference's tick-interleaved 1F1B schedule that also
    shrinks the bubble V× is a scheduling refinement on top of this
    placement).

    Returns mean micro-loss; differentiable w.r.t. params/post/x_micro.
    """
    from .pipeline_parallel import spmd_pipeline

    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    x_micro, y_micro = batch
    V = num_virtual

    # [pp·V, ...] → [pp, V, ...]: chunk v of every stage is [:, v]
    def split_chunks(a):
        return a.reshape((pp, V) + a.shape[1:])

    chunked = jax.tree_util.tree_map(split_chunks, stacked_params)
    x = x_micro
    for v in range(V):
        params_v = jax.tree_util.tree_map(lambda a, _v=v: a[:, _v],
                                          chunked)
        x = spmd_pipeline(block_fn, params_v, x, remat=remat)
    losses = jax.vmap(lambda o, y: loss_fn(o, y, post_params))(x, y_micro)
    return jnp.mean(losses)
