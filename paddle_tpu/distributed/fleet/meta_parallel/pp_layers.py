"""Pipeline layer description API.

(reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py — LayerDesc:59, SharedLayerDesc:78, SegmentLayers:93,
PipelineLayer:198.) The description API is kept; execution differs: on TPU
the stages run as ONE SPMD program (see pipeline_parallel.spmd_pipeline),
not as per-rank processes with p2p send/recv.
"""
import numpy as np

from .... import nn

__all__ = ["LayerDesc", "SharedLayerDesc", "SegmentLayers", "PipelineLayer"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, nn.Layer):
            raise TypeError("LayerDesc expects an nn.Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing in several stages (embedding tying).
    In SPMD the 'shared-weight allreduce' of the reference
    (pp_utils/utils.py FusedAllReduceBuffer) is unnecessary: both uses
    reference the SAME parameter and XLA accumulates its gradient."""

    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr
                 ="weight", *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """Split a layer list into per-stage segments (reference :93 —
    'uniform' by count or 'layer' weighted by parameter size)."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self.descs = layers_desc
        self.num_parts = num_parts
        self.method = method
        if len(layers_desc) < num_parts:
            raise ValueError("number of layers < number of stages")

    def do_segment(self):
        n = len(self.descs)
        if self.method == "uniform":
            bounds = [int(round(i * n / self.num_parts))
                      for i in range(self.num_parts + 1)]
            return bounds
        # weighted by rough parameter count
        weights = []
        for d in self.descs:
            if isinstance(d, LayerDesc):
                w = 1
            else:
                w = max(1, sum(int(np.prod(p.shape))
                               for p in getattr(d, "parameters", lambda: [])())
                        // 1_000_000)
            weights.append(w)
        total = sum(weights)
        bounds = [0]
        acc = 0
        target = total / self.num_parts
        for i, w in enumerate(weights):
            acc += w
            if acc >= target * len(bounds) and len(bounds) < self.num_parts:
                bounds.append(i + 1)
        bounds.append(n)
        while len(bounds) < self.num_parts + 1:
            bounds.insert(-1, bounds[-2])
        return bounds


class PipelineLayer(nn.Layer):
    """(reference :198.) Declarative stage list. On a pp=1 mesh it executes
    sequentially; PipelineParallel / spmd_pipeline use `.segments` to map
    stages onto the 'pp' mesh axis."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0,
                 **kwargs):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages or (
            topology.get_dim("pipe") if topology else 1)
        self.loss_fn = loss_fn
        self.seg_method = seg_method
        self.recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for i, d in enumerate(self.descs):
            if isinstance(d, SharedLayerDesc):
                if d.layer_name in self._shared:
                    proto = self._shared[d.layer_name]
                    layer = _SharedRef(proto, d.forward_func)
                else:
                    layer = d.build_layer()
                    self._shared[d.layer_name] = layer
            elif isinstance(d, LayerDesc):
                layer = d.build_layer()
            elif isinstance(d, nn.Layer):
                layer = d
            elif callable(d):
                layer = _FnLayer(d)
            else:
                raise TypeError(f"bad pipeline entry {d!r}")
            built.append(layer)
            self.add_sublayer(str(i), layer)
        self.run_function = built
        self.segments = SegmentLayers(
            built, self.num_stages, seg_method).do_segment()

    def get_stage_layers(self, stage_id):
        lo, hi = self.segments[stage_id], self.segments[stage_id + 1]
        return self.run_function[lo:hi]

    def forward(self, x):
        for layer in self.run_function:
            x = layer(x)
        return x


class _FnLayer(nn.Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class _SharedRef(nn.Layer):
    """Second occurrence of a shared layer: reuses the prototype's params."""

    def __init__(self, proto, forward_func=None):
        super().__init__()
        self._proto = [proto]  # list → not registered as sublayer
        self._forward_func = forward_func

    def forward(self, *args):
        proto = self._proto[0]
        if self._forward_func is not None:
            return self._forward_func(proto, *args)
        return proto(*args)
