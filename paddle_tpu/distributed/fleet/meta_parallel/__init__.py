from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    mark_sharding,
    shard_activation,
)
from .mp_ops import allreduce_mp, copy_to_mp  # noqa: F401
from .pipeline_1f1b import (  # noqa: F401
    PipelineSpecs,
    interleaved_pipeline_loss,
    interleaved_stacking_order,
    pipeline_1f1b,
    pipeline_forward_loss,
    schedule_ticks,
)
from .pipeline_parallel import PipelineParallel, spmd_pipeline  # noqa: F401
from .pp_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)
