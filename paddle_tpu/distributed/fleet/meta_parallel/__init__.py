from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
    mark_sharding,
    shard_activation,
)
from .pipeline_parallel import PipelineParallel, spmd_pipeline  # noqa: F401
from .pp_layers import (  # noqa: F401
    LayerDesc,
    PipelineLayer,
    SegmentLayers,
    SharedLayerDesc,
)
