"""Elastic / fault-tolerant training (reference:
python/paddle/distributed/fleet/elastic/manager.py:127 ElasticManager —
etcd-watched membership, restart-on-failure; launch --elastic_level).

On TPU pods the failure model is preemption/XLA aborts rather than
stragglers joining an etcd ring, so the TPU-native pieces are:

- the launcher's pod babysitting (`launch --max_restart`, which restarts
  the whole pod — reference elastic_level 1), and
- `run_with_fault_tolerance` here: an in-process supervision loop that
  pairs the training function with a Checkpointer; on a step failure it
  restores the latest complete checkpoint and resumes, preserving
  exactly-once step semantics (train→crash→resume == uninterrupted, see
  tests).

ElasticManager keeps the reference's API shape for scripts that consult
it (enabled / exit codes / watch loop hooks)."""
import time

__all__ = ["ElasticStatus", "ElasticManager", "run_with_fault_tolerance",
           "ELASTIC_EXIT_CODE"]

ELASTIC_EXIT_CODE = 101  # reference manager.py ELASTIC_EXIT_CODE


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Pod-membership watcher over the launcher's heartbeat directory
    (reference elastic/manager.py:127 — etcd node registry + TTL
    heartbeats; here the jax.distributed KV/launcher heartbeat files
    play that role: every worker touches hb_<rank> each second via
    distributed/env.py:_start_heartbeat, the launcher restarts/shrinks
    the pod on staleness, and this manager lets training code observe
    the same signal in-process)."""

    def __init__(self, args=None, etcd_client=None):
        import os

        self.enabled = bool(getattr(args, "elastic_level", 0)
                            or os.environ.get("PADDLE_HEARTBEAT_DIR"))
        self.hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
        self.timeout = float(os.environ.get("PADDLE_ELASTIC_TIMEOUT",
                                            "30"))
        self._status = None

    def pre_hook(self):
        pass

    def peers(self):
        """(rank, seconds-since-last-beat) for every registered worker."""
        import os

        if not self.hb_dir or not os.path.isdir(self.hb_dir):
            return []
        now = time.time()
        out = []
        for f in sorted(os.listdir(self.hb_dir)):
            if not f.startswith("hb_"):
                continue
            try:
                age = now - os.path.getmtime(os.path.join(self.hb_dir, f))
            except OSError:
                continue
            out.append((int(f[3:]), age))
        return out

    def watch(self):
        """HOLD while every registered peer beats within the timeout;
        RESTART when one goes stale (the launcher will re-form the pod);
        COMPLETED/ERROR after exit()."""
        if self._status is not None:
            return self._status
        for _, age in self.peers():
            if age > self.timeout:
                return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def exit(self, completed=True):
        self._status = (ElasticStatus.COMPLETED if completed
                        else ElasticStatus.ERROR)


def run_with_fault_tolerance(train_fn, checkpointer, max_restarts=3,
                             backoff_s=0.0, on_restart=None):
    """Run `train_fn(start_step) -> last_step`, restoring from
    `checkpointer` (paddle_tpu.distributed.checkpoint.Checkpointer) and
    retrying on failure.

    train_fn must checkpoint through `checkpointer` as it goes; on an
    exception the latest COMPLETE checkpoint is loaded (half-written
    ones are invisible by construction) and train_fn is re-entered at
    the restored step. Raises the last error after max_restarts."""
    attempt = 0
    while True:
        start = checkpointer.load_latest() or 0
        try:
            return train_fn(start)
        except Exception:
            attempt += 1
            if attempt > max_restarts:
                raise
            if on_restart is not None:
                on_restart(attempt)
            if backoff_s:
                time.sleep(backoff_s)
