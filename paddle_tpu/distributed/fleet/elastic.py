"""Elastic / fault-tolerant training (reference:
python/paddle/distributed/fleet/elastic/manager.py:127 ElasticManager —
etcd-watched membership, restart-on-failure; launch --elastic_level).

On TPU pods the failure model is preemption/XLA aborts rather than
stragglers joining an etcd ring, so the TPU-native pieces are:

- the launcher's pod babysitting (`launch --max_restart`, which restarts
  the whole pod — reference elastic_level 1), and
- `run_with_fault_tolerance` here: an in-process supervision loop that
  pairs the training function with a Checkpointer; on a step failure it
  restores the latest complete checkpoint and resumes, preserving
  exactly-once step semantics (train→crash→resume == uninterrupted, see
  tests).

ElasticManager keeps the reference's API shape for scripts that consult
it (enabled / exit codes / watch loop hooks)."""
import time

from ...observability import metrics as _obs

__all__ = ["ElasticStatus", "ElasticManager", "run_with_fault_tolerance",
           "request_scale_out", "ELASTIC_EXIT_CODE",
           "touch_heartbeat", "remove_heartbeat"]

# heartbeat telemetry: replaces ad-hoc age prints — the launcher, the
# watch loop, and /metrics scrapes all read the same gauges
_PEER_AGE = _obs.gauge("pt_elastic_peer_age_seconds",
                       "seconds since a peer's last heartbeat",
                       labelnames=("rank",))
_PEERS = _obs.gauge("pt_elastic_peers", "registered peers")
_STALE_PEERS = _obs.gauge("pt_elastic_stale_peers",
                          "peers past the heartbeat timeout")
_TRAIN_RESTARTS = _obs.counter(
    "pt_elastic_train_restarts_total",
    "in-process fault-tolerant restarts (run_with_fault_tolerance)")

ELASTIC_EXIT_CODE = 101  # reference manager.py ELASTIC_EXIT_CODE


class ElasticStatus:
    COMPLETED = "completed"
    ERROR = "error"
    HOLD = "hold"
    RESTART = "restart"
    EXIT = "exit"


class ElasticManager:
    """Pod-membership watcher over the launcher's heartbeat directory
    (reference elastic/manager.py:127 — etcd node registry + TTL
    heartbeats; here the jax.distributed KV/launcher heartbeat files
    play that role: every worker touches hb_<rank> each second via
    distributed/env.py:_start_heartbeat, the launcher restarts/shrinks
    the pod on staleness, and this manager lets training code observe
    the same signal in-process)."""

    def __init__(self, args=None, etcd_client=None):
        import os

        self.master_ep = os.environ.get("PADDLE_ELASTIC_MASTER")
        self.enabled = bool(getattr(args, "elastic_level", 0)
                            or self.master_ep
                            or os.environ.get("PADDLE_HEARTBEAT_DIR"))
        self.hb_dir = os.environ.get("PADDLE_HEARTBEAT_DIR")
        self.timeout = float(os.environ.get("PADDLE_ELASTIC_TIMEOUT",
                                            "30"))
        self._status = None

    def pre_hook(self):
        pass

    def _client(self):
        from ..launch.master import MembershipClient

        return MembershipClient(self.master_ep)

    def peers(self):
        """(rank, seconds-since-last-beat) for every registered worker.
        Prefers the cross-host membership master (launch/master.py —
        the reference's etcd registry); falls back to the single-host
        heartbeat directory."""
        import os

        if self.master_ep:
            try:
                return self._gauge_peers(self._client().peers())
            except OSError:
                # master unreachable: the membership VIEW is empty —
                # gauge that (stale last-healthy values lying on
                # /metrics are worse than an honest zero)
                return self._gauge_peers([])
        if not self.hb_dir or not os.path.isdir(self.hb_dir):
            return self._gauge_peers([])   # view empty — gauge it too
        now = time.time()
        out = []
        for f in sorted(os.listdir(self.hb_dir)):
            if not f.startswith("hb_"):
                continue
            try:
                age = now - os.path.getmtime(os.path.join(self.hb_dir, f))
            except OSError:
                continue
            out.append((int(f[3:]), age))
        return self._gauge_peers(out)

    def _gauge_peers(self, peers):
        """Mirror the membership view into the registry heartbeat
        gauges (docs/OBSERVABILITY.md). Ranks that left the view have
        their per-rank series REMOVED — a departed rank frozen at its
        last healthy age would scrape as alive forever."""
        _PEERS.set(len(peers))
        stale = 0
        seen = set()
        for rank, age in peers:
            seen.add(str(rank))
            _PEER_AGE.labels(rank=rank).set(age)
            if age > self.timeout:
                stale += 1
        for gone in set(_PEER_AGE._children) - {(r,) for r in seen}:
            _PEER_AGE.remove(*gone)
        _STALE_PEERS.set(stale)
        return peers

    def watch(self):
        """HOLD while every registered peer beats within the timeout;
        RESTART when one goes stale (the launcher will re-form the pod);
        COMPLETED/ERROR after exit()."""
        if self._status is not None:
            return self._status
        for _, age in self.peers():
            if age > self.timeout:
                return ElasticStatus.RESTART
        return ElasticStatus.HOLD

    def exit(self, completed=True):
        self._status = (ElasticStatus.COMPLETED if completed
                        else ElasticStatus.ERROR)

    def health(self):
        """rank -> {age, degraded, retries}: the degraded-vs-dead view
        the membership master aggregates from heartbeat retry telemetry
        (resilience.recent_failures). Empty without a master endpoint —
        the directory fallback carries liveness only."""
        if self.master_ep:
            try:
                return self._client().health()
            except OSError:
                return {}
        return {}

    def pending_joins(self):
        """Join requests awaiting the launcher (reference ETCDMaster
        node-arrival watch)."""
        if self.master_ep:
            try:
                return self._client().pending_joins()
            except OSError:
                return 0
        return len(pending_join_files(self.hb_dir))


# the heartbeat file protocol (env.py:_start_heartbeat writer,
# ElasticManager.peers / the launcher readers), exposed for OTHER
# heartbeat publishers — the fleet-serving replica runtime
# (inference/fleet_serving/replica.py) registers its replicas through
# these, so a serving fleet's liveness is observable via the SAME
# ElasticManager view as a training pod's
HB_PREFIX = "hb_"


def touch_heartbeat(hb_dir, rank):
    """Write/refresh `hb_<rank>` in the membership directory (same
    format as the worker heartbeat thread: the beat wall-time). Returns
    the path."""
    import os

    os.makedirs(hb_dir, exist_ok=True)
    path = os.path.join(hb_dir, f"{HB_PREFIX}{int(rank)}")
    with open(path, "w") as f:
        f.write(str(time.time()))
    return path


def remove_heartbeat(hb_dir, rank):
    """Tombstone one rank's heartbeat (clean exit must not read as a
    wedged peer — the env.py atexit contract). Idempotent."""
    import os

    try:
        os.unlink(os.path.join(hb_dir, f"{HB_PREFIX}{int(rank)}"))
    except OSError:
        pass


# the join-request file protocol, shared by request_scale_out (writer),
# ElasticManager.pending_joins and the launcher's watch (readers)
JOIN_PREFIX = "join_"


def pending_join_files(hb_dir):
    """Absolute paths of join_* request files in the heartbeat dir."""
    import os

    if not hb_dir or not os.path.isdir(hb_dir):
        return []
    return sorted(
        os.path.join(hb_dir, f) for f in os.listdir(hb_dir)
        if f.startswith(JOIN_PREFIX))


def request_scale_out(n=1, hb_dir=None, master=None):
    """Ask the launcher to admit `n` joining worker(s). A launcher
    running with --elastic_level>=1 tears the pod down (RC_SCALE_OUT)
    and re-forms it with nproc+n contiguous ranks; workers resume from
    the latest complete checkpoint and re-shard
    DistributedBatchSampler at the new world size (reference:
    elastic/manager.py:127 ETCDMaster re-ranks on peer ARRIVAL;
    launch/controllers/master.py:175).

    Transport: with a membership master active (PADDLE_ELASTIC_MASTER,
    or the `master` endpoint argument — e.g. an operator box or second
    "node" that shares NOTHING but the endpoint with the pod), the
    request is one RPC to the launcher's registry. Fallback: join_*
    request files in the shared heartbeat directory (single host).
    Returns n."""
    import os
    import uuid

    master = master or os.environ.get("PADDLE_ELASTIC_MASTER")
    if master:
        from ..launch.master import MembershipClient

        MembershipClient(master).join(n)
        return n
    hb_dir = hb_dir or os.environ.get("PADDLE_HEARTBEAT_DIR")
    if not hb_dir:
        raise RuntimeError(
            "request_scale_out needs a membership master "
            "(PADDLE_ELASTIC_MASTER) or the launcher heartbeat dir "
            "(PADDLE_HEARTBEAT_DIR) — start the job via "
            "paddle_tpu.distributed.launch")
    if int(os.environ.get("PADDLE_NNODES", "1")) > 1:
        raise RuntimeError(
            "file-based request_scale_out is single-node-pod scoped; "
            "multi-node scale-out goes through the membership master "
            "(PADDLE_ELASTIC_MASTER)")
    os.makedirs(hb_dir, exist_ok=True)
    for _ in range(n):
        path = os.path.join(hb_dir, JOIN_PREFIX + uuid.uuid4().hex[:8])
        with open(path, "w") as f:
            f.write(str(time.time()))
    return n


def _drain_checkpointer(checkpointer):
    """Join any in-flight async commit before restoring. A failed
    commit must not abort the recovery itself — its checkpoint simply
    never became COMPLETE and load_latest falls back past it."""
    from ..resilience import record

    try:
        checkpointer.wait()
    except Exception as e:
        record("ckpt_drain_failed", error=repr(e))


def run_with_fault_tolerance(train_fn, checkpointer, max_restarts=3,
                             backoff_s=0.0, on_restart=None, retry=None,
                             manager=None):
    """Run `train_fn(start_step) -> last_step`, restoring from
    `checkpointer` (paddle_tpu.distributed.checkpoint.Checkpointer) and
    retrying on failure.

    train_fn must checkpoint through `checkpointer` as it goes; on an
    exception the latest COMPLETE checkpoint is loaded (half-written
    ones are invisible by construction — the per-rank DONE marker
    protocol) and train_fn is re-entered at the restored step. Raises
    the last error after max_restarts.

    Two recovery tiers compose here:

    * ``DivergenceRollback`` (a resilience.DivergenceSentinel demanding
      a rollback on NaN/Inf or a loss spike) restores and resumes
      WITHOUT consuming a restart — the sentinel bounds its own budget
      (StepAbort past it), marks the poisoned data window, and the
      re-entered train_fn consults ``sentinel.should_skip(step)`` to
      advance past it. Journaled as ``train_rollback``.
    * any other exception consumes one of `max_restarts` in-process
      restarts — unless `manager` (an ElasticManager) reports a STALE
      PEER, in which case the failure is escalated to the launcher
      immediately (``elastic_escalate``): an in-process retry cannot
      re-form a pod whose member died; `launch --max_restart` can.

    `retry` (a resilience.RetryPolicy) supplies exponential backoff +
    jitter between attempts; the legacy fixed `backoff_s` applies when
    no policy is given. Every restart is journaled to the per-rank
    anomaly log (resilience.record)."""
    from ..resilience import DivergenceRollback, record

    attempt = 0
    while True:
        start = checkpointer.load_latest() or 0
        try:
            return train_fn(start)
        except DivergenceRollback as e:
            record("train_rollback", start_step=start, step=e.step,
                   reason=e.reason)
            # postmortem BEFORE the restore overwrites the live state:
            # the ring holds the journal/span trail that led into the
            # divergence (docs/OBSERVABILITY.md "Flight recorder")
            try:
                from ...observability import flight_recorder as _fr

                _fr.dump("divergence_rollback", step=e.step,
                         rollback_reason=e.reason, start_step=start,
                         value=str(e.value))
            except Exception:  # ptlint: disable=PTL804 (the guard wraps the flight-recorder dump itself)
                pass
            _drain_checkpointer(checkpointer)
            continue
        except Exception as e:
            attempt += 1
            _TRAIN_RESTARTS.inc()
            record("train_restart", attempt=attempt, start_step=start,
                   error=repr(e))
            if attempt > max_restarts:
                raise
            if manager is not None and getattr(manager, "enabled", False) \
                    and manager.watch() == ElasticStatus.RESTART:
                record("elastic_escalate", attempt=attempt,
                       error=repr(e))
                raise
            _drain_checkpointer(checkpointer)
            if on_restart is not None:
                on_restart(attempt)
            delay = (retry.backoff(attempt - 1) if retry is not None
                     else backoff_s)
            if delay:
                time.sleep(delay)
