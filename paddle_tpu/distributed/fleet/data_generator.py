"""fleet.data_generator — user-defined sample generators for PS/CTR ingest.

Reference surface: python/paddle/distributed/fleet/data_generator/
data_generator.py:21 (`DataGenerator`), :239 (`MultiSlotStringDataGenerator`),
:284 (`MultiSlotDataGenerator`). In the reference these run inside a
`pipe_command` subprocess whose stdout is parsed by the C++ MultiSlotDataFeed
(paddle/fluid/framework/data_feed.cc). Here the same wire protocol is kept —
one line per sample, ``<n> v1 .. vn`` per slot — and the consumer side is
`parse_multi_slot` (python) or, for the dense numeric case, the native C
parser (`paddle_tpu.native.parse_slots`). A generator can therefore still be
used as a shell pipe (`run_from_stdin`) or in-process (`run_from_memory`).
"""
import sys

__all__ = [
    "DataGenerator", "MultiSlotDataGenerator",
    "MultiSlotStringDataGenerator", "parse_multi_slot",
]


class DataGenerator:
    """Base generator. Subclasses implement `generate_sample(line)`
    returning a generator that yields samples shaped
    ``[(slot_name, [values...]), ...]``; optionally `generate_batch`
    to re-group buffered samples (reference data_generator.py:194)."""

    def __init__(self):
        self._proto_info = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    # -- user hooks -------------------------------------------------------
    def generate_sample(self, line):
        raise NotImplementedError(
            "Please rewrite this function to return a list or tuple: "
            "[('words', [1, 2, 3]), ('label', [0])]")

    def generate_batch(self, samples):
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- drivers ----------------------------------------------------------
    def _run(self, lines, out):
        batch_samples = []
        for line in lines:
            for user_parsed_line in self.generate_sample(line)():
                if user_parsed_line is None:
                    continue
                batch_samples.append(user_parsed_line)
                if len(batch_samples) == self.batch_size_:
                    for sample in self.generate_batch(batch_samples)():
                        out.write(self._gen_str(sample))
                    batch_samples = []
        if batch_samples:
            for sample in self.generate_batch(batch_samples)():
                out.write(self._gen_str(sample))

    def run_from_memory(self, out=None):
        """Drive `generate_sample(None)` once (memory-resident generators,
        reference data_generator.py:61)."""
        self._run([None], out or sys.stdout)

    def run_from_stdin(self, inp=None, out=None):
        """Read one raw input line at a time and emit wire-format samples
        (reference data_generator.py:96)."""
        self._run(inp or sys.stdin, out or sys.stdout)

    def _gen_str(self, line):
        raise NotImplementedError(
            "Please inherit MultiSlotDataGenerator or "
            "MultiSlotStringDataGenerator to use this function")


class MultiSlotStringDataGenerator(DataGenerator):
    """String-token wire format: ``<n> tok1 .. tokn`` per slot
    (reference data_generator.py:239). Fastest path: no type checks."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type; "
                "Example: [('words', ['1926', '08', '17']), ('label', ['0'])]")
        output = ""
        for name, elements in line:
            if output:
                output += " "
            out_str = [str(len(elements))]
            out_str.extend(str(e) for e in elements)
            output += " ".join(out_str)
        return output + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """Typed numeric wire format with a slot schema: each slot's dtype is
    pinned on first sample (uint64 for all-int values, float otherwise)
    and later samples must agree on slot names/order and count
    (reference data_generator.py:284 `_gen_str` + proto_info upgrade)."""

    def _gen_str(self, line):
        if isinstance(line, zip):
            line = list(line)
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type; "
                "Example: [('words', [1926, 8, 17]), ('label', [1])]")
        output = ""
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                if not isinstance(name, str):
                    raise ValueError(f"name must be str, got {type(name)}")
                if not isinstance(elements, list):
                    raise ValueError(
                        f"elements must be list, got {type(elements)}")
                if not elements:
                    raise ValueError(
                        f"the elements of each field ({name}) can not be empty")
                self._proto_info.append((name, "uint64"))
                if output:
                    output += " "
                output += str(len(elements))
                for elem in elements:
                    if isinstance(elem, float):
                        self._proto_info[-1] = (name, "float")
                    elif not isinstance(elem, int):
                        raise ValueError(
                            f"the type of element ({type(elem)}) must be int "
                            "or float")
                    output += " " + str(elem)
        else:
            if len(line) != len(self._proto_info):
                raise ValueError(
                    f"the complete field set of two samples are different: "
                    f"{len(line)} vs {len(self._proto_info)}")
            for index, item in enumerate(line):
                name, elements = item
                if name != self._proto_info[index][0]:
                    raise ValueError(
                        f"the field name of two samples are different: "
                        f"{name} vs {self._proto_info[index][0]}")
                if not elements:
                    raise ValueError(
                        f"the elements of each field ({name}) can not be empty")
                if output:
                    output += " "
                output += str(len(elements))
                for elem in elements:
                    if self._proto_info[index][1] != "float":
                        if isinstance(elem, float):
                            self._proto_info[index] = (name, "float")
                        elif not isinstance(elem, int):
                            raise ValueError(
                                f"the type of element ({type(elem)}) must be "
                                "int or float")
                    output += " " + str(elem)
        return output + "\n"


def _num(v):
    """int when exact, else float — floats without '.', nan and inf
    (all emitted by MultiSlotDataGenerator) must round-trip."""
    try:
        return int(v)
    except ValueError:
        return float(v)


def parse_multi_slot(text, n_slots, string=False):
    """Decode the multi-slot wire format back into per-row ragged slots:
    returns ``[[slot0_values, slot1_values, ...], ...]`` (one inner list per
    line). The consumer-side analog of data_feed.cc's MultiSlotDataFeed
    deserializer; `string=True` keeps raw tokens."""
    rows = []
    for lineno, line in enumerate(text.splitlines()):
        toks = line.split()
        if not toks:
            continue
        slots, i = [], 0
        try:
            for _ in range(n_slots):
                n = int(toks[i])
                vals = toks[i + 1: i + 1 + n]
                if len(vals) != n:
                    raise IndexError
                if not string:
                    vals = [_num(v) for v in vals]
                slots.append(vals)
                i += 1 + n
        except (IndexError, ValueError):
            raise ValueError(
                f"multi-slot parse error on line {lineno}: truncated or "
                "non-numeric slot") from None
        if i != len(toks):
            raise ValueError(
                f"multi-slot parse error on line {lineno}: trailing tokens")
        rows.append(slots)
    return rows
