"""Fleet strategy meta-optimizers: LARS, LocalSGD, DGC.

TPU-native re-designs of the reference's static-graph meta-optimizer
passes (reference: fleet/meta_optimizers/lars_optimizer.py,
localsgd_optimizer.py (+AdaptiveLocalSGD), dgc_optimizer.py; C++ DGC
momentum op operators/optimizers/dgc_momentum_op and the sparse
all-reduce handle details/sparse_all_reduce_op_handle.cc).

The reference rewrites the static program; here each strategy is a small
runtime object over the same two primitives everything else uses —
per-parameter pure updates (optimizer protocol) and eager
multi-controller collectives (`xproc`, which on CPU hosts is gloo and on
pods rides the same compiled-collective machinery as the in-graph path):

* `lars(...)` — returns the core `optimizer.LarsMomentum` (the trust-
  ratio math lives in the optimizer protocol, so it composes with
  TrainStep / DistributedTrainStep like any optimizer).
* `LocalSGD` — workers step LOCALLY (no per-step gradient sync);
  every `k_steps` calls the parameters are averaged across trainer
  processes. Cuts DP sync frequency k× at the cost of staleness —
  exactly the reference LocalSGDOptimizer contract.
* `DGCMomentum` — deep gradient compression: error-feedback top-k
  sparsified gradient exchange with momentum correction; only
  (index, value) pairs travel, cutting DP gradient traffic to
  sparsity·world of dense.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ...optimizer.optimizer import Momentum, _acc_zeros
from .. import xproc

__all__ = ["lars", "LocalSGD", "DGCMomentum"]


def lars(learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
         lars_weight_decay=0.0005, parameters=None, **kw):
    """Strategy entry (reference LarsOptimizer meta pass): the LARS
    update itself is `paddle_tpu.optimizer.LarsMomentum`."""
    from ...optimizer import LarsMomentum

    return LarsMomentum(learning_rate, momentum, lars_coeff,
                        lars_weight_decay, parameters=parameters, **kw)


class LocalSGD:
    """Periodic parameter averaging across trainer processes
    (reference: fleet/meta_optimizers/localsgd_optimizer.py — workers
    run k local steps, then c_allreduce the parameters).

    Usage:
        sync = LocalSGD(model, k_steps=4)
        for batch in loader:
            train_step(batch)          # any local step (TrainStep etc.)
            sync.step()                # averages params every k-th call

    Single-process jobs: step() is a no-op (serial == local). The
    `adaptive` mode grows k when the post-sync parameter drift is small
    (reference AdaptiveLocalSGDOptimizer's step-resolution controller).
    """

    def __init__(self, model, k_steps=1, adaptive=False, min_k=1,
                 max_k=16, drift_threshold=1e-3):
        self.model = model  # an nn.Layer OR a plain parameter list
        self.k_steps = max(1, int(k_steps))
        self.adaptive = adaptive
        self.min_k, self.max_k = min_k, max_k
        self.drift_threshold = drift_threshold
        self._calls = 0
        self.syncs = 0

    def step(self):
        self._calls += 1
        if self._calls % self.k_steps:
            return False
        if not xproc.is_multiprocess():
            return False
        params = (self.model if isinstance(self.model, (list, tuple))
                  else [p for _, p in self.model.named_parameters()])
        drift = 0.0
        for p in params:
            local = np.asarray(p._value)
            avg = xproc.all_reduce_np(local, op="avg")
            if self.adaptive:
                d = float(np.max(np.abs(avg - local)))
                drift = max(drift, d)
            new = jnp.asarray(avg)
            # keep the param's mesh placement: a bare jnp.asarray is an
            # uncommitted single-device array, and feeding that back
            # into a compiled step whose params were mesh-sharded costs
            # a SECOND executable (signature = shardings too) — caught
            # by the hybrid3d 2-proc one-executable probe
            try:
                new = jax.device_put(new, p._value.sharding)
            except (AttributeError, ValueError):
                pass
            p._value = new
        self.syncs += 1
        if self.adaptive:
            # every rank must adapt from the SAME drift or their sync
            # schedules desynchronize and collectives cross-pair
            drift = float(xproc.all_reduce_np(
                np.array([drift], np.float32), op="max")[0])
            # small drift → sync less often; large drift → more often
            if drift < self.drift_threshold and self.k_steps < self.max_k:
                self.k_steps = min(self.max_k, self.k_steps * 2)
            elif drift > 10 * self.drift_threshold and \
                    self.k_steps > self.min_k:
                self.k_steps = max(self.min_k, self.k_steps // 2)
        return True


class DGCMomentum(Momentum):
    """Deep-gradient-compression momentum (reference:
    fleet/meta_optimizers/dgc_optimizer.py, dgc_momentum_op,
    sparse_all_reduce_op_handle.cc; Lin et al., DGC).

    Per parameter: velocity-accumulate the raw gradient (momentum
    correction u ← m·u + g, error accumulator v ← v + u), take the
    top-(1−sparsity) entries of |v| as this step's sparse update, zero
    them in BOTH v (error feedback keeps the rest for later) and u
    (the paper's momentum-factor masking — stale momentum must not
    re-enter future accumulations), and — in multi-process jobs —
    exchange only the (index, value) pairs, scatter-summing every
    worker's selection into the dense update.

    `sparsity` follows the REFERENCE convention (dgc_configs sparsity =
    fraction of entries DROPPED; the reference default 0.999 keeps
    0.1%). With sparsity=0.0 every entry is sent each step and — with
    u fully masked each step — the update degenerates to plain SGD, the
    paper's dense limit."""

    def __init__(self, learning_rate=0.001, momentum=0.9, sparsity=0.999,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, momentum, parameters,
                         grad_clip=grad_clip)
        if not 0.0 <= float(sparsity) < 1.0:
            raise ValueError(f"sparsity (fraction dropped) must be in "
                             f"[0, 1), got {sparsity}")
        self.sparsity = float(sparsity)

    def _init_state(self, p):
        return {"u": _acc_zeros(p), "v": _acc_zeros(p)}

    def _update(self, pv, gv, state, lr, wd=0.0, param=None):
        if wd:
            gv = gv + wd * pv
        u = self._momentum * state["u"] + gv
        v = state["v"] + u
        flat = v.reshape(-1)
        k = max(1, int(np.ceil((1.0 - self.sparsity) * flat.shape[0])))
        _, idx = jax.lax.top_k(jnp.abs(flat), k)
        vals = flat[idx]
        if xproc.is_multiprocess():
            # sparse exchange: k (idx, val) pairs per worker, summed.
            # indices travel TYPED (int32) — a float transport silently
            # corrupts offsets past 2^24 under float32 canonicalization
            if flat.shape[0] >= 2 ** 31:
                raise NotImplementedError(
                    "DGC index transport is int32; parameter has "
                    f"{flat.shape[0]} elements")
            g_idx = xproc.all_gather_np(np.asarray(idx, np.int32))
            g_val = xproc.all_gather_np(np.asarray(vals, np.float32))
            dense = np.zeros(flat.shape[0], np.float64)
            world = g_idx.shape[0]
            for r in range(world):
                np.add.at(dense, g_idx[r].astype(np.int64),
                          g_val[r].astype(np.float64))
            update = jnp.asarray(dense / world, flat.dtype)
        else:
            update = jnp.zeros_like(flat).at[idx].set(vals)
        new_flat = flat.at[idx].set(0.0)  # error feedback: keep the rest
        # momentum factor masking (Lin et al. §3.2): selected coords drop
        # their momentum history too
        u_flat = u.reshape(-1).at[idx].set(0.0)
        new_p = pv - lr * update.reshape(pv.shape)
        return new_p, {"u": u_flat.reshape(u.shape),
                       "v": new_flat.reshape(v.shape)}
