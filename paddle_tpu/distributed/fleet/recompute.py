"""Activation recomputation.

(reference: fleet/recompute/recompute.py:346 `recompute` — a PyLayer that
replays forward with saved RNG state; recompute_hybrid.py for mp-aware
offload/partition.) TPU-native: `jax.checkpoint` (remat) IS the mechanism —
the XLA scheduler rematerializes inside the compiled backward, no RNG
bookkeeping needed (keys are explicit values).
"""
import functools

import jax

from ...ops._helpers import apply_jfn, ensure_tensor
from ...tensor_core import Tensor

__all__ = ["recompute", "recompute_sequential", "checkpoint_policy"]


def checkpoint_policy(name):
    """Map a policy name to a `jax.checkpoint` rematerialization policy.

    Policies trade recompute FLOPs against saved-activation HBM — on TPU
    `dots_saveable` keeps MXU matmul outputs and recomputes the cheap
    elementwise ops, usually the best step-time/memory point (the knob
    the reference lacks; its recompute is all-or-nothing per block)."""
    import jax.ad_checkpoint as adc

    if callable(name):  # a jax policy callable passes straight through
        return name
    policies = {
        None: None,  # also True/False from bool `remat` knobs
        True: None,
        False: None,
        "everything_saveable": adc.checkpoint_policies.everything_saveable,
        "nothing_saveable": adc.checkpoint_policies.nothing_saveable,
        "dots_saveable": adc.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims_saveable":
            adc.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    try:
        return policies[name]
    except KeyError:
        raise ValueError(
            f"unknown checkpoint policy {name!r}; "
            f"one of {sorted(k for k in policies if isinstance(k, str))}"
        ) from None


def recompute(function, *args, **kwargs):
    """Run `function(*args)` with rematerialized backward.

    If `function` is a Layer (or closes over Layers passed positionally), its
    parameters are threaded through the tape as explicit inputs — the
    reference's PyLayer saves them implicitly via autograd; here the tape op
    must see them to produce `.grad` (grads only flow to declared inputs).

    `policy=` selects what the backward may keep instead of recomputing:
    a name from `checkpoint_policy` or a raw jax policy callable (e.g.
    `jax.checkpoint_policies.save_only_these_names(...)`); default None =
    keep nothing, the reference's semantics.
    """
    preserve = kwargs.pop("preserve_rng_state", True)
    use_reentrant = kwargs.pop("use_reentrant", True)
    policy = checkpoint_policy(kwargs.pop("policy", None))
    tensors = []
    specs = []
    for a in args:
        if isinstance(a, Tensor):
            specs.append(("t", len(tensors)))
            tensors.append(a)
        else:
            specs.append(("v", a))

    fn = function
    params = list(getattr(function, "parameters", lambda: [])())
    n_args = len(tensors)
    tensors.extend(params)

    def jfn(*vals):
        rebuilt = []
        for kind, payload in specs:
            if kind == "t":
                rebuilt.append(Tensor(vals[payload],
                                      stop_gradient=False))
            else:
                rebuilt.append(payload)
        originals = [p._value for p in params]
        for p, v in zip(params, vals[n_args:]):
            p._value = v
        try:
            out = fn(*rebuilt, **kwargs)
        finally:
            for p, v in zip(params, originals):
                p._value = v
        if isinstance(out, (tuple, list)):
            return tuple(o._value for o in out)
        return out._value

    ck = jax.checkpoint(jfn, policy=policy)
    return apply_jfn("recompute", ck, *tensors)


def recompute_sequential(ctx, functions, *args):
    """(reference recompute_sequential:472) — chunked remat over a
    Sequential's sublayers."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    chunk = max(1, n // segments)
    out = args[0] if len(args) == 1 else args
    for i in range(0, n, chunk):
        seg = layers[i: i + chunk]

        def run(x, seg=seg):
            for l in seg:
                x = l(x)
            return x

        # expose the segment's params so recompute() threads them through
        # the tape (a plain closure has no .parameters)
        run.parameters = lambda seg=seg: [
            p for l in seg for p in l.parameters()
        ]
        out = recompute(run, out)
    return out
