"""Hybrid-parallel process topology.

TPU-native re-design of the reference topology
(reference: python/paddle/distributed/fleet/base/topology.py:52
CommunicateTopology — an N-D rank grid whose per-axis slices become NCCL
rings; :134 HybridCommunicateGroup). Here the grid IS the device mesh:
axis groups are mesh axis names, ranks are device coordinates, and no
communicators are created (XLA binds collectives to axes at compile time).
"""
import itertools

import numpy as np

from .. import collective as coll
from .. import mesh as mesh_mod

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self._world_size = int(np.prod(self._dims))
        shape = tuple(self._dims)
        self._coord_of = {}
        self._rank_of = {}
        for rank, coord in enumerate(itertools.product(
                *[range(d) for d in shape])):
            self._coord_of[rank] = coord
            self._rank_of[coord] = rank

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[n] for n in self._parallel_names)
        return self._rank_of[coord]

    def get_coord(self, rank):
        return self._coord_of[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._coord_of.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank-groups along `axis_name` (the reference builds one NCCL
        ring per entry; we return them for introspection/tests)."""
        axis = self._parallel_names.index(axis_name)
        other = [n for i, n in enumerate(self._parallel_names) if i != axis]
        groups = []
        for fixed in itertools.product(
                *[range(self.get_dim(n)) for n in other]):
            ranks = []
            for i in range(self._dims[axis]):
                kw = dict(zip(other, fixed))
                kw[self._parallel_names[axis]] = i
                ranks.append(self.get_rank(**kw))
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = list(self.get_coord(global_rank))
        for k, v in kwargs.items():
            coord[self._parallel_names.index(k)] = v
        return self._rank_of[tuple(coord)]


# reference axis name → mesh axis name
_MESH_AXIS = {"data": "dp", "pipe": "pp", "sharding": "sharding",
              "model": "mp", "sep": "sp", "expert": "ep"}


class HybridCommunicateGroup:
    """(reference topology.py:134.) Groups are mesh-axis Groups; the
    check/p2p groups of the reference collapse into axis references."""

    def __init__(self, topology=None, dp_degree=None, mp_degree=None,
                 pp_degree=None, sharding_degree=None, sp_degree=1,
                 ep_degree=1):
        if topology is not None:
            dims = {n: topology.get_dim(n)
                    for n in topology.get_hybrid_group_names()}
            dp_degree = dims.get("data", 1)
            pp_degree = dims.get("pipe", 1)
            sharding_degree = dims.get("sharding", 1)
            mp_degree = dims.get("model", 1)
            sp_degree = dims.get("sep", 1)
        self._dp_degree = dp_degree or 1
        self._mp_degree = mp_degree or 1
        self._pp_degree = pp_degree or 1
        self._sharding_degree = sharding_degree or 1
        self._sp_degree = sp_degree or 1
        self._ep_degree = ep_degree or 1
        self._topo = topology or CommunicateTopology(
            ("data", "pipe", "sharding", "model"),
            (self._dp_degree, self._pp_degree, self._sharding_degree,
             self._mp_degree))
        if not mesh_mod.has_mesh():
            mesh_mod.init_mesh(
                dp=self._dp_degree, pp=self._pp_degree,
                sharding=self._sharding_degree, mp=self._mp_degree,
                sp=self._sp_degree, ep=self._ep_degree)
        self._dp_group = coll.new_group(axes=("dp",))
        self._mp_group = coll.new_group(axes=("mp",))
        self._pp_group = coll.new_group(axes=("pp",))
        self._sharding_group = coll.new_group(axes=("sharding",))
        self._sp_group = coll.new_group(axes=("sp",))
        self._ep_group = coll.new_group(axes=("ep",))

    @property
    def global_rank(self):
        from .. import env

        return env.get_rank()

    @property
    def nranks(self):
        return (self._dp_degree * self._mp_degree * self._pp_degree *
                self._sharding_degree * self._sp_degree * self._ep_degree)

    def get_parallel_mode(self):
        if self._pp_degree > 1:
            return "pipeline"
        if self._sharding_degree > 1:
            return "sharding"
        if self._mp_degree > 1:
            return "model"
        return "data"

    # ---- degrees / ids (per-rank ids are compile-time axis indices under
    # SPMD; host-side they are 0 on a single controller) ----
    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sep_parallel_world_size(self):
        return self._sp_degree

    def get_expert_parallel_world_size(self):
        return self._ep_degree

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # ---- groups ----
    def get_data_parallel_group(self):
        return self._dp_group

    def get_model_parallel_group(self):
        return self._mp_group

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sep_parallel_group(self):
        return self._sp_group

    def get_expert_parallel_group(self):
        return self._ep_group

    def get_check_parallel_group(self, *a, **k):
        return coll.new_group(axes=("dp", "pp", "sharding", "mp"))

    def get_data_parallel_group_src_rank(self):
        return 0

    def get_model_parallel_group_src_rank(self):
        return 0

    def get_p2p_groups(self):
        return (self._pp_group, self._pp_group)

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank,
                                              pipe=stage_id, **kwargs)

    # pipeline helpers
    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    @property
    def topology(self):
        return self._topo
