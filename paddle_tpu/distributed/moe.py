"""Mixture-of-Experts with expert parallelism.

TPU-native re-design of the reference MoE
(reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:244
MoELayer with MoEScatter:88/MoEGather:135 PyLayers over the CUDA
global_scatter/global_gather ops; gates in moe/gate/). Design: experts are
one stacked weight tensor sharded over the 'ep' mesh axis; token dispatch
is a capacity-bucketed einsum + `lax.all_to_all` (inside SPMD) instead of
the reference's variable-length global_scatter — static shapes keep XLA
fast (dropped tokens follow the standard Switch capacity-factor recipe).
"""
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..ops._helpers import apply_jfn, ensure_tensor
from ..tensor_core import Tensor

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate",
           "moe_dispatch_combine"]


class NaiveGate(nn.Layer):
    """top-k linear gate (reference gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.gate = nn.Linear(d_model, num_experts)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.gate(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=2)


def moe_dispatch_combine(x, gate_logits, expert_fn, num_experts,
                         capacity_factor=1.25, topk=1, axis_name=None):
    """Pure-jax switch routing.

    x: [tokens, d]; gate_logits: [tokens, E]; expert_fn(e_idx, xs) applies
    expert e to xs — used with stacked expert weights via vmap.
    Returns (out [tokens, d], aux_loss scalar).
    """
    tokens, d = x.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    capacity = int(math.ceil(tokens / num_experts * capacity_factor * topk))

    out = jnp.zeros_like(x)
    aux = 0.0
    me = probs.mean(axis=0)
    for k in range(topk):
        top_idx = jnp.argmax(probs, axis=-1)  # [tokens]
        top_p = jnp.take_along_axis(probs, top_idx[:, None], -1)[:, 0]
        probs = probs * (1.0 - jax.nn.one_hot(top_idx, num_experts))
        onehot = jax.nn.one_hot(top_idx, num_experts)  # [tokens, E]
        # position of each token within its expert's queue
        pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [tokens, E]
        keep = (pos < capacity) & (onehot > 0)
        # dispatch tensor [E, capacity, tokens]
        pos_idx = pos.sum(-1).astype(jnp.int32)
        disp = (
            jax.nn.one_hot(pos_idx, capacity, dtype=x.dtype)[:, None, :]
            * keep[:, :, None]
        )  # [tokens, E, capacity]
        disp = jnp.swapaxes(disp, 0, 1)  # [E, tokens, capacity]
        expert_in = jnp.einsum("etc,td->ecd", disp, x)
        expert_out = expert_fn(expert_in)  # [E, capacity, d]
        combined = jnp.einsum("etc,ecd->td", disp, expert_out)
        out = out + combined * top_p[:, None].astype(x.dtype)
        ce = onehot.mean(axis=0)
        aux = aux + num_experts * jnp.sum(me * ce)
    return out, aux


class MoELayer(nn.Layer):
    """(reference moe_layer.py:244.) experts built as stacked params so the
    'ep' axis shards the expert dim; `forward` routes per token."""

    def __init__(self, d_model, d_hidden, num_experts, gate=None, topk=1,
                 capacity_factor=1.25, activation="gelu", mp_group=None,
                 recompute_interval=0):
        super().__init__()
        self.num_experts = num_experts
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.gate = gate or NaiveGate(d_model, num_experts, topk=topk)
        init = nn.initializer.XavierUniform()
        from ..core import dtype as dtype_mod

        dt = dtype_mod.convert_dtype("float32")
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        for p in (self.w1, self.b1, self.w2, self.b2):
            from .fleet.meta_parallel.mp_layers import mark_sharding

            mark_sharding(p, "ep", *([None] * (p.ndim - 1)))
        self._act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]
        self.aux_loss = None

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape

        flat = reshape(x, [-1, d])
        logits = self.gate(flat)
        act = self._act
        nE, topk, cf = self.num_experts, self.topk, self.capacity_factor

        def jfn(xv, gv, w1, b1, w2, b2):
            def expert_fn(expert_in):  # [E, capacity, d]
                h = act(jnp.einsum("ecd,edh->ech", expert_in, w1) + b1)
                return jnp.einsum("ech,ehd->ecd", h, w2) + b2

            out, aux = moe_dispatch_combine(
                xv, gv, expert_fn, nE, capacity_factor=cf, topk=topk)
            return out, aux

        out, aux = apply_jfn("moe_layer", jfn, flat, logits, self.w1,
                             self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return reshape(out, list(orig_shape))
