"""Mixture-of-Experts with expert parallelism.

TPU-native re-design of the reference MoE
(reference: python/paddle/incubate/distributed/models/moe/moe_layer.py:244
MoELayer with MoEScatter:88/MoEGather:135 PyLayers over the CUDA
global_scatter/global_gather ops; gates in moe/gate/). Two formulations:

* `MoELayer` (outside shard_map): experts are one stacked weight tensor
  sharded over the 'ep' mesh axis via GSPMD annotations; dispatch is a
  capacity-bucketed einsum over the full (replicated) token set and the
  partitioner inserts the collectives. Simple, but every rank routes all
  tokens — replication, not true EP.

* `moe_a2a_dispatch_combine` (inside shard_map, e.g. the 1F1B pipeline
  body): TRUE expert parallelism with token exchange. Each 'ep' rank
  takes a 1/ep slice of the tokens, capacity-buckets them locally
  (GShard grouped capacity: C = ceil(t_loc·cf/E) per group), exchanges
  buckets with `lax.all_to_all` (dispatch AND combine, explicit
  custom_vjp pairs like mp_ops.py), runs only its E/ep resident experts,
  and all-gathers the combined outputs. Per-rank dispatch traffic and
  routing FLOPs are O(tokens/ep), matching the reference's
  global_scatter/global_gather (paddle/fluid/operators/collective/
  global_scatter_op.cc:1, global_gather_op.cc:1) — static shapes keep
  XLA fast (dropped tokens follow the standard Switch capacity recipe).
  Gate statistics for the load-balancing aux loss are psum'd over 'ep'
  so the aux term matches the full-batch (serial) computation exactly.
"""
import functools
import math

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .. import nn
from ..ops._helpers import apply_jfn, ensure_tensor
from ..tensor_core import Tensor

__all__ = ["MoELayer", "NaiveGate", "SwitchGate", "GShardGate",
           "moe_dispatch_combine", "moe_a2a_dispatch_combine",
           "ep_scatter_tokens", "ep_gather_tokens", "ep_all_to_all",
           "moe_a2a_capacity", "switch_dispatch", "topk_rounds"]


# ---------------------------------------------------------------------
# explicit-SPMD collectives for token-sharded MoE (inside shard_map).
# VJP pairing follows mp_ops.py: the cotangent of a value REPLICATED
# over 'ep' is itself replicated, so gather/slice transpose to each
# other with NO psum (a default psum transpose would overcount by ep).
# ---------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def ep_scatter_tokens(x, ep, axis="ep"):
    """Slice this rank's tokens/ep chunk of a replicated [t, ...] batch.
    Forward: dynamic slice; backward: all_gather of the per-rank chunk
    cotangents (dx must be replicated again)."""
    t_loc = x.shape[0] // ep
    r = lax.axis_index(axis)
    return lax.dynamic_slice_in_dim(x, r * t_loc, t_loc, axis=0)


def _scatter_fwd(x, ep, axis):
    return ep_scatter_tokens(x, ep, axis), None


def _scatter_bwd(ep, axis, _, g):
    return (lax.all_gather(g, axis, axis=0, tiled=True),)


ep_scatter_tokens.defvjp(_scatter_fwd, _scatter_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_gather_tokens(x_loc, axis="ep"):
    """All-gather per-rank [t/ep, ...] chunks into the replicated [t, ...]
    batch. Forward: tiled all_gather; backward: slice this rank's chunk
    of the (replicated) cotangent."""
    return lax.all_gather(x_loc, axis, axis=0, tiled=True)


def _gather_fwd(x_loc, axis):
    return ep_gather_tokens(x_loc, axis), x_loc.shape[0]


def _gather_bwd(axis, t_loc, g):
    r = lax.axis_index(axis)
    return (lax.dynamic_slice_in_dim(g, r * t_loc, t_loc, axis=0),)


ep_gather_tokens.defvjp(_gather_fwd, _gather_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def ep_all_to_all(x, axis="ep"):
    """All-to-all over leading dim [ep, ...]: rank r's chunk j goes to
    rank j's slot r (the reference's global_scatter/global_gather wire
    format, static-shape). Self-adjoint: the transpose of an all-to-all
    is the same all-to-all (cotangent of my slot r flows back to rank
    r's chunk for me)."""
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0, tiled=False)


def _a2a_fwd(x, axis):
    return ep_all_to_all(x, axis), None


def _a2a_bwd(axis, _, g):
    return (ep_all_to_all(g, axis),)


ep_all_to_all.defvjp(_a2a_fwd, _a2a_bwd)


def switch_dispatch(probs, num_experts, capacity, dtype):
    """Shared top-1 (switch) dispatch recipe: argmax routing, per-expert
    cumsum positions, capacity overflow-drop, one-hot dispatch tensor.
    Returns (disp [E, t, C], top_p [t], onehot [t, E]) — the ONE place
    the capacity/keep logic lives (a2a path, in-pipeline dense path).
    For top-k, call per round on probs with previous winners zeroed
    (see topk_rounds)."""
    top_idx = jnp.argmax(probs, axis=-1)
    top_p = jnp.take_along_axis(probs, top_idx[:, None], -1)[:, 0]
    onehot = jax.nn.one_hot(top_idx, num_experts, dtype=jnp.float32)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot
    keep = (pos < capacity) & (onehot > 0)
    pos_idx = pos.sum(-1).astype(jnp.int32)
    disp = (jax.nn.one_hot(pos_idx, capacity, dtype=dtype)[:, None, :]
            * keep[:, :, None])                   # [t, E, C]
    return jnp.swapaxes(disp, 0, 1), top_p, onehot


def topk_rounds(probs, topk):
    """Iterator of per-round routing probabilities for top-k gating:
    round k sees probs with rounds <k's winners zeroed (the reference
    NaiveGate/GShardGate top-k recipe as k argmax rounds)."""
    work = probs
    for _ in range(topk):
        yield work
        top_idx = jnp.argmax(work, axis=-1)
        work = work * (1.0 - jax.nn.one_hot(top_idx, work.shape[-1],
                                            dtype=work.dtype))


def moe_a2a_capacity(tokens, ep, num_experts, capacity_factor):
    """Per-group (per-ep-rank) expert capacity: ceil(t_loc·cf/E) —
    GShard's grouped formulation, giving O(tokens/ep) per-rank buffers."""
    t_loc = tokens // ep
    return max(1, int(math.ceil(t_loc * capacity_factor / num_experts)))


def topk_pack_dispatch(probs, num_experts, capacity, dtype, topk,
                       stat_reduce=None):
    """Shared top-k routing: k switch rounds PACKED along the capacity
    dim into one dispatch/combine tensor pair — the ONE home of the
    routing loop for the dense, a2a and in-pipeline paths.

    Per-round capacity is `capacity` (= cf·t/E slots), so the total
    buffer across rounds is k·cf·t/E — GShard's top-k total — while
    expert FLOPs and exchange bytes stay LINEAR in k (a per-round
    dispatch at cf·k capacity run k times would cost k² and 2k
    collectives on the a2a path).

    Returns (disp [E, t, k·C], comb [E, t, k·C], aux). `comb` folds each
    round's gate probability into the combine side, so
    ``out = einsum('etc,ecd->td', comb, expert_out)``. `stat_reduce`
    (identity when None) reduces the gate statistics (me/ce vectors)
    over token-sharding axes for the GShard load-balancing aux term.
    """
    me = probs.mean(axis=0)
    if stat_reduce is not None:
        me = stat_reduce(me)
    disps, combs = [], []
    aux = jnp.zeros([], jnp.float32)
    for round_probs in topk_rounds(probs, topk):
        disp, top_p, onehot = switch_dispatch(round_probs, num_experts,
                                              capacity, dtype)
        ce = onehot.mean(axis=0)
        if stat_reduce is not None:
            ce = stat_reduce(ce)
        aux = aux + num_experts * jnp.sum(me * ce)
        disps.append(disp)
        combs.append(disp * top_p[None, :, None].astype(dtype))
    if topk == 1:
        return disps[0], combs[0], aux
    return (jnp.concatenate(disps, axis=2),
            jnp.concatenate(combs, axis=2), aux)


def moe_a2a_dispatch_combine(x, gate_w, expert_fn, num_experts, ep,
                             capacity_factor=1.25, axis="ep",
                             stat_axes=None, n_stat_shards=None,
                             topk=1):
    """Token-sharded top-k routing with all-to-all exchange (topk=1 is
    the switch formulation; topk=2 the GShard/reference default —
    moe_layer.py gates). Each of the k rounds runs its own
    dispatch→a2a→experts→a2a→combine pass, outputs summed with the
    round's gate probability.

    Must run inside shard_map with `axis` in scope. `x` [tokens, d] is
    REPLICATED over `axis`; `gate_w` [d, E] replicated; `expert_fn`
    consumes [E/ep, ep·C, d] (this rank's resident experts applied to
    the tokens every rank dispatched to them) using the rank's LOCAL
    expert-weight shards. Returns (out [tokens, d] replicated,
    aux scalar) where aux is the GShard load-balancing term
    E·Σ_e mean_prob_e·frac_tokens_e over the FULL token set (gate
    statistics psum'd over `axis` — matches the serial computation
    exactly).

    Capacity/overflow is per GROUP (each rank's tokens/ep slice,
    C = ceil(t_loc·cf/E)): the standard GShard/Switch grouped recipe.
    With cf high enough that no token overflows, the output is exactly
    the serial full-batch routing (positions differ, kept set doesn't).

    stat_axes/n_stat_shards: mesh axes the aux gate statistics are
    psum'd over and the number of token shards they span — pass ALL
    token-sharding axes (e.g. ('ep','dp','sp')) to make aux the exact
    GLOBAL-batch value on every rank. Defaults to ((axis,), ep).

    (reference: moe_layer.py:88 MoEScatter / :135 MoEGather over
    global_scatter_op.cc / global_gather_op.cc — variable-length brpc
    exchange; here fixed-capacity buckets over one XLA all-to-all.)
    """
    t, d = x.shape
    if t % ep:
        raise ValueError(
            f"token count {t} not divisible by ep={ep}; pick "
            "batch/micro/sequence sharding so each ep group is equal")
    if num_experts % ep:
        raise ValueError(
            f"num_experts={num_experts} not divisible by ep={ep}")
    from .fleet.meta_parallel.mp_ops import allreduce_mp, copy_to_mp

    t_loc = t // ep
    e_loc = num_experts // ep
    C = moe_a2a_capacity(t, ep, num_experts, capacity_factor * topk)

    x_loc = ep_scatter_tokens(x, ep, axis)            # [t_loc, d]
    # each rank computes a DIFFERENT token slice, so the replicated
    # gate weight accumulates partial grads per rank — the psum-backward
    # bracket (mp_ops copy_to_mp) restores the full-batch gate gradient
    logits = x_loc @ copy_to_mp(gate_w, axis)         # [t_loc, E]
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # aux over the FULL batch: per-rank means psum'd over the token
    # groups (slices partition the tokens, so mean = psum(mean)/n).
    # allreduce_mp = psum forward / identity backward: each rank's
    # cotangent on its LOCAL probs is exactly ∂aux/∂probs_local (me/ce
    # are replicated values), a raw psum would n×-overcount.
    s_axes = tuple(stat_axes) if stat_axes else (axis,)
    n_sh = n_stat_shards if n_stat_shards is not None else ep
    me = allreduce_mp(probs.mean(axis=0), s_axes) / n_sh

    def one_round(round_probs):
        disp, top_p, onehot = switch_dispatch(round_probs, num_experts,
                                              C, x.dtype)
        ce = allreduce_mp(onehot.mean(axis=0), s_axes) / n_sh
        round_aux = num_experts * jnp.sum(me * ce)
        send = jnp.einsum("etc,td->ecd", disp, x_loc)  # [E, C, d]
        # group experts by owner (contiguous E/ep blocks — matches the
        # 'ep' sharding of the stacked expert weights) and exchange
        recv = ep_all_to_all(send.reshape(ep, e_loc, C, d), axis)
        expert_in = jnp.transpose(recv, (1, 0, 2, 3)).reshape(
            e_loc, ep * C, d)
        expert_out = expert_fn(expert_in)             # [e_loc, ep·C, d]
        back = jnp.transpose(
            expert_out.reshape(e_loc, ep, C, d), (1, 0, 2, 3))
        ret = ep_all_to_all(back, axis).reshape(num_experts, C, d)
        combined = jnp.einsum("etc,ecd->td", disp, ret)
        return combined * top_p[:, None].astype(x.dtype), round_aux

    out_loc = jnp.zeros_like(x_loc)
    aux = jnp.zeros([], jnp.float32)
    for round_probs in topk_rounds(probs, topk):
        o, a = one_round(round_probs)
        out_loc = out_loc + o
        aux = aux + a
    return ep_gather_tokens(out_loc, axis), aux


class NaiveGate(nn.Layer):
    """top-k linear gate (reference gate/naive_gate.py)."""

    def __init__(self, d_model, num_experts, topk=2):
        super().__init__()
        self.gate = nn.Linear(d_model, num_experts)
        self.topk = topk
        self.num_experts = num_experts

    def forward(self, x):
        return self.gate(x)


class SwitchGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=1)


class GShardGate(NaiveGate):
    def __init__(self, d_model, num_experts):
        super().__init__(d_model, num_experts, topk=2)


def moe_dispatch_combine(x, gate_logits, expert_fn, num_experts,
                         capacity_factor=1.25, topk=1, axis_name=None):
    """Pure-jax switch routing.

    x: [tokens, d]; gate_logits: [tokens, E]; expert_fn(e_idx, xs) applies
    expert e to xs — used with stacked expert weights via vmap.
    Returns (out [tokens, d], aux_loss scalar).
    """
    tokens, d = x.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    capacity = moe_a2a_capacity(tokens, 1, num_experts, capacity_factor)

    # under spmd the gate statistics (me/ce) must average over the
    # token-sharding axis or the GShard aux term sees per-shard loads
    stat_reduce = (None if axis_name is None
                   else (lambda v: jax.lax.pmean(v, axis_name)))
    disp, comb, aux = topk_pack_dispatch(probs, num_experts, capacity,
                                         x.dtype, topk,
                                         stat_reduce=stat_reduce)
    expert_in = jnp.einsum("etc,td->ecd", disp, x)   # [E, k·C, d]
    expert_out = expert_fn(expert_in)
    return jnp.einsum("etc,ecd->td", comb, expert_out), aux


class MoELayer(nn.Layer):
    """(reference moe_layer.py:244.) experts built as stacked params so the
    'ep' axis shards the expert dim; `forward` routes per token."""

    def __init__(self, d_model, d_hidden, num_experts, gate=None, topk=1,
                 capacity_factor=1.25, activation="gelu", mp_group=None,
                 recompute_interval=0):
        super().__init__()
        self.num_experts = num_experts
        self.topk = topk
        self.capacity_factor = capacity_factor
        self.gate = gate or NaiveGate(d_model, num_experts, topk=topk)
        init = nn.initializer.XavierUniform()
        from ..core import dtype as dtype_mod

        dt = dtype_mod.convert_dtype("float32")
        self.w1 = self.create_parameter([num_experts, d_model, d_hidden])
        self.b1 = self.create_parameter([num_experts, 1, d_hidden],
                                        is_bias=True)
        self.w2 = self.create_parameter([num_experts, d_hidden, d_model])
        self.b2 = self.create_parameter([num_experts, 1, d_model],
                                        is_bias=True)
        for p in (self.w1, self.b1, self.w2, self.b2):
            from .fleet.meta_parallel.mp_layers import mark_sharding

            mark_sharding(p, "ep", *([None] * (p.ndim - 1)))
        self._act = {"gelu": jax.nn.gelu, "relu": jax.nn.relu}[activation]
        self.aux_loss = None

    def forward(self, x):
        orig_shape = x.shape
        d = orig_shape[-1]
        from ..ops.manipulation import reshape

        flat = reshape(x, [-1, d])
        logits = self.gate(flat)
        act = self._act
        nE, topk, cf = self.num_experts, self.topk, self.capacity_factor

        def jfn(xv, gv, w1, b1, w2, b2):
            def expert_fn(expert_in):  # [E, capacity, d]
                h = act(jnp.einsum("ecd,edh->ech", expert_in, w1) + b1)
                return jnp.einsum("ech,ehd->ecd", h, w2) + b2

            out, aux = moe_dispatch_combine(
                xv, gv, expert_fn, nE, capacity_factor=cf, topk=topk)
            return out, aux

        out, aux = apply_jfn("moe_layer", jfn, flat, logits, self.w1,
                             self.b1, self.w2, self.b2)
        self.aux_loss = aux
        return reshape(out, list(orig_shape))
