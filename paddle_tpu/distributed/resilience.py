"""Unified transient-fault hardening: retry/backoff, step guards,
preemption drain, anomaly journal.

One :class:`RetryPolicy` (exponential backoff + jitter + deadline — the
policy ``tools/tpu_retry.sh`` hand-rolls in bash) is applied uniformly to
the coordination-KV gets in ``xproc``, the p2p transport's reconnects,
and ``Checkpointer`` I/O, so every transient-fault path shares one
telemetry stream.  :class:`StepGuard` detects NaN/Inf losses and
skips-and-journals the step with a bounded consecutive-skip abort;
:class:`DivergenceSentinel` is its escalation for compiled (fused-
update) steps — NaN/Inf or a loss spike triggers a checkpoint
ROLLBACK (:class:`DivergenceRollback`, caught by
``fleet.elastic.run_with_fault_tolerance``) with a poisoned-data-window
skip set and a bounded rollback budget.
:class:`PreemptionHandler` turns SIGTERM (the TPU maintenance-event
shape) into a drain-to-final-checkpoint instead of a mid-step kill.

Every event lands in the per-rank anomaly journal
(``$PADDLE_LOG_DIR/anomalies.rank<r>.jsonl``; override dir with
``$PT_ANOMALY_DIR``) for post-mortem forensics, and in an in-memory ring
so tests and the heartbeat thread (degraded-vs-dead marking,
launch/master.py) can observe it without touching disk.

Faults are *provoked* by the sibling ``chaos`` module; this module is
the hardening the injectors exercise.
"""
import collections
import json
import math
import os
import random
import signal
import threading
import time

from ..observability import metrics as _obs

__all__ = ["RetryPolicy", "RetryError", "StepGuard", "StepAbort",
           "DivergenceSentinel", "DivergenceRollback",
           "PreemptionHandler", "install_preemption_handler",
           "AnomalyJournal", "record", "events", "recent_failures",
           "stats", "reset"]


# ------------------------------------------------------------- telemetry

stats = {"retries": collections.Counter(),   # policy name -> retry count
         "giveups": collections.Counter()}   # policy name -> exhausted

# registry mirror (docs/OBSERVABILITY.md): per-call names carry the
# target ("kv.get:<key>") — label by the op prefix only, or every key
# becomes its own series
_RETRIES_TOTAL = _obs.counter(
    "pt_retries_total", "transient-fault retries, by operation",
    labelnames=("op",))
_GIVEUPS_TOTAL = _obs.counter(
    "pt_retry_giveups_total", "retry budgets exhausted, by operation",
    labelnames=("op",))
_JOURNAL_EVENTS = _obs.counter(
    "pt_journal_events_total", "anomaly-journal events, by kind",
    labelnames=("kind",))
_ROLLBACKS_TOTAL = _obs.counter(
    "pt_rollback_total",
    "DivergenceSentinel-triggered checkpoint rollbacks, by reason "
    "(nan | loss_spike)", labelnames=("reason",))

_recent = collections.deque(maxlen=512)      # (t_monotonic, policy name)
_recent_lock = threading.Lock()


def recent_failures(window_s=30.0):
    """Retry events observed in the last `window_s` seconds — the
    degraded-rank signal the heartbeat thread reports to the membership
    master (a rank that is beating but retry-storming is *degraded*, not
    dead; the launcher logs it instead of failing the pod)."""
    cut = time.monotonic() - window_s
    with _recent_lock:
        return sum(1 for t, _ in _recent if t >= cut)


def _note_retry(name):
    _RETRIES_TOTAL.labels(op=name.split(":", 1)[0]).inc()
    with _recent_lock:
        stats["retries"][name] += 1
        _recent.append((time.monotonic(), name))


# --------------------------------------------------------------- journal

class AnomalyJournal:
    """Append-only JSONL journal, one file per rank. Disk writes are
    best-effort (journaling must never take training down); the last 256
    events are always kept in memory for assertions and telemetry."""

    def __init__(self, path=None):
        self._explicit_path = path
        self._path = path
        self._resolved = path is not None
        self._lock = threading.Lock()
        self.events = collections.deque(maxlen=256)

    def _resolve(self):
        if self._resolved:
            return self._path
        self._resolved = True
        log_dir = (os.environ.get("PT_ANOMALY_DIR")
                   or os.environ.get("PADDLE_LOG_DIR"))
        if log_dir:
            rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            self._path = os.path.join(log_dir,
                                      f"anomalies.rank{rank}.jsonl")
        return self._path

    @property
    def path(self):
        return self._resolve()

    def write(self, kind, **fields):
        _JOURNAL_EVENTS.labels(kind=kind).inc()
        entry = {"t": time.time(),
                 "rank": int(os.environ.get("PADDLE_TRAINER_ID", "0")),
                 "kind": kind}
        entry.update(fields)
        # journal events also flow into the flight recorder's ring, so
        # a postmortem dump carries the anomalies that PRECEDED the
        # failure (one event stream: docs/OBSERVABILITY.md)
        try:
            from ..observability.flight_recorder import record_event

            record_event("journal", entry=dict(entry))
        except Exception:  # ptlint: disable=PTL804 (the journal cannot journal its own mirror failure)
            pass
        # append + path resolution under the lock; file I/O OUTSIDE it —
        # open()/write() on a slow (or hung NFS) log dir must not queue
        # every other journaling thread behind disk (PTL802). Lines may
        # interleave across threads, but each json.dumps is a single
        # write() of one line, and jsonl readers don't care about order.
        with self._lock:
            self.events.append(entry)
            path = self._resolve()
        if path:
            try:
                os.makedirs(os.path.dirname(path) or ".",
                            exist_ok=True)
                with open(path, "a") as f:
                    f.write(json.dumps(entry) + "\n")
            except OSError:
                pass
        return entry


_journal = AnomalyJournal()


def record(kind, **fields):
    """Append one event to the per-rank anomaly journal."""
    return _journal.write(kind, **fields)


def events(kind=None):
    """In-memory view of recent journal entries (newest last)."""
    evs = list(_journal.events)
    return evs if kind is None else [e for e in evs if e["kind"] == kind]


def reset():
    """Test hook: clear telemetry and re-resolve the journal path."""
    global _journal
    stats["retries"].clear()
    stats["giveups"].clear()
    with _recent_lock:
        _recent.clear()
    _journal = AnomalyJournal()


# ----------------------------------------------------------- RetryPolicy

class RetryError(TimeoutError):
    """All attempts exhausted (count or deadline). `.last` holds the
    final underlying exception (also chained as __cause__)."""

    def __init__(self, msg, last=None):
        super().__init__(msg)
        self.last = last


class RetryPolicy:
    """Exponential backoff + jitter + deadline.

    ``run(fn)`` calls `fn` until it returns, retrying exceptions listed
    in `retry_on` while attempts and the deadline budget last. Sleeps
    ``base_s * multiplier**attempt`` (capped at `max_backoff_s`) plus up
    to ``jitter`` fractional randomization, never past the deadline.

    `max_attempts=None` retries until the deadline alone — the right
    shape for "peer is mid-restart" waits where the caller's timeout is
    the real budget.

    `give_up_on` lists exception types (subclasses of `retry_on` shapes)
    that are NEVER transient for this operation — they exhaust
    immediately, raising the same RetryError the caller already handles,
    without burning backoff sleeps (e.g. FileNotFoundError on a
    checkpoint shard: the file will not appear on retry).
    """

    def __init__(self, max_attempts=5, base_s=0.05, multiplier=2.0,
                 max_backoff_s=2.0, deadline_s=None, jitter=0.25,
                 retry_on=(OSError,), give_up_on=(), name="op", rng=None):
        self.max_attempts = max_attempts
        self.base_s = base_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.deadline_s = deadline_s
        self.jitter = jitter
        self.retry_on = tuple(retry_on)
        self.give_up_on = tuple(give_up_on)
        self.name = name
        self._rng = rng or random

    def backoff(self, attempt):
        """Sleep length after failed attempt `attempt` (0-based)."""
        raw = min(self.base_s * self.multiplier ** attempt,
                  self.max_backoff_s)
        return raw * (1.0 + self.jitter * self._rng.random())

    def run(self, fn, *args, deadline_s=None, name=None, on_retry=None,
            **kwargs):
        name = name or self.name
        budget = self.deadline_s if deadline_s is None else deadline_s
        deadline = None if budget is None else time.monotonic() + budget
        attempt = 0
        while True:
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                attempt += 1
                if isinstance(e, self.give_up_on):
                    stats["giveups"][name] += 1
                    _GIVEUPS_TOTAL.labels(op=name.split(":", 1)[0]).inc()
                    record("retry_exhausted", op=name, attempts=attempt,
                           error=repr(e))
                    raise RetryError(
                        f"{name}: non-transient failure: {e!r}",
                        last=e) from e
                _note_retry(name)
                record("retry", op=name, attempt=attempt, error=repr(e))
                if on_retry is not None:
                    on_retry(attempt, e)
                out_of_attempts = (self.max_attempts is not None
                                   and attempt >= self.max_attempts)
                delay = self.backoff(attempt - 1)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        out_of_attempts = True
                    else:
                        delay = min(delay, remaining)
                if out_of_attempts:
                    stats["giveups"][name] += 1
                    _GIVEUPS_TOTAL.labels(op=name.split(":", 1)[0]).inc()
                    record("retry_exhausted", op=name, attempts=attempt,
                           error=repr(e))
                    raise RetryError(
                        f"{name}: gave up after {attempt} attempt(s): "
                        f"{e!r}", last=e) from e
                time.sleep(delay)


# ------------------------------------------------------------- StepGuard

class StepAbort(RuntimeError):
    """Too many consecutive skipped steps — the anomaly is systemic
    (diverged optimizer, corrupted params), not transient; let the
    elastic layer restore a checkpoint instead of burning data."""


def _scalar(value):
    """float() of a loss however it arrives: paddle Tensor, jax array,
    numpy, or python scalar. (Forces a device sync — NaN detection is
    inherently a sync point; call once per step.)"""
    numpy = getattr(value, "numpy", None)
    if callable(numpy):
        value = numpy()
    try:
        return float(value)
    except (TypeError, ValueError):
        import numpy as np

        return float(np.asarray(value).reshape(-1)[0])


class StepGuard:
    """Step-level failure guard: NaN/Inf losses are skipped-and-journaled
    with a bounded consecutive-skip abort. `max_consecutive_skips` is the
    ALLOWANCE: that many consecutive skips are tolerated, and the skip
    that exceeds it raises StepAbort.

    Usage (eager loop — check BEFORE applying the update)::

        guard = StepGuard(max_consecutive_skips=3)
        while step < STEPS:
            loss = loss_fn(...)
            if not guard.check(loss, step=step):
                continue            # retry (transient) or advance (skip)
            loss.backward(); opt.step(); opt.clear_grad()
            step += 1

    With a compiled TrainStep the update is fused into the step program;
    `check` then gates *persisting* the step (checkpoint / step advance),
    and recovery from a poisoned update is a checkpoint restore — see
    docs/RESILIENCE.md.

    Chaos integration: each check fires scope ``step`` (crash/hang-at-
    step-N injectors) and routes the loss value through the
    ``step.nan`` poisoner, so the detection path itself is exercised.
    """

    def __init__(self, max_consecutive_skips=5, name="train"):
        self.max_consecutive_skips = max_consecutive_skips
        self.name = name
        self.skipped = 0            # total skipped steps
        self.ok = 0                 # total accepted steps
        self._consecutive = 0

    def check(self, loss, step=None):
        """True → proceed with the update; False → skip this step
        (already journaled). Raises StepAbort on the skip that exceeds
        the `max_consecutive_skips` allowance."""
        from . import chaos

        chaos.fire("step")          # crash/hang-at-step-N injectors
        value = chaos.poison(_scalar(loss))
        if math.isfinite(value):
            self._consecutive = 0
            self.ok += 1
            return True
        self.skipped += 1
        self._consecutive += 1
        record("nan_step", guard=self.name, step=step, value=str(value),
               consecutive=self._consecutive)
        if self._consecutive > self.max_consecutive_skips:
            record("step_abort", guard=self.name, step=step,
                   consecutive=self._consecutive)
            raise StepAbort(
                f"{self.name}: {self._consecutive} consecutive non-finite "
                f"losses (> {self.max_consecutive_skips}) at step {step}")
        return False


# ---------------------------------------------- DivergenceSentinel

class DivergenceRollback(RuntimeError):
    """The sentinel demands a checkpoint rollback: the live parameters
    are presumed poisoned (a fused-update compiled step applies the
    update BEFORE the loss is observable on the host), so skipping
    forward is not enough — restore the last COMPLETE checkpoint and
    advance past the poisoned data window.
    `fleet.elastic.run_with_fault_tolerance` catches this and restores
    WITHOUT consuming a restart (the sentinel bounds its own budget)."""

    def __init__(self, msg, step=None, reason="nan", value=None):
        super().__init__(msg)
        self.step = step
        self.reason = reason
        self.value = value


class DivergenceSentinel:  # ptlint: thread-shared
    """Divergence monitor + rollback trigger over the per-step loss
    telemetry — StepGuard's escalation path for compiled train steps.

    StepGuard's skip-and-retry is the right call for an EAGER loop,
    where a NaN loss can gate the update. With a compiled
    TrainStep/DistributedTrainStep/HybridTrainStep the optimizer update
    is fused into the step program: by the time the host sees the loss,
    the parameters are already updated — a NaN or a spiking loss means
    the live state may be poisoned. The sentinel therefore journals the
    anomaly, marks the poisoned data window (``should_skip``), and
    raises :class:`DivergenceRollback` so the supervision loop
    (`run_with_fault_tolerance`) restores the last COMPLETE checkpoint
    and resumes in-process — no pod restart, commitment preserved by
    `Checkpointer.load` (docs/RESILIENCE.md "Coordinated checkpointing
    + rollback").

    Detection: non-finite loss (reason ``nan``), or — once
    ``min_history`` finite losses are in the rolling window — a loss
    above ``spike_factor`` × the window median (reason ``loss_spike``;
    assumes the positive-loss shape of CE/MSE objectives).
    ``max_rollbacks`` bounds the budget: the rollback that exceeds it
    raises StepAbort instead (systemic divergence — hand the job to the
    elastic restart layer rather than thrash restore/replay forever).

    Usage inside a run_with_fault_tolerance train_fn::

        sentinel = DivergenceSentinel()
        def train_fn(start):
            step = start
            while step < STEPS:
                if sentinel.should_skip(step):   # poisoned data window
                    advance_data(); step += 1; continue
                loss = train_step(*batch(step))
                sentinel.check(loss, step=step)  # raises on divergence
                ckpt.save(step + 1); step += 1

    Chaos integration mirrors StepGuard: every check fires scope
    ``step`` and routes the observed loss through the ``step.nan``
    poisoner. Thread-shared: the heartbeat/telemetry threads read
    counters while the train loop writes them — all mutation is under
    one lock (PTL7xx fence)."""

    def __init__(self, window=16, spike_factor=4.0, min_history=4,
                 max_rollbacks=3, skip_window=1, name="train"):
        self.window = int(window)
        self.spike_factor = float(spike_factor)
        self.min_history = max(1, int(min_history))
        self.max_rollbacks = int(max_rollbacks)
        self.skip_window = max(1, int(skip_window))
        self.name = name
        self.rollbacks = 0          # rollbacks demanded so far
        self.ok = 0                 # accepted steps
        self._lock = threading.Lock()
        self._history = collections.deque(maxlen=self.window)
        self._poisoned = set()      # step indices to skip after restore

    def should_skip(self, step):
        """True when `step` sits in a poisoned data window — the loop
        must advance its data pipeline past it WITHOUT dispatching the
        update (replaying the batch that diverged once diverges
        again)."""
        with self._lock:
            return step in self._poisoned

    def poisoned_steps(self):
        with self._lock:
            return sorted(self._poisoned)

    def check(self, loss, step=None):
        """Accept one observed loss. Returns True when training may
        proceed; raises DivergenceRollback (restore + skip window) on
        NaN/Inf or a loss spike, StepAbort past the rollback budget."""
        from . import chaos

        chaos.fire("step")          # crash/hang-at-step-N injectors
        value = chaos.poison(_scalar(loss))
        with self._lock:
            reason = None
            if not math.isfinite(value):
                reason = "nan"
            elif len(self._history) >= self.min_history:
                med = sorted(self._history)[len(self._history) // 2]
                if med > 0 and value > self.spike_factor * med:
                    reason = "loss_spike"
            if reason is None:
                self.ok += 1
                self._history.append(value)
                return True
            # poison the data window ending at `step`, so the resumed
            # run advances past the batches that fed the divergence
            if step is not None:
                for s in range(step - self.skip_window + 1, step + 1):
                    self._poisoned.add(s)
            self.rollbacks += 1
            n = self.rollbacks
        record("rollback", guard=self.name, step=step, reason=reason,
               value=str(value), rollbacks=n)
        if n > self.max_rollbacks:
            record("step_abort", guard=self.name, step=step,
                   rollbacks=n)
            raise StepAbort(
                f"{self.name}: rollback budget exhausted ({n} > "
                f"{self.max_rollbacks}) at step {step} — divergence is "
                "systemic, not transient")
        _ROLLBACKS_TOTAL.labels(reason=reason).inc()
        raise DivergenceRollback(
            f"{self.name}: {reason} at step {step} (loss={value!r}) — "
            "restoring last complete checkpoint",
            step=step, reason=reason, value=value)


# ---------------------------------------------------- PreemptionHandler

class PreemptionHandler:
    """SIGTERM → drain to a final checkpoint and exit cleanly (the TPU
    maintenance-event shape: the scheduler sends SIGTERM, then SIGKILL
    after a grace window).

    The signal handler only sets a flag — the train loop polls
    ``triggered()`` at step boundaries and calls ``drain(checkpointer,
    step)``, so the checkpoint is taken at a consistent point instead of
    mid-step. Must be installed from the main thread."""

    def __init__(self, signals=(signal.SIGTERM,)):
        self._flag = threading.Event()
        self._signum = None
        self._signal_logged = False
        self._old = {}
        for sig in signals:
            self._old[sig] = signal.signal(sig, self._on_signal)

    def _on_signal(self, signum, frame):
        # flag-set ONLY: record() takes the (non-reentrant) journal lock
        # and does file I/O — from a signal handler that interrupts a
        # journal write it would self-deadlock the main thread
        self._signum = signum
        self._flag.set()

    def _log_signal(self):
        if self._flag.is_set() and not self._signal_logged:
            self._signal_logged = True
            record("preempt_signal", signum=self._signum)

    def triggered(self):
        self._log_signal()          # journal from the poll site, not
        return self._flag.is_set()  # the signal handler

    def drain(self, checkpointer=None, step=None):
        """Flush pending async saves and take a final checkpoint.
        Returns True once drained (idempotent; safe with no
        checkpointer — then it only journals)."""
        self._log_signal()
        if checkpointer is not None:
            checkpointer.wait()
            if step is not None:
                checkpointer.save(step)
                checkpointer.wait()
        record("preempt_drain", step=step)
        return True

    def restore(self):
        """Reinstate the signal handlers that were active before."""
        for sig, old in self._old.items():
            signal.signal(sig, old)
        self._old.clear()


def install_preemption_handler(signals=(signal.SIGTERM,)):
    """Install and return a PreemptionHandler (main thread only)."""
    return PreemptionHandler(signals=signals)
