"""Parameter-server path: sparse embedding tables for recommendation.

TPU-native re-design of the reference PS stack (reference:
paddle/fluid/distributed/ps/table/memory_sparse_table.h:39 (hash-grown
rows, per-slot optimizer rules sparse_sgd_rule.cc),
ps/service/ps_client.h:63 pull/push RPC, python
distributed/ps/the_one_ps.py:919 TheOnePSRuntime).

The reference splits the job into brpc KV servers + trainers doing async
pull/push, because GPU memory can't hold ads-scale vocabularies. The
TPU-native split is host-RAM vs HBM on the SAME machines:

- `MemorySparseTable` — in-process host KV (id → row), rows created on
  first touch (unbounded vocab), per-row optimizer state applied on push
  (SGD / AdaGrad rules, as the reference applies optimizers server-side).
  Single-process per table; multi-host id routing (reference `id % nproc`
  table sharding) is not implemented yet — in a multi-host job give each
  process its own table over a disjoint id space, or use
  `ShardedEmbedding`.
- `SparseEmbedding` — the `paddle.static.nn.sparse_embedding` analog: a
  layer that pulls the batch's unique rows to HBM, runs the dense lookup
  on device (tape-differentiable), and pushes row gradients back on
  backward via a gradient hook (async-push semantics). Eager-mode by
  design, like the reference's PS mode (the dense math still jits).
- `ShardedEmbedding` — the SPMD alternative when the vocab fits HBM:
  table row-sharded over a mesh axis; XLA inserts the gather/all-to-all
  (SparseCore-style path). Works inside DistributedTrainStep.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..tensor_core import Tensor
from . import mesh as mesh_mod

__all__ = ["SparseSGDRule", "SparseAdaGradRule", "SparseAdamRule",
           "MemorySparseTable", "SSDSparseTable", "ShardedSparseTable",
           "GeoSparseTable", "make_sparse_table", "resolve_rule",
           "SparseEmbedding", "ShardedEmbedding", "live_tables"]

# every SparseEmbedding registers here so fleet.stop_worker()/
# save_persistables can flush/save all live PS tables (the reference's
# server-side table registry, the_one_ps.py _get_tables). Weak refs:
# the registry must not keep dead embeddings' tables alive.
import weakref as _weakref

_LIVE_TABLES = []  # (name, weakref) pairs


def _register_table(table, name=None):
    for _, ref in _LIVE_TABLES:
        if ref() is table:
            return  # one table shared by several embeddings: register once
    name = name or f"sparse_table_{len(_LIVE_TABLES)}"
    _LIVE_TABLES.append((name, _weakref.ref(table)))


def live_tables():
    """(name, table) for every live registered table; dead refs pruned."""
    out = []
    alive = []
    for name, ref in _LIVE_TABLES:
        t = ref()
        if t is not None:
            out.append((name, t))
            alive.append((name, ref))
    _LIVE_TABLES[:] = alive
    return out


# ------------------------------------------------------ optimizer rules

class SparseSGDRule:
    """reference: ps/table/sparse_sgd_rule.cc naive rule."""

    slot_dim = 0

    def __init__(self, learning_rate=0.01):
        self.lr = learning_rate

    def slots_width(self, dim):
        return self.slot_dim

    def init_slots(self, n, dim):
        return np.zeros((n, 0), np.float32)

    def apply(self, rows, slots, grads):
        return rows - self.lr * grads, slots


class SparseAdaGradRule:
    """reference: sparse_adagrad rule — per-row accumulated g², applied
    server-side on push."""

    slot_dim = 1

    def __init__(self, learning_rate=0.05, initial_g2sum=0.0, eps=1e-8):
        self.lr = learning_rate
        self.g0 = initial_g2sum
        self.eps = eps

    def slots_width(self, dim):
        return self.slot_dim

    def init_slots(self, n, dim):
        return np.full((n, 1), self.g0, np.float32)

    def apply(self, rows, slots, grads):
        g2 = slots[:, 0] + (grads * grads).mean(axis=1)
        scale = self.lr / (np.sqrt(g2) + self.eps)
        return rows - scale[:, None] * grads, g2[:, None]


class SparseAdamRule:
    """reference: sparse_sgd_rule.cc SparseAdamSGDRule — per-element
    m/v moments plus a per-row step count, applied server-side on push.
    Slot layout [m(dim), v(dim), t] matches the native C++ core."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 eps=1e-8):
        self.lr = learning_rate
        self.beta1 = beta1
        self.beta2 = beta2
        self.eps = eps

    def slots_width(self, dim):
        return 2 * dim + 1

    def init_slots(self, n, dim):
        return np.zeros((n, 2 * dim + 1), np.float32)

    def apply(self, rows, slots, grads):
        dim = rows.shape[1]
        m, v, t = slots[:, :dim], slots[:, dim:2 * dim], slots[:, -1]
        t = t + 1.0
        m = self.beta1 * m + (1 - self.beta1) * grads
        v = self.beta2 * v + (1 - self.beta2) * grads * grads
        mhat = m / (1 - self.beta1 ** t[:, None])
        vhat = v / (1 - self.beta2 ** t[:, None])
        new_rows = rows - self.lr * mhat / (np.sqrt(vhat) + self.eps)
        return new_rows, np.concatenate([m, v, t[:, None]], axis=1)


def resolve_rule(rule):
    """Accept a rule object or its reference config name ('sgd'/'naive',
    'adagrad', 'adam'; reference sparse_sgd_rule.cc registers rules by
    name)."""
    if rule is None or not isinstance(rule, str):
        return rule
    names = {"sgd": SparseSGDRule, "naive": SparseSGDRule,
             "adagrad": SparseAdaGradRule,
             "std_adagrad": SparseAdaGradRule,
             "adam": SparseAdamRule}
    try:
        return names[rule]()
    except KeyError:
        raise ValueError(
            f"unknown sparse rule {rule!r}; one of {sorted(names)}"
        ) from None


# --------------------------------------------------------------- table

def make_sparse_table(embedding_dim, rule=None, initializer=None, seed=0,
                      backend="auto", path=None, accessor=None):
    """Table factory. backend="auto"/"native" uses the C++ core
    (paddle_tpu.native NativeSparseTable, mirroring the reference's C++
    memory_sparse_table) when available and the rule is a stock
    SGD/AdaGrad/Adam with no custom initializer; backend="ssd" (requires
    `path`) memmaps rows to disk (reference ssd_sparse_table.h);
    otherwise (or with backend="python") the numpy MemorySparseTable.
    accessor="ctr" tracks per-row show/click with decay-scored eviction
    (reference ctr_accessor.cc; memory/native engines only).
    All expose the same pull/push/len/state_dict contract."""
    rule = resolve_rule(rule)
    if path is not None and backend == "auto":
        backend = "ssd"  # an explicit path is a request for persistence
    if path is not None and backend not in ("ssd",):
        raise ValueError(
            f'`path` given but backend={backend!r} does not persist — '
            'use backend="ssd" (or "auto")')
    if backend == "ssd":
        if path is None:
            raise ValueError('backend="ssd" needs a directory `path`')
        if accessor is not None:
            raise ValueError(
                "accessor='ctr' is not supported on the SSD backend yet "
                "(show/click meta is not memmapped) — use memory/native")
        return SSDSparseTable(embedding_dim, path, rule=rule,
                              initializer=initializer, seed=seed)
    if backend in ("auto", "native"):
        from .. import native

        kind = None
        if rule is None or isinstance(rule, SparseAdaGradRule):
            kind = "adagrad"
        elif isinstance(rule, SparseAdamRule):
            kind = "adam"
        elif isinstance(rule, SparseSGDRule):
            kind = "sgd"
        usable = (kind is not None and initializer is None
                  and native.is_available())
        if usable:
            r = rule or SparseAdaGradRule()
            kw = dict(lr=r.lr, seed=seed, accessor=accessor)
            if kind == "adagrad":
                kw.update(g0=r.g0, eps=r.eps)
            elif kind == "adam":
                kw.update(beta1=r.beta1, beta2=r.beta2, eps=r.eps)
            return native.NativeSparseTable(embedding_dim, rule=kind, **kw)
        if backend == "native":
            raise RuntimeError(
                "native backend requested but unavailable (no g++) "
                "or incompatible with a custom rule/initializer")
    return MemorySparseTable(embedding_dim, rule=rule,
                             initializer=initializer, seed=seed,
                             accessor=accessor)


class MemorySparseTable:
    """Host-RAM KV table with create-on-first-touch rows (pure-python
    engine; see make_sparse_table for the native C++ alternative).
    accessor="ctr" tracks per-row (show, click, unseen) with
    `update_show_click` and decay-scored eviction via `shrink`
    (reference ps/table/ctr_accessor.cc)."""

    def __init__(self, embedding_dim, rule=None, initializer=None, seed=0,
                 accessor=None):
        if accessor not in (None, "ctr"):
            raise ValueError(f"accessor={accessor!r}: expected None/'ctr'")
        self.accessor = accessor
        self._meta = np.zeros((0, 3), np.float32)  # show, click, unseen
        self.dim = embedding_dim
        self.rule = resolve_rule(rule) or SparseAdaGradRule()
        self._rng = np.random.default_rng(seed)
        self._init = initializer or (
            lambda n: (self._rng.standard_normal((n, self.dim)) /
                       np.sqrt(self.dim)).astype(np.float32))
        # id-aware initializers (f(n, ids)) make row values a pure
        # function of the id — required for shard-count-independent
        # initialization (a sharded table must equal the 1-process one)
        import inspect

        try:
            self._init_takes_ids = (
                len(inspect.signature(self._init).parameters) >= 2)
        except (TypeError, ValueError):
            self._init_takes_ids = False
        self._rows = {}   # id -> row index in the arrays below
        self._data = np.zeros((0, self.dim), np.float32)
        self._slots = self.rule.init_slots(0, self.dim)

    def __len__(self):
        return len(self._rows)

    def _ensure(self, ids):
        # dedupe: a new id repeated within one batch must allocate ONE row
        missing = list(dict.fromkeys(
            int(i) for i in ids if int(i) not in self._rows))
        if missing:
            base = len(self._rows)
            for k, i in enumerate(missing):
                self._rows[i] = base + k
            new = (self._init(len(missing), np.asarray(missing, np.int64))
                   if self._init_takes_ids else self._init(len(missing)))
            self._append_rows(new,
                              self.rule.init_slots(len(missing), self.dim))
            if self.accessor:
                self._meta = np.concatenate(
                    [self._meta, np.zeros((len(missing), 3), np.float32)])

    def _append_rows(self, new_rows, new_slots):
        """Storage hook: append freshly-initialized rows (overridden by
        SSDSparseTable to write into the memmap)."""
        self._data = np.concatenate([self._data, new_rows])
        self._slots = np.concatenate([self._slots, new_slots])

    def _ordered_ids(self):
        """ids sorted by their row index (the on-disk/state-dict order)."""
        ids = np.fromiter(self._rows.keys(), np.int64, len(self._rows))
        order = np.argsort([self._rows[int(i)] for i in ids])
        return ids[order]

    def pull(self, ids):
        """ids: 1-D int array → (n, dim) float32 rows (reference
        PSClient::PullSparse)."""
        ids = np.asarray(ids).reshape(-1)
        self._ensure(ids)
        idx = np.fromiter((self._rows[int(i)] for i in ids), np.int64,
                          len(ids))
        if self.accessor:
            self._meta[idx, 2] = 0.0
        return self._data[idx]

    def push(self, ids, grads):
        """Apply the optimizer rule to the given rows (reference
        PSClient::PushSparse; dedup-accumulates repeated ids)."""
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads, np.float32).reshape(len(ids), self.dim)
        uniq, inv = np.unique(ids, return_inverse=True)
        acc = np.zeros((len(uniq), self.dim), np.float32)
        np.add.at(acc, inv, grads)
        self._ensure(uniq)
        idx = np.fromiter((self._rows[int(i)] for i in uniq), np.int64,
                          len(uniq))
        new_rows, new_slots = self.rule.apply(
            self._data[idx], self._slots[idx], acc)
        self._data[idx] = new_rows
        self._slots[idx] = new_slots
        if self.accessor:
            self._meta[idx, 2] = 0.0

    def set_rows(self, ids, rows):
        """Overwrite row VALUES directly (no optimizer rule) — the geo
        trainer's base refresh and bulk loading path (reference
        memory_sparse_geo_table.h direct value install)."""
        ids = np.asarray(ids).reshape(-1)
        rows = np.asarray(rows, np.float32).reshape(len(ids), self.dim)
        self._ensure(ids)
        idx = np.fromiter((self._rows[int(i)] for i in ids), np.int64,
                          len(ids))
        # fancy-index assignment copies VALUES into the table's own
        # storage; `rows` itself is never retained
        self._data[idx] = rows  # ptlint: disable=PTL501

    # -- CTR accessor (reference ctr_accessor.cc) --
    def update_show_click(self, ids, shows, clicks):
        """Accumulate per-row show/click event counts."""
        if not self.accessor:
            raise RuntimeError("table created without accessor='ctr'")
        ids = np.asarray(ids).reshape(-1)
        shows = np.asarray(shows, np.float32).reshape(-1)
        clicks = np.asarray(clicks, np.float32).reshape(-1)
        if not len(ids) == len(shows) == len(clicks):
            raise ValueError("ids/shows/clicks length mismatch")
        self._ensure(ids)
        idx = np.fromiter((self._rows[int(i)] for i in ids), np.int64,
                          len(ids))
        np.add.at(self._meta[:, 0], idx, shows)
        np.add.at(self._meta[:, 1], idx, clicks)
        self._meta[idx, 2] = 0.0

    def shrink(self, decay=0.98, nonclk_coeff=0.1, delete_threshold=0.8,
               delete_after_unseen=7):
        """One maintenance round: decay show/click, age rows one round,
        evict rows scoring click + nonclk_coeff·(show − click) below
        delete_threshold once unseen longer than delete_after_unseen
        (reference Table::Shrink + ctr_accessor ShowClickScore).
        Returns the evicted row count."""
        if not self.accessor:
            raise RuntimeError("table created without accessor='ctr'")
        self._meta[:, 0] *= decay
        self._meta[:, 1] *= decay
        self._meta[:, 2] += 1.0
        show, click, unseen = (self._meta[:, 0], self._meta[:, 1],
                               self._meta[:, 2])
        score = click + nonclk_coeff * (show - click)
        drop = (score < delete_threshold) & (unseen > delete_after_unseen)
        if not drop.any():
            return 0
        keep = ~drop
        kept_ids = self._ordered_ids()[keep]  # row-index order
        self._data = self._data[keep]
        self._slots = self._slots[keep]
        self._meta = self._meta[keep]
        self._rows = {int(i): k for k, i in enumerate(kept_ids)}
        return int(drop.sum())

    # -- checkpoint integration (paddle_tpu.distributed.checkpoint) --
    def state_dict(self):
        sd = {"ids": self._ordered_ids(), "data": self._data,
              "slots": self._slots}
        if self.accessor:
            sd["meta"] = self._meta
        return sd

    def set_state_dict(self, sd):
        ids = np.asarray(sd["ids"]._value if isinstance(sd["ids"], Tensor)
                         else sd["ids"]).reshape(-1)
        self._rows = {int(i): k for k, i in enumerate(ids)}
        # np.array (not asarray): the table owns its storage — an
        # aliased state-dict buffer mutated by the caller after load
        # would silently corrupt rows (PTL501)
        self._data = np.array(
            sd["data"]._value if isinstance(sd["data"], Tensor)
            else sd["data"], np.float32)
        self._slots = np.array(
            sd["slots"]._value if isinstance(sd["slots"], Tensor)
            else sd["slots"], np.float32)
        if self.accessor:
            self._meta = (np.array(
                sd["meta"]._value if isinstance(sd.get("meta"), Tensor)
                else sd["meta"], np.float32) if "meta" in sd
                else np.zeros((len(ids), 3), np.float32))


class SSDSparseTable(MemorySparseTable):
    """Disk-backed sparse table: row values and optimizer slots live in
    memmap'd files under `path`, only the id→row index stays in RAM
    (reference: ps/table/ssd_sparse_table.h:39, which spills cold rows to
    RocksDB). The OS page cache plays the hot-row cache — recently
    touched pages stay resident, cold pages are evicted under memory
    pressure — so billion-row tables train on hosts whose RAM holds only
    the index. Same pull/push/state_dict contract as MemorySparseTable;
    call `flush()` (or rely on `save` in checkpointing) to persist, and
    reopening the same `path` restores the table.
    """

    _DATA, _SLOTS, _IDS, _META = "rows.f32", "slots.f32", "ids.npy", \
        "meta.json"

    def __init__(self, embedding_dim, path, rule=None, initializer=None,
                 seed=0, capacity=4096):
        import json
        import os

        super().__init__(embedding_dim, rule=rule, initializer=initializer,
                         seed=seed)
        self._path = path
        os.makedirs(path, exist_ok=True)
        # slots_width(dim): Adam's slot width depends on dim; plain
        # slot_dim attr kept as the fallback for custom rules
        self._slot_dim = (self.rule.slots_width(self.dim)
                          if hasattr(self.rule, "slots_width")
                          else self.rule.slot_dim)
        ids_f = os.path.join(path, self._IDS)
        if (not os.path.exists(ids_f)
                and os.path.exists(self._file(self._DATA))):
            raise ValueError(
                f"SSD table dir {path} has row data but no {self._IDS} "
                "(crash before flush?) — recover or clear the directory; "
                'refusing the destructive "w+" re-create')
        if os.path.exists(ids_f):
            # the flat files carry no shape info — validate against the
            # persisted meta or a dim typo reinterprets every row
            with open(self._file(self._META)) as f:
                meta = json.load(f)
            if (meta["dim"] != self.dim
                    or meta["slot_dim"] != self._slot_dim):
                raise ValueError(
                    f"SSD table at {path} was written with dim="
                    f"{meta['dim']}/slot_dim={meta['slot_dim']}, "
                    f"reopened with dim={self.dim}/slot_dim="
                    f"{self._slot_dim}")
            ids = np.load(ids_f)
            self._rows = {int(i): k for k, i in enumerate(ids)}
            self._cap = max(capacity, 1, len(ids))
            self._map(create=False)
        else:
            self._cap = max(capacity, 1)
            self._map(create=True)
        self._refresh_views(len(self._rows))

    # -- storage primitives ------------------------------------------------
    def _file(self, name):
        import os

        return os.path.join(self._path, name)

    def _map(self, create):
        mode = "w+" if create else "r+"
        self._data_mm = np.memmap(self._file(self._DATA), np.float32,
                                  mode=mode, shape=(self._cap, self.dim))
        if self._slot_dim:
            self._slots_mm = np.memmap(
                self._file(self._SLOTS), np.float32, mode=mode,
                shape=(self._cap, self._slot_dim))

    def _refresh_views(self, n):
        self._n = n
        self._data = self._data_mm[:n]
        self._slots = (self._slots_mm[:n] if self._slot_dim
                       else np.zeros((n, 0), np.float32))

    def _grow_to(self, need):
        cap = self._cap
        while cap < need:
            cap *= 2
        if cap == self._cap:
            return
        self._data_mm.flush()
        row_bytes = self.dim * 4
        with open(self._file(self._DATA), "r+b") as f:
            f.truncate(cap * row_bytes)
        if self._slot_dim:
            self._slots_mm.flush()
            with open(self._file(self._SLOTS), "r+b") as f:
                f.truncate(cap * self._slot_dim * 4)
        self._cap = cap
        self._map(create=False)

    # -- overridden storage hook ------------------------------------------
    def _append_rows(self, new_rows, new_slots):
        base = self._n
        need = base + len(new_rows)
        self._grow_to(need)
        self._data_mm[base:need] = new_rows
        if self._slot_dim:
            self._slots_mm[base:need] = new_slots
        self._refresh_views(need)

    # -- persistence -------------------------------------------------------
    def flush(self):
        import json

        np.save(self._file(self._IDS), self._ordered_ids())
        with open(self._file(self._META), "w") as f:
            json.dump({"dim": self.dim, "slot_dim": self._slot_dim}, f)
        self._data_mm.flush()
        if self._slot_dim:
            self._slots_mm.flush()

    def set_state_dict(self, sd):
        def _np_of(v):
            return np.asarray(v._value if isinstance(v, Tensor) else v)

        ids = _np_of(sd["ids"]).reshape(-1)
        data = _np_of(sd["data"]).astype(np.float32)
        self._grow_to(max(len(ids), 1))
        self._rows = {int(i): k for k, i in enumerate(ids)}
        self._data_mm[:len(ids)] = data
        if self._slot_dim:
            self._slots_mm[:len(ids)] = _np_of(
                sd["slots"]).astype(np.float32)
        self._refresh_views(len(ids))
        self.flush()


# ------------------------------------------------- multi-host sharding

class ShardedSparseTable:
    """Multi-process id-routed sparse table.

    The reference shards ids across PS server processes (`id % server_num`)
    with async trainer-side push queues (reference:
    ps/table/memory_sparse_table.h:39 shard layout,
    ps/service/brpc_ps_client.h:195 id-routed pull/push RPC,
    ps/service/communicator/communicator.h:427 AsyncCommunicator bounded
    push queues). TPU-native redesign: there are no separate server
    processes — every trainer process owns the shard `id % world == rank`
    of the table in host RAM next to its chip.

    Transport (reference brpc_ps_client.h:195's point-to-point RPC):
    requests and rows move PEER-TO-PEER over the jax.distributed
    coordination KV (`xproc.send_np/recv_np`) — each rank sends every
    owner exactly its own request ids and receives exactly its own rows,
    so wire traffic is O(batch·dim) per rank, independent of world size.
    (transport="gather" keeps the old object-all-gather path — O(world·
    batch) received per rank — for A/B and debugging.) Row assembly is
    vectorized: responses preserve request order, so per-owner rows
    scatter straight into the unique-row matrix, no python dict loop.

    Contract: pull/flush are collective — every process must call them
    the same number of times. SPMD data-parallel training guarantees this
    (DistributedBatchSampler pads every rank to the same batch count).

    Push is ASYNC with bounded staleness (AsyncCommunicator semantics):
    `push` only queues gradients locally; the queue is flushed — one
    routing round applying grads on their owner shards — every
    `staleness`-th push call (and on `flush()`). With staleness=1 pushes
    are synchronous and a sharded run is bit-identical to a 1-process
    table (asserted by tests/test_ps_deepfm.py).
    """

    _TAG_PULL_REQ, _TAG_PULL_ROWS = 151, 152
    _TAG_PUSH_IDS, _TAG_PUSH_GRADS = 153, 154
    _TAG_SC_IDS, _TAG_SC_CNT = 155, 156

    def __init__(self, embedding_dim, rule=None, initializer=None, seed=0,
                 staleness=1, backend="auto", world=None, rank=None,
                 path=None, transport="p2p", timeout_ms=600_000,
                 accessor=None):
        from . import xproc

        if world is None:
            world = jax.process_count() if xproc.is_multiprocess() else 1
        if rank is None:
            rank = jax.process_index() if world > 1 else 0
        self.world, self.rank = world, rank
        self.dim = embedding_dim
        self.staleness = max(1, int(staleness))
        if transport not in ("p2p", "gather"):
            raise ValueError(f"transport={transport!r}: p2p or gather")
        self.transport = transport
        # p2p recv deadline: must cover peer rank skew (first-step XLA
        # compiles, data stalls) — 10 min default, not xproc's 60 s
        self.timeout_ms = int(timeout_ms)
        if path is not None:
            # each shard owns its OWN directory — ranks sharing one
            # memmap file would overwrite each other's row layouts
            import os

            path = os.path.join(path, f"rank{rank}")
        self.local = make_sparse_table(embedding_dim, rule=rule,
                                       initializer=initializer, seed=seed,
                                       backend=backend, path=path,
                                       accessor=accessor)
        self._pending_ids = []
        self._pending_grads = []
        self._push_calls = 0
        import threading

        self._local_lock = threading.Lock()
        self._io_pool = None   # lazy persistent executor (pull hot path)

    def _io_executor(self):
        """Long-lived thread pool for per-peer serve/recv concurrency —
        spawning 2·world threads on every pull would rival the latency
        the concurrency hides."""
        if self._io_pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._io_pool = ThreadPoolExecutor(
                max_workers=max(2, 2 * (self.world - 1)),
                thread_name_prefix="ps-io")
        return self._io_pool

    def __len__(self):
        return len(self.local)

    def _gather_obj(self, obj):
        from . import xproc

        return xproc.all_gather_obj(obj, max_len=1 << 27)

    def _peers(self):
        return [r for r in range(self.world) if r != self.rank]

    def _exchange_by_owner(self, owner, arrays, tags):
        """Scatter row-aligned `arrays` (leading dim = rows, e.g. ids +
        their grads) to the rank owning each row, and return this rank's
        concatenated incoming set (own slice + one recv per peer). One
        tag per array; all sends are posted before any blocking recv.
        The shared spine of the p2p flush / update_show_click routing."""
        from . import xproc

        for r in self._peers():
            sel = owner == r
            for arr, tag in zip(arrays, tags):
                xproc.send_np(arr[sel], r, tag)
        parts = [[arr[owner == self.rank]] for arr in arrays]
        peers = self._peers()
        if peers:
            # per-peer recvs run CONCURRENTLY (arrival order across peers
            # is arbitrary; a sequential loop made latency linear in
            # world size — round-4 weak spot)
            def _recv_peer(r):
                return [xproc.recv_np(r, tag, timeout_ms=self.timeout_ms)
                        for tag in tags]

            for got in self._io_executor().map(_recv_peer, peers):
                for k, arr in enumerate(got):
                    parts[k].append(arr)
        return [np.concatenate(p) for p in parts]

    def pull(self, ids):
        """Route each id to its owner shard, receive the rows back."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        if self.world == 1:
            return self.local.pull(ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        if self.transport == "gather":
            return self._pull_gather(ids, uniq, inv)
        from . import xproc

        owner = uniq % self.world
        rows = np.empty((len(uniq), self.dim), np.float32)
        # 1) every rank posts its request to each owner (non-blocking)
        for r in self._peers():
            xproc.send_np(uniq[owner == r], r, self._TAG_PULL_REQ)
        mine = owner == self.rank
        with self._local_lock:
            rows[mine] = self.local.pull(uniq[mine]) if mine.any() else 0
        # 2+3) serve each peer's request from the local shard AND collect
        # responses, all peers CONCURRENTLY — a slow peer no longer
        # stalls serving (or receiving from) the others; local table
        # access stays serialized under a lock (create-on-touch mutates)
        peers = self._peers()
        if peers:
            local_lock = self._local_lock

            def _serve(r):
                want = xproc.recv_np(r, self._TAG_PULL_REQ,
                                     timeout_ms=self.timeout_ms)
                with local_lock:
                    served = (self.local.pull(want) if len(want)
                              else np.zeros((0, self.dim), np.float32))
                # parameter rows must arrive bit-exact — the int8 wire
                # opt-in (PT_QUANT_ALLREDUCE) is for gradient-like
                # payloads, never the master copies being served
                xproc.send_np(served, r, self._TAG_PULL_ROWS,
                              quantize=False)

            def _recv(r):
                return xproc.recv_np(r, self._TAG_PULL_ROWS,
                                     timeout_ms=self.timeout_ms)

            ex = self._io_executor()
            serve_futs = [ex.submit(_serve, r) for r in peers]
            recv_futs = [ex.submit(_recv, r) for r in peers]
            try:
                resp = [f.result() for f in recv_futs]
                for f in serve_futs:
                    f.result()
            except Exception:
                # a dead peer must not leak queued work into the
                # fixed-size pool: cancel whatever hasn't started
                # (threads already blocked in recv will expire on their
                # own timeout)
                for f in serve_futs + recv_futs:
                    f.cancel()
                raise
            # responses preserve request order: scatter by owner mask
            for r, got in zip(peers, resp):
                rows[owner == r] = got
        return rows[inv] if len(ids) else \
            np.zeros((0, self.dim), np.float32)

    def _pull_gather(self, ids, uniq, inv):
        """Legacy all-gather transport (every rank sees every request)."""
        requests = self._gather_obj(uniq)          # round 1: who needs what
        served = {}
        for requester, want in enumerate(requests):
            mine = want[want % self.world == self.rank]
            if len(mine):
                served[requester] = (mine, self.local.pull(mine))
        responses = self._gather_obj(served)       # round 2: serve rows
        rows = np.empty((len(uniq), self.dim), np.float32)
        for owner_rank, resp in enumerate(responses):
            if self.rank in resp:
                sids, srows = resp[self.rank]
                # sids ⊂ uniq and both sorted: vectorized placement
                rows[np.searchsorted(uniq, sids)] = srows
        return rows[inv] if len(ids) else \
            np.zeros((0, self.dim), np.float32)

    def push(self, ids, grads):
        """Queue gradients; flush every `staleness`-th call."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        # np.array: grads are QUEUED until flush() — the training loop
        # reuses its gradient buffers every step, so an aliased view
        # here would flush later steps' values (PTL501)
        grads = np.array(grads, np.float32).reshape(len(ids), self.dim)
        self._pending_ids.append(ids)
        self._pending_grads.append(grads)
        # single-writer: push() runs only on the training-loop thread;
        # _local_lock guards the LOCAL table against the pull-serving
        # io-pool, which never touches the push-side staleness counter
        self._push_calls += 1  # ptlint: disable=PTL702
        if self._push_calls % self.staleness == 0:
            self.flush()

    def update_show_click(self, ids, shows, clicks):
        """Route show/click event counts to owner shards (collective,
        like flush; reference ctr_accessor statistics live server-side)."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        shows = np.asarray(shows, np.float32).reshape(-1)
        clicks = np.asarray(clicks, np.float32).reshape(-1)
        if not len(ids) == len(shows) == len(clicks):
            # validate BEFORE any send: a partial exchange would leave a
            # tag stream with an orphaned message and silently mis-pair
            # every later batch
            raise ValueError("ids/shows/clicks length mismatch")
        counts = np.stack([shows, clicks], axis=1)  # (n, 2) row-aligned
        if self.world == 1:
            self.local.update_show_click(ids, counts[:, 0], counts[:, 1])
            return
        cat_ids, cat_cnt = self._exchange_by_owner(
            ids % self.world, (ids, counts),
            (self._TAG_SC_IDS, self._TAG_SC_CNT))
        if len(cat_ids):
            self.local.update_show_click(cat_ids, cat_cnt[:, 0],
                                         cat_cnt[:, 1])

    def shrink(self, **kw):
        """Per-shard eviction round (collective: call on every rank).
        Returns this rank's evicted count."""
        return self.local.shrink(**kw)

    def flush(self):
        """Collective: route queued grads to owner shards and apply the
        optimizer rule there (server-side optimize, as in the reference)."""
        if self.world == 1:
            for i, g in zip(self._pending_ids, self._pending_grads):
                self.local.push(i, g)
            self._pending_ids, self._pending_grads = [], []
            return
        if self._pending_ids:
            ids = np.concatenate(self._pending_ids)
            grads = np.concatenate(self._pending_grads)
        else:
            ids = np.zeros((0,), np.int64)
            grads = np.zeros((0, self.dim), np.float32)
        self._pending_ids, self._pending_grads = [], []
        if self.transport == "gather":
            incoming = self._gather_obj((ids, grads))  # one routing round
            cat_ids = np.concatenate([i for i, _ in incoming])
            cat_grads = np.concatenate([g for _, g in incoming])
            mask = cat_ids % self.world == self.rank
            if mask.any():
                # local push dedup-accumulates repeated ids, so grads for
                # the same id from several trainers sum correctly
                self.local.push(cat_ids[mask], cat_grads[mask])
            return
        cat_ids, cat_grads = self._exchange_by_owner(
            ids % self.world, (ids, grads),
            (self._TAG_PUSH_IDS, self._TAG_PUSH_GRADS))
        if len(cat_ids):
            # ONE rule application per flush: dedup happens inside push
            self.local.push(cat_ids, cat_grads)

    # checkpoint: each rank persists its own shard (pairs with the
    # per-rank sharded checkpoint layout in distributed/checkpoint.py)
    def state_dict(self):
        return self.local.state_dict()

    def set_state_dict(self, sd):
        self.local.set_state_dict(sd)


# --------------------------------------------------------- layer shims

class SparseEmbedding:
    """PS-backed embedding lookup (reference static.nn.sparse_embedding /
    _pull_sparse ops). Pull unique rows → dense device lookup
    (differentiable) → push row grads on backward via hook.

    Overlap: `prefetch(next_ids)` starts the host-KV pull for the NEXT
    batch on a background thread while the chip computes the current
    step (the reference's AsyncCommunicator pull pipeline,
    communicator.h:427); the matching `__call__` consumes the prefetched
    rows without blocking on the table."""

    def __init__(self, embedding_dim, table=None, rule=None, name=None,
                 backend="auto", path=None):
        import threading

        self.table = table if table is not None else make_sparse_table(
            embedding_dim, rule=rule, backend=backend, path=path)
        _register_table(self.table, name)
        self.dim = embedding_dim
        self._pool = None
        self._pending = None  # (key, uniq, inv, shape, future)
        self._bound = None    # SparseTrainStep trace mode (rows, inv)
        # serializes background pulls against backward-hook pushes: the
        # table's row map/arrays are not safe under concurrent mutation
        self._table_lock = threading.Lock()

    def _decompose(self, ids):
        ids_np = np.asarray(
            ids._value if isinstance(ids, Tensor) else ids).astype(np.int64)
        uniq, inv = np.unique(ids_np.reshape(-1), return_inverse=True)
        return ids_np, uniq, inv

    @staticmethod
    def _key(ids_np):
        return (ids_np.shape, ids_np.tobytes())

    def prefetch(self, ids):
        """Start pulling `ids`'s rows in the background. The pull holds
        the table lock, so it serializes against the backward-hook push
        (bounded staleness: a prefetch reads the table state when the
        lock is acquired, as in the reference async PS). Collective
        tables (multi-host ShardedSparseTable) pull in the FOREGROUND —
        collectives issued from a side thread would interleave with the
        main thread's flush collectives and deadlock ranks."""
        import concurrent.futures

        ids_np, uniq, inv = self._decompose(ids)

        def locked_pull():
            with self._table_lock:
                return self.table.pull(uniq)

        if getattr(self.table, "world", 1) > 1:
            fut = concurrent.futures.Future()
            fut.set_result(locked_pull())
        else:
            if self._pool is None:
                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="ps-prefetch")
            fut = self._pool.submit(locked_pull)
        self._pending = (self._key(ids_np), uniq, inv, ids_np.shape, fut)
        return fut

    def _acquire(self, ids):
        """Pull-or-consume-prefetch: returns (ids_np, uniq, inv, rows_np).
        Shared by the eager __call__ and SparseTrainStep's host stage."""
        if self._pending is not None:
            key, p_uniq, p_inv, p_shape, fut = self._pending
            probe = np.asarray(
                ids._value if isinstance(ids, Tensor) else ids).astype(
                np.int64)
            if self._key(probe) == key:
                self._pending = None
                return probe, p_uniq, p_inv, fut.result()
        ids_np, uniq, inv = self._decompose(ids)
        with self._table_lock:
            rows_np = self.table.pull(uniq)
        return ids_np, uniq, inv, rows_np

    def __call__(self, ids):
        from ..ops._helpers import apply_jfn

        if self._bound is not None:
            # SparseTrainStep trace mode: rows/inv are jit ARGUMENTS —
            # no host pull, no hook (the step returns row grads to push)
            rows_b, inv_b = self._bound
            return apply_jfn(
                "sparse_embedding_lookup",
                lambda w, i: jnp.take(w, i, axis=0), rows_b, inv_b)
        ids_np, uniq, inv, rows_np = self._acquire(ids)
        rows = Tensor(jnp.asarray(rows_np), stop_gradient=False)
        table = self.table
        lock = self._table_lock

        def _push(g):
            with lock:
                table.push(uniq, np.asarray(
                    g._value if isinstance(g, Tensor) else g))
            return g

        rows.register_hook(_push)
        inv_t = Tensor(jnp.asarray(inv.reshape(ids_np.shape)),
                       stop_gradient=True)
        return apply_jfn(
            "sparse_embedding_lookup",
            lambda w, i: jnp.take(w, i, axis=0), rows, inv_t)

    def parameters(self):
        return []  # rows live in the table, optimized server-side


from ..jit import TrainStep as _TrainStepBase


def find_sparse_embeddings(obj, _seen=None):
    """Walk an object graph for SparseEmbedding instances (they are not
    Layers, so Layer traversal misses them)."""
    if _seen is None:
        _seen = set()
    if id(obj) in _seen:
        return []
    _seen.add(id(obj))
    if isinstance(obj, SparseEmbedding):
        return [obj]
    out = []
    d = getattr(obj, "__dict__", None)
    if isinstance(d, dict):
        for v in d.values():
            out += find_sparse_embeddings(v, _seen)
    if isinstance(obj, dict):  # Layer._sub_layers etc.
        for v in obj.values():
            out += find_sparse_embeddings(v, _seen)
    if isinstance(obj, (list, tuple)):
        for v in obj:
            out += find_sparse_embeddings(v, _seen)
    return out


class SparseTrainStep(_TrainStepBase):
    """Compiled PS training step (the throughput fix for eager PS
    models): per step, the HOST pulls each table's unique rows, then ONE
    donated XLA program runs forward + backward + the dense-param
    optimizer update AND returns the row gradients, which the host
    pushes back to the tables (server-side optimizer rules apply them).
    The eager per-op dispatch loop — reference async-PS's trainer shape,
    and this module's default — becomes three stages that pipeline with
    `prefetch` (issue it AFTER the step so the pending slot survives
    until the next step's pull).

    Subclasses jit.TrainStep: param/optimizer bookkeeping, donation, and
    the armed-profiler ips hook are shared; _build/__call__ differ
    because rows/inv are extra traced inputs and row grads an extra
    output. Unique-row counts vary per batch, so rows/inv are PADDED to
    a fixed capacity (ids.size worst case): one compile, stable shapes;
    padded rows are never referenced by inv and get exactly zero
    gradient.

    Constraints: every SparseEmbedding must key off the SAME ids tensor
    (`batch[ids_index]`, the single-table CTR layout); loss_fn must be
    jit-traceable (pure jnp/tape ops). Single-PROCESS: the dense update
    runs inside the compiled step with local grads, so multi-host
    data-parallel PS training keeps the eager loop (whose hook pushes
    and explicit dense all-reduce are collective-safe —
    tests/ps_worker.py phase B is the pattern).
    """

    def __init__(self, model, loss_fn, optimizer, ids_index=0,
                 donate_params=True):
        self.embs = find_sparse_embeddings(model)
        if not self.embs:
            raise ValueError("model has no SparseEmbedding tables; use "
                             "jit.TrainStep for dense models")
        super().__init__(model, loss_fn, optimizer,
                         donate_params=donate_params)
        self.ids_index = ids_index

    def lower(self, *batch):
        raise NotImplementedError(
            "SparseTrainStep's compiled signature carries per-step "
            "rows/inv operands; lower a dense TrainStep for memory "
            "analysis instead")

    def compile_stats(self, check_donation=False):
        if check_donation:
            # same reason lower() is unsupported: the donation probe
            # would re-lower with TrainStep's 7-arg layout against this
            # step's 9-arg signature
            raise NotImplementedError(
                "SparseTrainStep's compiled signature carries per-step "
                "rows/inv operands; run the donation probe on a dense "
                "TrainStep of the same model instead")
        return super().compile_stats()

    def _build(self):
        import jax

        from ..core import rng as rng_mod

        model, loss_fn, opt = self.model, self.loss_fn, self.optimizer
        param_objs, trainable, embs = (self._param_objs, self._trainable,
                                       self.embs)
        train_objs = [p for p, t in zip(param_objs, trainable) if t]
        # per-step dropout keys, as TrainStep — and like there, a runtime
        # ARGUMENT, not a closure constant: baked keys make per-instance
        # HLOs, which the jax 0.4.x persistent compile cache can serve
        # across instances with a mismatched donation aliasing map
        self._base_key = rng_mod.next_key()

        def pure_loss(train_vals, rows_vals, frozen_vals, inv_vals,
                      batch_vals, step_key):
            originals = [p._value for p in param_objs]
            it_t, it_f = iter(train_vals), iter(frozen_vals)
            for p, tr in zip(param_objs, trainable):
                p._value = next(it_t) if tr else next(it_f)
            try:
                for emb, rv, iv in zip(embs, rows_vals, inv_vals):
                    emb._bound = (Tensor(rv, stop_gradient=False),
                                  Tensor(iv, stop_gradient=True))
                batch = [Tensor(v, stop_gradient=True)
                         for v in batch_vals]
                with rng_mod.trace_key_scope(step_key):
                    loss = loss_fn(model, *batch)
                new_frozen = [p._value for p, tr in zip(param_objs,
                                                        trainable)
                              if not tr]
            finally:
                for emb in embs:
                    emb._bound = None
                for p, v in zip(param_objs, originals):
                    p._value = v
            return loss._value, new_frozen

        def step(train_vals, frozen_vals, opt_states, lr, rows_vals,
                 inv_vals, batch_vals, step_idx, base_key):
            step_key = jax.random.fold_in(base_key, step_idx)
            (loss, new_frozen), (dgrads, rgrads) = jax.value_and_grad(
                pure_loss, argnums=(0, 1), has_aux=True)(
                train_vals, rows_vals, frozen_vals, inv_vals, batch_vals,
                step_key)
            new_vals, new_states = opt.apply_gradients_tree(
                train_vals, dgrads, opt_states, lr, param_objs=train_objs)
            return loss, new_vals, new_states, new_frozen, rgrads

        self._compiled = jax.jit(step, donate_argnums=(0, 1, 2))

    def __call__(self, *batch):
        if self._compiled is None:
            self._build()
        ids = batch[self.ids_index]
        cap = int(np.prod(np.asarray(
            ids._value if isinstance(ids, Tensor) else ids).shape))
        rows_vals, inv_vals, uniqs, counts = [], [], [], []
        for emb in self.embs:
            ids_np, uniq, inv, rows_np = emb._acquire(ids)
            u = len(uniq)
            pad = np.zeros((cap - u, rows_np.shape[1]), rows_np.dtype)
            rows_vals.append(jnp.asarray(np.concatenate([rows_np, pad])))
            inv_vals.append(jnp.asarray(inv.reshape(ids_np.shape)))
            uniqs.append(uniq)
            counts.append(u)
        train_vals, frozen_vals = self._split_vals()
        if self._opt_states is None:
            self._opt_states = self.optimizer.init_states_tree(train_vals)
        batch_vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b)
                      for b in batch]
        loss, new_vals, self._opt_states, new_frozen, rgrads = \
            self._compiled(train_vals, frozen_vals, self._opt_states,
                           np.float32(self.optimizer.get_lr()),
                           rows_vals, inv_vals,
                           batch_vals,
                           jnp.asarray(self.optimizer._step_count,
                                       jnp.uint32), self._base_key)
        it, it_f = iter(new_vals), iter(new_frozen)
        for p, t in zip(self._param_objs, self._trainable):
            p._value = next(it) if t else next(it_f)
        self.optimizer._step_count += 1
        for emb, uniq, u, g in zip(self.embs, uniqs, counts, rgrads):
            with emb._table_lock:
                emb.table.push(uniq, np.asarray(g)[:u])
        from ..profiler import benchmark

        bm = benchmark()
        if bm.enabled:  # armed ips meter, as jit.TrainStep
            n = batch_vals[0].shape[0] if batch_vals and \
                getattr(batch_vals[0], "ndim", 0) else None
            bm.auto_step(num_samples=n)
        return Tensor(loss, stop_gradient=True)


class GeoSparseTable:
    """Geo-async trainer-side sparse table (reference: GeoCommunicator,
    ps/service/communicator/communicator.h:598 — delta-accumulating
    trainer sync; ps/table/memory_sparse_geo_table.h:1 — the server
    merges pushed deltas into the authoritative rows).

    Semantics: every trainer owns a LOCAL working copy trained with the
    optimizer rule IMMEDIATELY (zero per-step routing for known ids).
    Every `sync_every`-th push runs one geo round:

      1. delta = local_row − base_row for every locally-dirty id,
      2. deltas route to their owner shard (id % world) and MERGE by
         summation into the authoritative table,
      3. the trainer refreshes: merged rows are pulled back, installed
         as the new local values AND the new base.

    Staleness is bounded by `sync_every` pushes; with sync_every=1 and
    one trainer this degenerates to a plain local table. pull()s of ids
    this trainer has never seen fetch the authoritative base first (one
    collective round per step, empty-request safe — the reference's
    sparse init pull). All pull/push calls are COLLECTIVE, like
    ShardedSparseTable: data-parallel lockstep guarantees matching call
    counts.
    """

    def __init__(self, embedding_dim, rule=None, initializer=None,
                 seed=0, sync_every=8, world=None, rank=None,
                 timeout_ms=600_000, refresh_chunk=4096):
        from . import xproc

        if world is None:
            world = jax.process_count() if xproc.is_multiprocess() else 1
        if rank is None:
            rank = jax.process_index() if world > 1 else 0
        self.world, self.rank = world, rank
        self.dim = embedding_dim
        self.sync_every = max(1, int(sync_every))
        # the geo delta algebra needs local create-on-touch to agree
        # with the authority's initial value WITHOUT a network round:
        # the initializer must be a pure function of the id (the
        # reference geo tables initialize deterministically too)
        if initializer is None:
            raise ValueError(
                "GeoSparseTable needs an id-deterministic initializer "
                "(rows are created locally AND on the authority shard; "
                "order-dependent random init would corrupt deltas)")
        self._init_fn = initializer
        self.refresh_chunk = max(1, int(refresh_chunk))
        self.local = MemorySparseTable(embedding_dim, rule=rule,
                                       initializer=initializer, seed=seed)
        # authoritative store: delta MERGE is row += delta, expressed as
        # the SGD rule at lr=1 applied to −delta (no second rule state)
        self._authority = ShardedSparseTable(
            embedding_dim, rule=SparseSGDRule(1.0),
            initializer=initializer, seed=seed, staleness=1,
            world=world, rank=rank, timeout_ms=timeout_ms)
        self._base = {}       # id -> row value at last sync
        self._refresh_cursor = 0
        self._dirty = set()
        self._push_count = 0

    def __len__(self):
        return len(self.local)

    def pull(self, ids):
        """Local rows; unseen ids fetch their authoritative base first
        (collective — every rank participates, possibly with an empty
        request)."""
        ids = np.asarray(ids).reshape(-1).astype(np.int64)
        uniq = np.unique(ids)
        new = np.array([i for i in uniq if int(i) not in self._base],
                       np.int64)
        if self.world > 1 or len(new):
            rows = self._authority.pull(new)
            if len(new):
                self.local.set_rows(new, rows)
                for i, r in zip(new, rows):
                    self._base[int(i)] = r.copy()
        return self.local.pull(ids)

    def push(self, ids, grads):
        """Apply immediately to the local copy; every sync_every-th call
        runs the collective geo round."""
        ids_flat = np.asarray(ids).reshape(-1).astype(np.int64)
        # push-only ids (never pulled): their base is the deterministic
        # initializer value — record it BEFORE the rule mutates the row,
        # no network round needed (see __init__'s initializer contract)
        new = np.array([i for i in np.unique(ids_flat)
                        if int(i) not in self._base], np.int64)
        if len(new):
            for i, r in zip(new, self._init_fn(len(new), new)):
                self._base[int(i)] = np.asarray(r, np.float32).copy()
        self.local.push(ids, grads)
        self._dirty.update(int(i) for i in ids_flat)
        self._push_count += 1
        if self._push_count % self.sync_every == 0:
            self.sync()

    def sync(self):
        """One geo round (collective): push local deltas for DIRTY ids,
        merge on owners, then refresh base/local for the dirty ids PLUS
        a rotating window of known ids — the recv half picks up other
        trainers' merged updates (reference GeoCommunicator send+recv
        per round) without pulling the whole touched vocabulary every
        round (refresh cost is bounded by dirty + refresh_chunk)."""
        dirty = np.array(sorted(self._dirty), np.int64)
        self._dirty.clear()
        if len(dirty):
            local_rows = self.local.pull(dirty)
            base_rows = np.stack([self._base[int(i)] for i in dirty])
            delta = local_rows - base_rows
        else:
            delta = np.zeros((0, self.dim), np.float32)
        # merge: authority_row += delta (SGD lr=1 on −delta), summed
        # over all trainers pushing the same id this round. The
        # authority runs at staleness=1, so push() flushes — no second
        # exchange round needed.
        self._authority.push(dirty, -delta)
        known_all = np.array(sorted(self._base), np.int64)
        lo = self._refresh_cursor
        window = known_all[lo:lo + self.refresh_chunk]
        self._refresh_cursor = (0 if lo + self.refresh_chunk
                                >= len(known_all)
                                else lo + self.refresh_chunk)
        refresh = np.unique(np.concatenate([dirty, window])) \
            if len(dirty) or len(window) else dirty
        merged = self._authority.pull(refresh)
        if len(refresh):
            self.local.set_rows(refresh, merged)
            for i, r in zip(refresh, merged):
                self._base[int(i)] = r.copy()

    def flush(self):
        self.sync()

    def state_dict(self):
        return self._authority.state_dict()

    def set_state_dict(self, sd):
        self._authority.set_state_dict(sd)
        # restored authority invalidates everything trainer-side: a
        # stale local/base pair would hide the load AND corrupt the
        # next merge with deltas against pre-restore values
        self.local = MemorySparseTable(self.dim, rule=self.local.rule,
                                       initializer=self._init_fn)
        self._base.clear()
        self._dirty.clear()
        self._refresh_cursor = 0


def ShardedEmbedding(num_embeddings, embedding_dim, axis="mp", **kwargs):
    """Factory: a dense nn.Embedding whose table is row-sharded over a
    mesh axis — the SPMD path when the vocabulary fits device memory
    (SparseCore-style; XLA lowers the gather to collectives over ICI).
    Usable inside DistributedTrainStep. Returns an Embedding instance
    (kept a function, not a subclass: the sharding is placement state on
    the weight, not behavior)."""
    from ..nn.layer.common import Embedding
    from jax.sharding import PartitionSpec as P

    layer = Embedding(num_embeddings, embedding_dim, **kwargs)
    layer.weight._pspec = P(axis, None)
    if mesh_mod.has_mesh():
        try:
            layer.weight._value = jax.device_put(
                layer.weight._value,
                mesh_mod.named_sharding(axis, None))
        except Exception as e:
            import warnings

            warnings.warn(
                f"ShardedEmbedding: placing the table on axis "
                f"{axis!r} failed ({e}); the weight stays REPLICATED "
                "until a parallel step re-shards it", RuntimeWarning)
    return layer
