"""distributed.cloud_utils (reference:
python/paddle/distributed/cloud_utils.py:23 get_cloud_cluster) — derive
the job's cluster layout from launcher environment variables."""
import os

__all__ = ["get_cloud_cluster", "get_trainers_num"]


def get_trainers_num():
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=None, selected_devices=None):
    """Return (node_ips, current_ip, trainer_endpoints) from the
    PADDLE_* env contract the launcher sets (reference reads the same
    variables; the cloud-specific fallbacks don't apply off-cloud)."""
    endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "")
    eps = [e for e in endpoints.split(",") if e]
    if not eps:
        port = args_port or 6170
        ips = (args_node_ips.split(",") if args_node_ips
               else ["127.0.0.1"])
        eps = [f"{ip}:{port}" for ip in ips]
    # order-preserving dedup (prefix matching would collide 10.0.0.1
    # with 10.0.0.10)
    node_ips = list(dict.fromkeys(e.rsplit(":", 1)[0] for e in eps))
    cur = args_node_ip or os.getenv("POD_IP", node_ips[0])
    return node_ips, cur, eps
