"""distributed.entry_attr (reference:
python/paddle/distributed/entry_attr.py) — sparse-table entry filter
configs; canonical classes live in api_extra."""
from .api_extra import (  # noqa: F401
    CountFilterEntry, ProbabilityEntry, ShowClickEntry)

__all__ = ["ProbabilityEntry", "CountFilterEntry", "ShowClickEntry"]
