"""distributed.metric — globally-reduced training metrics (AUC family).

Reference: python/paddle/distributed/metric/metrics.py (init_metric:26 /
print_metric:102 / print_auc:120 — a YAML-configured driver over the C++
fleet metric calculators, framework/fleet/metrics.cc, whose global AUC
all-reduces per-bucket positive/negative histograms over gloo).

TPU-native redesign: the calculator is `metric.Auc`'s bucket estimator
(identical math to the C++ one); globalization is one `all_reduce` of the
two histograms over the trainer processes (`xproc.all_reduce_np` — the
gloo-analog eager path), so the YAML "monitors" config reduces to
constructing DistributedAuc instances. Single-process jobs work too: the
all-reduce degrades to identity.
"""
import numpy as np

from ..metric import Auc
from . import xproc

__all__ = ["DistributedAuc", "init_metric", "print_metric", "print_auc"]


class DistributedAuc(Auc):
    """Bucketed AUC whose accumulate() folds in every trainer's buckets
    (reference metrics.cc GlobalAuc). Carries the monitor `phase`
    (JOINING/UPDATING) from the YAML config for phase-filtered printing."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 phase="all"):
        super().__init__(curve=curve, num_thresholds=num_thresholds,
                         name=name)
        self.phase = phase

    def accumulate(self):
        if xproc.is_multiprocess():
            # host-side exact merge: bucket counts are integers and a
            # device all-reduce would canonicalize float64→float32 with
            # x64 off, rounding counts past 2^24 on long CTR runs
            shards = xproc.all_gather_obj(
                (self._stat_pos.astype(np.int64),
                 self._stat_neg.astype(np.int64)))
            saved = self._stat_pos, self._stat_neg
            self._stat_pos = np.sum([p for p, _ in shards], axis=0,
                                    dtype=np.float64)
            self._stat_neg = np.sum([n for _, n in shards], axis=0,
                                    dtype=np.float64)
            try:
                return super().accumulate()
            finally:
                self._stat_pos, self._stat_neg = saved
        return super().accumulate()


_METRICS = {}


def init_metric(metric_ptr=None, metric_yaml_path=None, bucket_size=4095,
                **_compat):
    """Build the monitor registry from a YAML config of the reference
    shape (``monitors: [{name, method, label, target, phase}, ...]``,
    reference metrics.py:26). `metric_ptr` (the C++ handle) has no TPU
    analog and is ignored; calculators land in a module registry keyed
    by name for `print_metric`/`print_auc`."""
    import yaml

    with open(metric_yaml_path) as f:
        content = yaml.safe_load(f)
    monitors = content.get("monitors") or []
    for runner in monitors:  # validate everything BEFORE registering any
        method = runner.get("method", "AucCalculator")
        if method not in ("AucCalculator", "MultiTaskAucCalculator",
                          "CmatchRankAucCalculator", "MaskAucCalculator"):
            raise ValueError(f"unsupported metric method {method}")
    _METRICS.clear()  # a new config replaces the registry, never mixes
    for runner in monitors:
        name = runner["name"]
        _METRICS[name] = DistributedAuc(num_thresholds=bucket_size,
                                        name=name,
                                        phase=runner.get("phase", "all"))
    return _METRICS


def get_metric(name):
    return _METRICS[name]


def print_metric(metric_ptr_or_name, name=None):
    """Reference metrics.py:102 — format one metric's current value."""
    name = metric_ptr_or_name if name is None else name
    m = _METRICS[name]
    msg = f"{name}: AUC={m.accumulate():.6f}"
    print(msg)
    return msg


def print_auc(metric_ptr_or_is_day=None, is_day=False, phase="all"):
    """Reference metrics.py:120 — print the registered AUC monitors,
    filtered to `phase` ('JOINING'/'UPDATING'; 'all' prints everything)."""
    out = []
    for name in sorted(_METRICS):
        if phase != "all" and _METRICS[name].phase != phase:
            continue
        out.append(print_metric(name))
    return "\n".join(out)
