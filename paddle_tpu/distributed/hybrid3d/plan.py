"""Hybrid3DConfig — one validated description of a DP × TP × PP run.

The reference scatters the hybrid topology across a protobuf strategy,
a communicator bootstrap, and per-layer wiring (fleet topology.py +
HybridCommunicateGroup); here the whole 3-axis plan is ONE frozen value
that (a) builds the global mesh, (b) validates the model's divisibility
constraints up front, and (c) stamps itself into bench records and
telemetry so a measured step time always arrives with its mesh shape.

Axis naming: the public axis is **tp** (tensor parallel); it maps onto
the mesh's 'mp' axis (the reference's Megatron naming, kept so every
existing PartitionSpec and mp_ops collective keeps working). ZeRO
composes on the DP axis: optimizer-state (and optionally param) leaves
gain the 'dp' axis on a free divisible dim — in a pure-DP or hybrid
mesh the dp ranks are exactly the replica group that ZeRO-1 shards
over ("Scale MLPerf-0.6 models on Google TPU-v3 Pods" runs the same
composition at pod scale).
"""
from dataclasses import dataclass
from typing import Optional

__all__ = ["Hybrid3DConfig", "init_hybrid_mesh", "build_gpt3d"]

_SCHEDULES = ("1f1b", "gpipe")
_ZERO_LEVELS = (None, "os", "os_g", "p_g_os")


@dataclass(frozen=True)
class Hybrid3DConfig:
    """Frozen plan for a 3D-parallel training run.

    dp/tp/pp: mesh degrees (tp rides the 'mp' mesh axis).
    n_micro: microbatches per global batch (the pipeline's M).
    schedule: '1f1b' (lockstep, O(pp) activations) or 'gpipe'
        (serialized halves, O(M) activations — the simpler schedule).
    n_virtual: interleaved virtual stages per device (1F1B only).
    remat: 'stage' | 'layer' | False — the pipelined model's knob.
    zero: None | 'os' | 'os_g' | 'p_g_os' — ZeRO level applied by
        HybridTrainStep; states (and params at p_g_os) shard over
        `zero_axis` ('dp' by default — the replica axis IS the ZeRO
        group in a hybrid mesh; 'sharding' keeps the dedicated axis).
    sp: optional sequence-parallel degree (the 4th axis, for long
        context inside pipeline stages).
    quant_allreduce: quantize the dp-axis gradient all-reduce to
        block-scaled int8 INSIDE the compiled step (EQuARX in-XLA —
        distributed.quant_collective; docs/QUANTIZATION.md "In-XLA
        collectives"). ~3.9× fewer dp bytes per step; loss/aux scalars
        and the mp/pp collectives stay exact. TRI-STATE: None (the
        default) defers to the PT_QUANT_ALLREDUCE_XLA env opt-in;
        True/False pin it explicitly (a default of False would make
        the documented knob→config→env chain unreachable whenever a
        config is passed).
    """
    dp: int = 1
    tp: int = 1
    pp: int = 1
    n_micro: int = 4
    schedule: str = "1f1b"
    n_virtual: int = 1
    remat: object = "stage"
    zero: Optional[str] = None
    zero_axis: str = "dp"
    sp: int = 1
    quant_allreduce: Optional[bool] = None

    def __post_init__(self):
        for name in ("dp", "tp", "pp", "n_micro", "n_virtual", "sp"):
            v = getattr(self, name)
            if not isinstance(v, int) or v < 1:
                raise ValueError(f"{name}={v!r}: expected an int >= 1")
        if self.schedule not in _SCHEDULES:
            raise ValueError(
                f"schedule={self.schedule!r}: expected one of {_SCHEDULES}")
        if self.schedule == "gpipe" and self.n_virtual > 1:
            raise ValueError(
                "interleaved virtual stages are a 1F1B refinement; "
                "gpipe runs n_virtual=1")
        if self.zero not in _ZERO_LEVELS:
            raise ValueError(
                f"zero={self.zero!r}: expected one of {_ZERO_LEVELS}")
        if self.zero_axis not in ("dp", "sharding"):
            raise ValueError(
                f"zero_axis={self.zero_axis!r}: expected 'dp' or "
                "'sharding'")

    @property
    def n_devices(self):
        return self.dp * self.tp * self.pp * self.sp

    def mesh_kwargs(self):
        """Keyword args for `mesh.init_mesh` (tp → the 'mp' axis)."""
        return {"dp": self.dp, "pp": self.pp, "mp": self.tp,
                "sp": self.sp}

    def validate_model(self, gpt_config, moe=False):
        """Fail fast on the divisibility constraints the pipeline would
        otherwise raise mid-loss (same messages, earlier). `moe=True`
        drops the ffn check — a MoE model's experts shard over 'ep',
        not 'mp', so the dense-FFN constraint doesn't apply."""
        if self.pp > 1 and gpt_config.num_layers % (
                self.pp * self.n_virtual):
            raise ValueError(
                f"num_layers={gpt_config.num_layers} not divisible by "
                f"pp*n_virtual={self.pp}*{self.n_virtual}")
        if self.tp > 1:
            dims = [(gpt_config.num_heads, "num_heads"),
                    (gpt_config.vocab_size, "vocab_size")]
            if not moe:
                dims.append((gpt_config.ffn_size, "ffn_size"))
            for dim, what in dims:
                if dim % self.tp:
                    raise ValueError(
                        f"{what}={dim} not divisible by tp={self.tp}")
        return self

    def describe(self):
        """Flat dict for bench stamps / telemetry labels."""
        return {
            "mesh_shape": {"dp": self.dp, "tp": self.tp, "pp": self.pp,
                           **({"sp": self.sp} if self.sp > 1 else {})},
            "n_micro": self.n_micro,
            "schedule": self.schedule,
            "n_virtual": self.n_virtual,
            "remat": self.remat if self.remat else "off",
            "zero": self.zero or "off",
            **({"quant_allreduce": True} if self.quant_allreduce
               else {}),
        }

    def tag(self):
        """Short config id, e.g. 'dp2.tp2.pp2-1f1b' — bench arm keys."""
        parts = [f"dp{self.dp}", f"tp{self.tp}", f"pp{self.pp}"]
        if self.sp > 1:
            parts.append(f"sp{self.sp}")
        s = ".".join(parts) + f"-{self.schedule}"
        if self.n_virtual > 1:
            s += f"v{self.n_virtual}"
        if self.zero:
            s += f"-zero_{self.zero}"
        if self.quant_allreduce:
            s += "-q8"
        return s


def init_hybrid_mesh(config, devices=None):
    """Build the global (dp, pp, mp[=tp], sp) mesh for `config`.

    With `devices=None` the plan must use every visible device (the
    mesh invariant); pass an explicit slice for degenerate test runs.
    """
    from .. import mesh as mesh_mod

    return mesh_mod.init_mesh(devices=devices, **config.mesh_kwargs())


def build_gpt3d(gpt_config, config, **model_kw):
    """PipelinedGPTForCausalLM wired for `config` (schedule, virtual
    stages, remat validated against the mesh degrees up front)."""
    from ...text.models.gpt_pipeline import PipelinedGPTForCausalLM

    config.validate_model(gpt_config,
                          moe=bool(model_kw.get("moe_experts")))
    return PipelinedGPTForCausalLM(
        gpt_config, n_micro=config.n_micro, remat=config.remat,
        n_virtual=config.n_virtual, schedule=config.schedule, **model_kw)
