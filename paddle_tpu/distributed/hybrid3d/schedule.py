"""GPipe microbatch schedule as ONE SPMD program.

Sibling of the lockstep 1F1B scan
(`fleet.meta_parallel.pipeline_1f1b._run_schedule`): the same
shard_map-over-'pp' design — activations hop stages on a `lax.ppermute`
ring, the backward is hand-scheduled by re-linearizing each stage from
its saved input — but with GPipe's two serialized halves (reference:
fleet/meta_parallel/pipeline_parallel.py `forward_backward_pipeline`
run with all-forward-then-all-backward ordering; Huang et al., GPipe):

    forward  : stage s forwards micro m at tick  t = m + s
    backward : stage s backwards micro m at tick t = (M−1−m) + (pp−1−s)

Each half is a fill-drain pass of M + pp − 1 ticks, so the whole step
is 2(M + pp − 1) ticks vs 1F1B's M + 2(pp − 1) — the classic GPipe
bubble — and every stage keeps ALL M micro inputs alive across the
halves, so activation memory is O(M) per stage vs 1F1B's O(pp). The
trade is simplicity and schedule symmetry; `schedule_ticks`'s docstring
derives why 1F1B is the lockstep optimum. Both schedules share
`PipelineSpecs` (mp/dp/sp composition), remat, and the MoE aux channel,
so a model can flip between them without touching its specs.
"""
import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import mesh as mesh_mod
from ..fleet.meta_parallel.pipeline_1f1b import (
    PipelineSpecs, _tree_add_masked, _tree_zeros, _unflatten_like)

__all__ = ["pipeline_gpipe", "gpipe_ticks"]


def gpipe_ticks(M, pp):
    """Total scan length of the two serialized GPipe halves."""
    return 2 * (M + pp - 1)


def _run_gpipe(block_fn, loss_fn, stacked_params, post_params, x_micro,
               y_micro, pp, remat, dp_axis=None, sum_axes=None,
               aux_weight=None, quant_dp=False):
    """Inside shard_map over 'pp'. Returns (loss, aux, param_grads,
    post_grads, dx_micro) — the same contract as 1F1B's `_run_schedule`,
    with the same psum/pmean finishing, so the two schedules are
    interchangeable behind `pipeline_gpipe`/`pipeline_1f1b`."""
    from ..fleet.recompute import checkpoint_policy

    params = stacked_params
    stage = lax.axis_index("pp")
    M = x_micro.shape[0]
    Tf = M + pp - 1

    has_aux = aux_weight is not None
    aw = float(aux_weight) if has_aux else 0.0
    # identical aux-cotangent scaling story as _run_schedule: the block's
    # aux is the GLOBAL value, each rank's vjp yields a partial, and the
    # loss-grad reductions (psum over sum_axes, pmean over dp) reassemble
    # aw·d(aux_global) iff the seed carries the axis sizes
    aux_seed = aw
    if has_aux:
        if dp_axis is not None:
            aux_seed *= mesh_mod.axis_size(dp_axis)
        for ax in (sum_axes or ()):
            aux_seed *= mesh_mod.axis_size(ax)
    blk0 = (block_fn if has_aux
            else (lambda p, x: (block_fn(p, x), jnp.zeros([], jnp.float32))))
    blk = (jax.checkpoint(blk0, policy=checkpoint_policy(remat))
           if remat else blk0)
    micro_shape = x_micro.shape[1:]

    # ---------------- forward half: fill-drain, save EVERY input -------
    def fwd_tick(carry, t):
        saved, aux_sum, fwd_recv = carry
        m = t - stage
        valid = (m >= 0) & (m < M)
        m_c = jnp.clip(m, 0, M - 1)
        x_in = jnp.where(stage == 0, x_micro[m_c], fwd_recv)
        out, aux_f = blk(params, x_in)
        aux_sum = aux_sum + jnp.where(valid, aux_f, 0.0).astype(jnp.float32)
        # GPipe keeps all M inputs (the O(M) activation footprint);
        # clipped ticks must not clobber slot 0 / M−1
        saved = lax.cond(
            valid,
            lambda b: lax.dynamic_update_index_in_dim(b, x_in, m_c, 0),
            lambda b: b,
            saved,
        )
        fwd_recv = lax.ppermute(
            out, "pp", [(i, (i + 1) % pp) for i in range(pp)])
        return (saved, aux_sum, fwd_recv), None

    (saved, aux_sum, _), _ = lax.scan(
        fwd_tick,
        (jnp.zeros((M,) + micro_shape, x_micro.dtype),
         jnp.zeros([], jnp.float32),
         jnp.zeros(micro_shape, x_micro.dtype)),
        jnp.arange(Tf))

    # ---------------- backward half: drain in reverse micro order ------
    def bwd_tick(carry, t):
        pgrads, hgrads, dxs, loss_sum, bwd_recv = carry
        u = t - (pp - 1 - stage)
        valid = (u >= 0) & (u < M)
        m = M - 1 - jnp.clip(u, 0, M - 1)
        x_saved = saved[m]
        y_m = y_micro[m]

        (out_b, _aux_b), vjp_blk = jax.vjp(blk, params, x_saved)
        is_head = (stage == pp - 1) & valid

        def head_branch(ob, y):
            loss_val, vjp_head = jax.vjp(
                lambda o, hp: loss_fn(o, y, hp), ob, post_params)
            d_out, dh_l = vjp_head(jnp.ones_like(loss_val))
            return loss_val.astype(jnp.float32), d_out, dh_l

        def skip_branch(ob, y):
            return (jnp.zeros([], jnp.float32), jnp.zeros_like(ob),
                    _tree_zeros(post_params))

        loss_val, d_out, dh_l = lax.cond(
            is_head, head_branch, skip_branch, out_b, y_m)
        cot = jnp.where(is_head, d_out, bwd_recv)
        aux_cot = jnp.where(valid, jnp.float32(aux_seed), jnp.float32(0.0))
        dparams, dx = vjp_blk((cot, aux_cot))

        pgrads = _tree_add_masked(pgrads, dparams, valid)
        hgrads = jax.tree_util.tree_map(lambda a, d: a + d, hgrads, dh_l)
        loss_sum = loss_sum + loss_val
        dxs = lax.cond(
            valid & (stage == 0),
            lambda bf: lax.dynamic_update_index_in_dim(bf, dx, m, 0),
            lambda bf: bf,
            dxs,
        )
        bwd_recv = lax.ppermute(
            dx, "pp", [(i, (i - 1) % pp) for i in range(pp)])
        return (pgrads, hgrads, dxs, loss_sum, bwd_recv), None

    (pgrads, hgrads, dxs, loss_sum, _), _ = lax.scan(
        bwd_tick,
        (_tree_zeros(params), _tree_zeros(post_params),
         jnp.zeros_like(x_micro), jnp.zeros([], jnp.float32),
         jnp.zeros(micro_shape, x_micro.dtype)),
        jnp.arange(Tf))

    # ---------------- finishing reductions (same as _run_schedule) -----
    loss = lax.psum(loss_sum, "pp") / M
    aux = lax.psum(aux_sum, "pp") / M
    inv_m = 1.0 / M
    pgrads = jax.tree_util.tree_map(lambda g: g * inv_m, pgrads)
    hgrads = jax.tree_util.tree_map(
        lambda g: lax.psum(g, "pp") * inv_m, hgrads)
    dxs = lax.psum(dxs, "pp") * inv_m
    if sum_axes:
        for ax in sum_axes:
            loss = lax.psum(loss, ax)
            aux = lax.psum(aux, ax)
            pgrads = jax.tree_util.tree_map(
                lambda g, _ax=ax: lax.psum(g, _ax), pgrads)
            hgrads = jax.tree_util.tree_map(
                lambda g, _ax=ax: lax.psum(g, _ax), hgrads)
    if dp_axis is not None:
        inv_dp = 1.0 / mesh_mod.axis_size(dp_axis)
        loss = lax.pmean(loss, dp_axis)
        aux = lax.pmean(aux, dp_axis)
        if quant_dp:
            # the 1F1B schedule's int8 grad all-reduce, identically
            # (see _run_schedule — the two schedules share the
            # finishing-reduction contract)
            from ..quant_collective import quantized_pmean_tree

            pgrads, hgrads = quantized_pmean_tree(
                (pgrads, hgrads), dp_axis)
        else:
            pgrads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), pgrads)
            hgrads = jax.tree_util.tree_map(
                lambda g: lax.pmean(g, dp_axis), hgrads)
        dxs = dxs * inv_dp
    return loss + aw * aux, aux, pgrads, hgrads, dxs


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 5, 6, 7))
def pipeline_gpipe(block_fn, loss_fn, stacked_params, post_params, batch,
                   remat=True, specs=None, aux_weight=None):
    """Differentiable GPipe pipeline loss — `pipeline_1f1b`'s contract
    (block_fn/loss_fn/stacked/post/batch/specs/aux_weight all identical;
    see its docstring) on the all-forward-then-all-backward schedule.
    No virtual stages: interleaving is a 1F1B refinement — chunks of one
    micro would collide in GPipe's serialized halves."""
    loss, aux, _, _, _ = _gpipe_call(block_fn, loss_fn, stacked_params,
                                     post_params, batch, remat, specs,
                                     aux_weight)
    return loss if aux_weight is None else (loss, aux)


def _gpipe_call(block_fn, loss_fn, stacked_params, post_params, batch,
                remat, specs=None, aux_weight=None):
    mesh = mesh_mod.global_mesh()
    pp = mesh.shape["pp"]
    has_aux = aux_weight is not None
    aw = float(aux_weight) if has_aux else 0.0
    x_micro, y_micro = batch
    if pp == 1:
        # degenerate single-stage path: identical to 1F1B's (there is no
        # schedule left to differ on) — straight-line micro-batched vjp
        from ..fleet.recompute import checkpoint_policy

        blk0 = (block_fn if has_aux else
                (lambda p, x: (block_fn(p, x),
                               jnp.zeros([], jnp.float32))))
        blk1 = (jax.checkpoint(blk0, policy=checkpoint_policy(remat))
                if remat else blk0)

        def full(sp_, hp, xm):
            def one(x, y):
                out, a = blk1(sp_, x)
                return loss_fn(out, y, hp), a

            losses, auxs = jax.vmap(one)(xm, y_micro)
            aux = jnp.mean(auxs)
            return jnp.mean(losses) + aw * aux, aux

        (loss, aux), vjp = jax.vjp(full, stacked_params, post_params,
                                   x_micro)
        pg, hg, dx = vjp((jnp.ones_like(loss), jnp.zeros_like(aux)))
        return loss, aux, pg, hg, dx

    sp = specs if specs is not None else PipelineSpecs()
    stack_spec = _unflatten_like(
        stacked_params, sp.stacked,
        lambda a: P(*(["pp"] + [None] * (a.ndim - 1))), require_pp=True)
    post_spec = _unflatten_like(
        post_params, sp.post, lambda a: P(*([None] * a.ndim)))
    x_spec = sp.x if sp.x is not None else P(*([None] * x_micro.ndim))
    y_spec = sp.y if sp.y is not None else P(*([None] * y_micro.ndim))

    run = jax.shard_map(
        functools.partial(_run_gpipe, block_fn, loss_fn, pp=pp,
                          remat=remat, dp_axis=sp.dp_axis,
                          sum_axes=sp.sum_axes, aux_weight=aux_weight,
                          quant_dp=sp.quant_dp),
        mesh=mesh,
        in_specs=(stack_spec, post_spec, x_spec, y_spec),
        out_specs=(P(), P(), stack_spec, post_spec, x_spec),
        check_vma=False,
    )
    # ALWAYS jit (same reasoning as _pipeline_call): shard_map bodies
    # with closed_calls cannot run outside jit on this jax version
    run = jax.jit(run)
    return run(stacked_params, post_params, x_micro, y_micro)


def _gpipe_fwd(block_fn, loss_fn, stacked_params, post_params, batch,
               remat, specs=None, aux_weight=None):
    loss, aux, pg, hg, dx = _gpipe_call(
        block_fn, loss_fn, stacked_params, post_params, batch, remat,
        specs, aux_weight)
    out = loss if aux_weight is None else (loss, aux)
    return out, (pg, hg, dx, batch[1])


def _gpipe_bwd(block_fn, loss_fn, remat, specs, aux_weight, res, g):
    pg, hg, dx, y = res
    if aux_weight is not None:
        g, _ = g
    scale = lambda t: jax.tree_util.tree_map(lambda a: a * g, t)
    return (scale(pg), scale(hg),
            (scale(dx), jax.tree_util.tree_map(jnp.zeros_like, y)))


pipeline_gpipe.defvjp(_gpipe_fwd, _gpipe_bwd)
