"""paddle_tpu.distributed.hybrid3d — mesh-native DP × TP × PP.

The hybrid-parallel layer of the framework: data, tensor and pipeline
parallelism composed as ONE sharded, donated, zero-recompile executable
per mesh config — `shard_map`/`pjit` over the 3-axis (dp, tp→'mp', pp)
global mesh, the way "Scale MLPerf-0.6 models on Google TPU-v3 Pods"
scales to 1024-chip pods. The pieces:

* `Hybrid3DConfig` / `init_hybrid_mesh` / `build_gpt3d` (plan.py) —
  one frozen, validated plan per run; builds the mesh, validates model
  divisibility, stamps itself into bench/telemetry records.
* `HybridTrainStep` (jit/hybrid_step.py, re-exported here and as
  `paddle.jit.HybridTrainStep`) — `TrainStep`'s mesh-aware sibling:
  same step layout + donation spec (so `analyze_step` and the
  donation/zero-recompile probes work unchanged), param/opt-state
  shardings pinned, ZeRO composed on the dp axis, the donation gauge
  published as `pt_step_donation_held{step="hybrid3d"}`.
* `pipeline_gpipe` (schedule.py) — the GPipe microbatch schedule as a
  `lax.scan` over stages, interchangeable with the lockstep 1F1B scan
  behind the same `PipelineSpecs`.
* TP sharding rules (tp.py) — weight-stationary column/row placement
  helpers, including the int8 path: `shard_model_int8_tp` shards
  `Int8WeightOnlyLinear` weight+scale buffers over the tp axis
  (closing docs/QUANTIZATION.md's "no TP shard yet" gap).

Strategy meta-optimizers (LARS / DGC / LocalSGD) compose through the
optimizer protocol: `fleet.distributed_optimizer` swaps the inner
optimizer per the strategy toggles and `HybridTrainStep` runs it inside
the same donated executable.
"""
from .plan import Hybrid3DConfig, build_gpt3d, init_hybrid_mesh  # noqa: F401
from .schedule import gpipe_ticks, pipeline_gpipe  # noqa: F401
from .tp import (  # noqa: F401
    column_parallel_spec, int8_tp_placement, row_parallel_spec,
    shard_int8_linear, shard_model_int8_tp, tp_axis)
from ...jit.hybrid_step import HybridTrainStep  # noqa: F401

__all__ = [
    "Hybrid3DConfig", "init_hybrid_mesh", "build_gpt3d",
    "HybridTrainStep", "pipeline_gpipe", "gpipe_ticks",
    "shard_int8_linear", "shard_model_int8_tp", "int8_tp_placement",
    "column_parallel_spec", "row_parallel_spec", "tp_axis",
]
