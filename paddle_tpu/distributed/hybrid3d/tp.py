"""Weight-stationary tensor-parallel sharding rules — fp32 and int8.

The Megatron pattern for the GPT Linears (qkv/fc1 column-sharded on the
output dim, proj/fc2 row-sharded on the input dim, vocab-parallel
embedding/head) already exists twice in this tree: as explicit
shard_map collectives inside the pipeline stages
(fleet/meta_parallel/mp_layers.py + text/models/gpt_pipeline.py) and as
GSPMD `mark_sharding` annotations on the stacked parameters. What was
MISSING is the int8 path: `quantize_model_int8` swaps Linears for
`Int8WeightOnlyLinear` whose weight lives as int8 BUFFERS
(weight_q [in, out] + per-out-channel w_step [1, out]) — and until now
those buffers were replicated on a >1 'mp' mesh
(docs/QUANTIZATION.md's "no TP shard yet" note). This module is the one
place that knows how to place them:

* column-parallel: weight_q P(None, 'mp'), w_step P(None, 'mp'),
  bias P('mp') — each tp rank holds out/tp output channels AND their
  dequant scales (the scale rides its channel, so dequant needs no
  collective);
* row-parallel: weight_q P('mp', None), w_step replicated — the int32
  accumulator of a row shard is a PARTIAL sum; XLA inserts the
  all-reduce after the dequant epilogue (GSPMD semantics preserved).

These are GSPMD placements, not shard_map slices — annotation-only, so
any choice is semantics-preserving and `auto` can fall back safely.
"""
import jax
from jax.sharding import PartitionSpec as P

from .. import mesh as mesh_mod

__all__ = ["shard_int8_linear", "shard_model_int8_tp", "tp_axis",
           "column_parallel_spec", "row_parallel_spec"]

TP_AXIS = "mp"


def tp_axis():
    """The mesh axis tensor parallelism rides ('mp' — the reference's
    Megatron naming, shared with every existing PartitionSpec)."""
    return TP_AXIS


def column_parallel_spec(ndim, out_dim=-1, axis=TP_AXIS):
    """Spec sharding the OUTPUT-channel dim (weight-stationary column
    parallel: each rank owns out/tp columns)."""
    out_dim = out_dim % ndim
    return P(*[axis if d == out_dim else None for d in range(ndim)])


def row_parallel_spec(ndim, in_dim=0, axis=TP_AXIS):
    """Spec sharding the INPUT dim (row parallel: partial sums, XLA
    all-reduces after the matmul)."""
    in_dim = in_dim % ndim
    return P(*[axis if d == in_dim else None for d in range(ndim)])


def _mark(buf, spec):
    from ..fleet.meta_parallel.mp_layers import mark_sharding

    mark_sharding(buf, *spec)
    return buf


def shard_int8_linear(layer, kind="auto", axis=TP_AXIS):
    """TP-shard one `Int8WeightOnlyLinear`'s buffers over `axis`.

    kind: 'column' | 'row' | 'auto'. Auto prefers column (the scale
    stays with its channel — no sharded-scale subtleties) and falls
    back to row, skipping the layer when neither dim divides the axis
    size. Returns the placement applied: 'column' | 'row' | None.
    """
    n = mesh_mod.axis_size(axis)
    if n <= 1:
        return None
    out_f = int(layer.out_features)
    in_f = int(layer.in_features)
    want = kind
    if kind == "auto":
        want = ("column" if out_f % n == 0
                else ("row" if in_f % n == 0 else None))
    if want == "column":
        if out_f % n:
            raise ValueError(
                f"out_features={out_f} not divisible by {axis}={n}")
        _mark(layer.weight_q, column_parallel_spec(2, 1, axis))
        _mark(layer.w_step, column_parallel_spec(2, 1, axis))
        if layer.bias is not None:
            _mark(layer.bias, P(axis))
    elif want == "row":
        if in_f % n:
            raise ValueError(
                f"in_features={in_f} not divisible by {axis}={n}")
        _mark(layer.weight_q, row_parallel_spec(2, 0, axis))
        # per-OUT-channel scales don't follow a row shard — replicate
        _mark(layer.w_step, P(None, None))
        if layer.bias is not None:
            _mark(layer.bias, P(None))
    elif want is not None:
        raise ValueError(f"kind={kind!r}: expected column/row/auto")
    return want


def shard_model_int8_tp(model, rules=None, axis=TP_AXIS):
    """Walk `model` and TP-shard every `Int8WeightOnlyLinear` (the
    quantize_model_int8 output) over `axis`.

    rules: optional {substring: 'column'|'row'} matched against the
    sublayer path (first hit wins) — e.g. the Megatron GPT pattern
    {'qkv': 'column', 'fc1': 'column', 'proj': 'row', 'fc2': 'row'}.
    Unmatched layers use 'auto'. Returns {path: placement} for the
    layers touched (placement None = skipped, indivisible)."""
    from ...quantization.runtime import Int8WeightOnlyLinear

    placed = {}
    if mesh_mod.axis_size(axis) <= 1:
        return placed
    for path, sub in model.named_sublayers():
        if not isinstance(sub, Int8WeightOnlyLinear):
            continue
        kind = "auto"
        for pat, k in (rules or {}).items():
            if pat in path:
                kind = k
                break
        placed[path] = shard_int8_linear(sub, kind, axis)
    return placed


def int8_tp_placement(layer):
    """Report where a quantized linear's buffers live: 'column', 'row',
    or 'replicated' — the doc/test-facing probe."""
    spec = getattr(layer.weight_q, "_pspec", None)
    if spec is None:
        return "replicated"
    spec = tuple(spec)
    if len(spec) == 2 and spec[1] == TP_AXIS:
        return "column"
    if len(spec) >= 1 and spec[0] == TP_AXIS:
        return "row"
    return "replicated"


__all__.append("int8_tp_placement")
