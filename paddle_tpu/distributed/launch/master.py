"""Membership master — cross-host worker registry for elastic jobs.

TPU-native re-design of the reference's elastic membership service
(reference: python/paddle/distributed/launch/controllers/master.py:175
ETCDMaster — etcd node registry with TTL heartbeats, re-rank on peer
arrival/loss; fleet/elastic/manager.py:127 watches it). There is no etcd
in this stack, and the jax.distributed coordination KV dies with the pod
it serves — so the LAUNCHER hosts this tiny TCP registry instead. It
outlives pod restarts (it belongs to the launcher process), needs no
shared filesystem, and works across hosts: workers and operators talk to
it via one `host:port` endpoint (env ``PADDLE_ELASTIC_MASTER``).

Protocol: one JSON object per line over a short-lived connection —
heartbeat rates are ~1/s/worker, far below any framing concern.

  {"op": "beat", "rank": R}        register/refresh worker R
  {"op": "clear", "rank": R}       deregister (clean exit tombstone)
  {"op": "join", "n": N}           request N workers admitted (operator)
  {"op": "peers"}                  -> {"peers": {"R": age_seconds, ...}}
  {"op": "joins"}                  -> {"count": pending join requests}
  {"op": "consume_joins", "n": N}  consume N requests (launcher)
  {"op": "reset"}                  drop all beats (pod re-form)

The heartbeat-DIRECTORY protocol (hb_*/join_* files) remains as the
fallback when no master endpoint is set — zero-dependency single-host
operation.
"""
import json
import os
import socket
import threading
import time

__all__ = ["MembershipMaster", "MembershipClient", "master_endpoint"]


def master_endpoint():
    """The job's membership-master endpoint, if one is active."""
    return os.environ.get("PADDLE_ELASTIC_MASTER") or None


def _advertise_ip(route_via=None):
    """Address this host is reachable at: route toward the job
    coordinator (every rank provably reaches it) or a public address
    and read the socket's own name; loopback for single-host jobs.
    Override with PADDLE_TPU_MASTER_ADVERTISE. Same recipe as
    xproc._local_ip (the p2p transport's endpoint publication)."""
    targets = []
    if route_via and route_via.rsplit(":", 1)[0] not in (
            "127.0.0.1", "localhost", ""):
        hp = route_via.rsplit(":", 1)
        targets.append((hp[0], int(hp[1]) if len(hp) > 1 and
                        hp[1].isdigit() else 80))
    targets.append(("8.8.8.8", 80))
    for target in targets:
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            try:
                s.connect(target)
                return s.getsockname()[0]
            finally:
                s.close()
        except OSError:
            continue
    return "127.0.0.1"


class MembershipMaster:
    """Threaded TCP registry. Start in the launcher (or any supervisor
    process); hand `endpoint` to workers via PADDLE_ELASTIC_MASTER."""

    def __init__(self, host="0.0.0.0", advertise=None, route_via=None):
        self._beats = {}          # rank -> last beat time
        self._health = {}         # rank -> {"degraded": bool, "retries": n}
        self._joins = 0
        self._lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, 0))
        self._sock.listen(64)
        port = self._sock.getsockname()[1]
        adv = (advertise
               or os.environ.get("PADDLE_TPU_MASTER_ADVERTISE")
               or _advertise_ip(route_via))
        self.endpoint = f"{adv}:{port}"
        self._thread = threading.Thread(
            target=self._serve, name="membership-master", daemon=True)
        self._thread.start()

    # -- server --
    def _serve(self):
        while True:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn):
        try:
            with conn, conn.makefile("rw", encoding="utf-8") as f:
                line = f.readline()
                if not line:
                    return
                req = json.loads(line)
                resp = self._dispatch(req)
                f.write(json.dumps(resp) + "\n")
                f.flush()
        except (OSError, ValueError):
            pass

    def _dispatch(self, req):
        op = req.get("op")
        with self._lock:
            if op == "beat":
                r = int(req["rank"])
                self._beats[r] = time.time()
                # degraded-vs-dead: a beat can carry retry telemetry
                # (resilience.recent_failures) — the rank is alive but
                # retry-storming; monitors log it instead of failing
                # the pod
                self._health[r] = {
                    "degraded": bool(req.get("degraded", False)),
                    "retries": int(req.get("retries", 0))}
                return {"ok": True}
            if op == "clear":
                self._beats.pop(int(req["rank"]), None)
                self._health.pop(int(req["rank"]), None)
                return {"ok": True}
            if op == "join":
                self._joins += int(req.get("n", 1))
                return {"ok": True}
            if op == "peers":
                now = time.time()
                return {"peers": {str(r): now - t
                                  for r, t in self._beats.items()}}
            if op == "health":
                return {"health": {str(r): h
                                   for r, h in self._health_view().items()}}
            if op == "joins":
                return {"count": self._joins}
            if op == "consume_joins":
                n = min(self._joins, int(req.get("n", self._joins)))
                self._joins -= n
                return {"consumed": n}
            if op == "reset":
                self._beats.clear()
                return {"ok": True}
        return {"error": f"unknown op {op!r}"}

    # -- launcher-side local views (no socket round-trip) --
    def peers(self):
        now = time.time()
        with self._lock:
            return [(r, now - t) for r, t in sorted(self._beats.items())]

    def _health_view(self):
        """rank -> {age, degraded, retries}. Caller holds self._lock."""
        now = time.time()
        return {r: {"age": now - t,
                    **self._health.get(r, {"degraded": False,
                                           "retries": 0})}
                for r, t in sorted(self._beats.items())}

    def health(self):
        """rank -> {age, degraded, retries} local view (launcher-side)."""
        with self._lock:
            return self._health_view()

    def pending_joins(self):
        with self._lock:
            return self._joins

    def consume_joins(self, n=None):
        with self._lock:
            take = self._joins if n is None else min(n, self._joins)
            self._joins -= take
            return take

    def clear_rank(self, rank):
        """Deregister a cleanly-exited worker (launcher-side)."""
        with self._lock:
            self._beats.pop(int(rank), None)
            self._health.pop(int(rank), None)

    def reset_beats(self):
        with self._lock:
            self._beats.clear()
            self._health.clear()

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class MembershipClient:
    """One-RPC-per-call client (workers beat ~1/s; operators post joins)."""

    def __init__(self, endpoint, timeout=10.0):
        host, port = endpoint.rsplit(":", 1)
        self._addr = (host, int(port))
        self._timeout = timeout

    def _rpc(self, req):
        with socket.create_connection(self._addr,
                                      timeout=self._timeout) as s:
            with s.makefile("rw", encoding="utf-8") as f:
                f.write(json.dumps(req) + "\n")
                f.flush()
                line = f.readline()
        return json.loads(line) if line else {}

    def beat(self, rank, degraded=False, retries=0):
        """Heartbeat, optionally carrying retry telemetry: degraded=True
        marks the rank as alive-but-retry-storming (distinct from dead —
        the launcher logs it rather than failing the pod)."""
        req = {"op": "beat", "rank": int(rank)}
        if degraded or retries:
            req["degraded"] = bool(degraded)
            req["retries"] = int(retries)
        return self._rpc(req)

    def health(self):
        """rank -> {age, degraded, retries} for every beating worker."""
        got = self._rpc({"op": "health"}).get("health", {})
        return {int(r): h for r, h in got.items()}

    def clear(self, rank):
        return self._rpc({"op": "clear", "rank": int(rank)})

    def join(self, n=1):
        return self._rpc({"op": "join", "n": int(n)})

    def peers(self):
        got = self._rpc({"op": "peers"}).get("peers", {})
        return [(int(r), age) for r, age in sorted(
            got.items(), key=lambda kv: int(kv[0]))]

    def pending_joins(self):
        return int(self._rpc({"op": "joins"}).get("count", 0))
