"""Distributed job launcher — ``python -m paddle_tpu.distributed.launch``.

TPU-native re-design of the reference launcher
(reference: python/paddle/distributed/launch/main.py:18 `launch()`,
launch/controllers/collective.py:24 CollectiveController.build_pod).

The reference spawns one process per GPU and hands each a NCCL rendezvous
via PADDLE_TRAINER_ENDPOINTS.  On TPU the natural unit is one process per
HOST (each process owns all local chips; XLA drives ICI/DCN collectives),
so the launcher's job collapses to:

  1. set the env contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
     PADDLE_MASTER / ...) for each worker process,
  2. point every worker at one coordinator (jax.distributed uses a
     KV-store at PADDLE_MASTER the way the reference uses TCPStore —
     reference: python/paddle/distributed/parallel.py:94),
  3. babysit the pod: stream logs, propagate failures, optionally
     restart (--max_restart, reference launch/controllers/controller.py).

Workers call `paddle_tpu.distributed.init_parallel_env()` which picks up
the contract and runs `jax.distributed.initialize` (multi-controller
SPMD bring-up) before building the global mesh.

For CPU-host testing, `--nproc_per_node N` on one node emulates N hosts
(JAX gloo collectives connect the processes).
"""
import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "parse_args"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job.",
    )
    p.add_argument("--master", default=None,
                   help="coordinator host:port (default: auto on one node)")
    p.add_argument("--rank", type=int, default=0,
                   help="rank of this node (0..nnodes-1)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes in the job")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes on this node (1 per TPU host is "
                        "the norm; >1 emulates a pod on CPU)")
    p.add_argument("--log_dir", default="log", help="per-rank log directory")
    p.add_argument("--job_id", default="default", help="job id for log names")
    p.add_argument("--devices", default=None,
                   help="restrict visible devices (sets TPU_VISIBLE_DEVICES)")
    p.add_argument("--max_restart", type=int, default=0,
                   help="restart the pod up to N times on failure")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank, master):
    nproc = args.nproc_per_node
    world = args.nnodes * nproc
    rank = args.rank * nproc + local_rank
    env = dict(os.environ)
    env.update({
        "PADDLE_MASTER": master,
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(nproc),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_JOB_ID": args.job_id,
    })
    if args.devices is not None:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    return env


def _spawn_pod(args, master):
    """Start nproc_per_node workers; local rank 0 inherits the console."""
    os.makedirs(args.log_dir, exist_ok=True)
    procs = []
    cmd = [sys.executable, args.training_script] + args.training_script_args
    for lr in range(args.nproc_per_node):
        env = _worker_env(args, lr, master)
        rank = env["PADDLE_TRAINER_ID"]
        if lr == 0:
            out = None  # inherit
        else:
            # append so logs from failed attempts survive --max_restart
            out = open(os.path.join(
                args.log_dir, f"{args.job_id}.rank{rank}.log"), "a")
        procs.append((subprocess.Popen(
            cmd, env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None), out))
    return procs


def _wait_pod(procs, poll_s=0.2):
    """Block until all exit ok or one fails (then kill the rest)."""
    alive = {i: p for i, (p, _) in enumerate(procs)}
    failed_rc = 0
    while alive and not failed_rc:
        time.sleep(poll_s)
        for i, p in list(alive.items()):
            rc = p.poll()
            if rc is None:
                continue
            del alive[i]
            if rc != 0:
                failed_rc = rc
    for p in alive.values():
        p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in alive.values():
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
    for _, out in procs:
        if out:
            out.close()
    return failed_rc


def launch(argv=None):
    args = parse_args(argv)
    if args.training_script_args[:1] == ["--"]:
        args.training_script_args = args.training_script_args[1:]
    master = args.master
    if master is None:
        if args.nnodes > 1:
            sys.exit("--master is required when --nnodes > 1")
        master = f"127.0.0.1:{_free_port()}"
    attempts = args.max_restart + 1
    for attempt in range(attempts):
        if attempt:
            print(f"[launch] pod failed; restart {attempt}/{args.max_restart}",
                  file=sys.stderr, flush=True)
        procs = _spawn_pod(args, master)
        rc = _wait_pod(procs)
        if rc == 0:
            return 0
    sys.exit(rc)
