"""Distributed job launcher — ``python -m paddle_tpu.distributed.launch``.

TPU-native re-design of the reference launcher
(reference: python/paddle/distributed/launch/main.py:18 `launch()`,
launch/controllers/collective.py:24 CollectiveController.build_pod).

The reference spawns one process per GPU and hands each a NCCL rendezvous
via PADDLE_TRAINER_ENDPOINTS.  On TPU the natural unit is one process per
HOST (each process owns all local chips; XLA drives ICI/DCN collectives),
so the launcher's job collapses to:

  1. set the env contract (PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM /
     PADDLE_MASTER / ...) for each worker process,
  2. point every worker at one coordinator (jax.distributed uses a
     KV-store at PADDLE_MASTER the way the reference uses TCPStore —
     reference: python/paddle/distributed/parallel.py:94),
  3. babysit the pod: stream logs, propagate failures, optionally
     restart (--max_restart, reference launch/controllers/controller.py).

Workers call `paddle_tpu.distributed.init_parallel_env()` which picks up
the contract and runs `jax.distributed.initialize` (multi-controller
SPMD bring-up) before building the global mesh.

For CPU-host testing, `--nproc_per_node N` on one node emulates N hosts
(JAX gloo collectives connect the processes).
"""
import argparse
import os
import signal
import socket
import subprocess
import sys
import time

__all__ = ["launch", "parse_args"]


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="Launch a distributed paddle_tpu job.",
    )
    p.add_argument("--master", default=None,
                   help="coordinator host:port (default: auto on one node)")
    p.add_argument("--rank", type=int, default=0,
                   help="rank of this node (0..nnodes-1)")
    p.add_argument("--nnodes", type=int, default=1,
                   help="number of nodes in the job")
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="worker processes on this node (1 per TPU host is "
                        "the norm; >1 emulates a pod on CPU)")
    p.add_argument("--log_dir", default="log", help="per-rank log directory")
    p.add_argument("--job_id", default="default", help="job id for log names")
    p.add_argument("--devices", default=None,
                   help="restrict visible devices (sets TPU_VISIBLE_DEVICES)")
    p.add_argument("--max_restart", type=int, default=0,
                   help="restart the pod up to N times on failure")
    p.add_argument("--elastic_level", type=int, default=0,
                   help="0: restart-only; >=1: elastic membership — "
                        "scale-IN (after 2 consecutive failed attempts, "
                        "re-form the pod over the surviving slots with "
                        "contiguous rank remap) AND scale-OUT (a "
                        "fleet.elastic.request_scale_out join request "
                        "tears the pod down and re-forms it with the "
                        "joiners admitted; workers resume from the "
                        "latest checkpoint) — reference "
                        "elastic/manager.py. Single-node pods only.")
    p.add_argument("--elastic_timeout", type=float, default=30.0,
                   help="seconds without a worker heartbeat before the "
                        "pod is declared hung and restarted")
    p.add_argument("--log_level", default="INFO")
    p.add_argument("training_script", help="script to run")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def _worker_env(args, local_rank, master, nproc=None, mm_endpoint=None,
                attempt=0):
    nproc = nproc if nproc is not None else args.nproc_per_node
    world = args.nnodes * nproc
    rank = args.rank * nproc + local_rank
    env = dict(os.environ)
    env.update({
        "PADDLE_MASTER": master,
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(world),
        "PADDLE_LOCAL_RANK": str(local_rank),
        "PADDLE_LOCAL_SIZE": str(nproc),
        "PADDLE_NNODES": str(args.nnodes),
        "PADDLE_JOB_ID": args.job_id,
        "PADDLE_HEARTBEAT_DIR": os.path.join(args.log_dir, "hb"),
        "PADDLE_ELASTIC_TIMEOUT": str(args.elastic_timeout),
        # per-rank anomaly journal (resilience.py) lands next to the logs
        "PADDLE_LOG_DIR": args.log_dir,
        # pod incarnation: namespaces KV-collective keys so a restarted
        # pod can never collide with a previous incarnation's leftovers
        "PADDLE_POD_ATTEMPT": str(attempt),
    })
    if mm_endpoint:
        env["PADDLE_ELASTIC_MASTER"] = mm_endpoint
    if args.devices is not None:
        env["TPU_VISIBLE_DEVICES"] = args.devices
    return env


def _spawn_pod(args, master, nproc=None, mm=None, attempt=0):
    """Start nproc workers; local rank 0 inherits the console."""
    nproc = nproc if nproc is not None else args.nproc_per_node
    os.makedirs(args.log_dir, exist_ok=True)
    hb_dir = os.path.join(args.log_dir, "hb")
    os.makedirs(hb_dir, exist_ok=True)
    # clear stale beats from a previous attempt. join_* requests are NOT
    # touched: they are consumed only by launch() after counting, so a
    # request landing during a teardown window is admitted next round
    # instead of silently dropped.
    if mm is not None:
        mm.reset_beats()
    for f in os.listdir(hb_dir):
        if f.startswith("hb_"):
            try:
                os.unlink(os.path.join(hb_dir, f))
            except OSError:
                pass
    procs = []
    cmd = [sys.executable, args.training_script] + args.training_script_args
    for lr in range(nproc):
        env = _worker_env(args, lr, master, nproc,
                          mm_endpoint=mm.endpoint if mm else None,
                          attempt=attempt)
        rank = env["PADDLE_TRAINER_ID"]
        if lr == 0:
            out = None  # inherit
        else:
            # append so logs from failed attempts survive --max_restart
            out = open(os.path.join(
                args.log_dir, f"{args.job_id}.rank{rank}.log"), "a")
        procs.append((subprocess.Popen(
            cmd, env=env, stdout=out,
            stderr=subprocess.STDOUT if out else None), out))
    return procs


RC_SCALE_OUT = 97  # synthetic: pod torn down to admit joining workers


def _pending_joins(hb_dir):
    """join_* request files dropped by elastic.request_scale_out
    (reference: elastic/manager.py:127 — ETCDMaster re-ranks on node
    ARRIVAL; the heartbeat dir plays the etcd registry). Shared
    protocol lives in fleet/elastic.py."""
    from ..fleet.elastic import pending_join_files

    return pending_join_files(hb_dir)


def _stale_beats(mm, hb_dir, hb_timeout):
    """(name, age) of workers whose heartbeat exceeds hb_timeout — from
    the membership master when one is active (cross-host, no shared
    FS), else from the heartbeat directory's file mtimes."""
    if mm is not None:
        return [(f"rank {r}", age) for r, age in mm.peers()
                if age > hb_timeout]
    out = []
    now = time.time()
    try:
        beats = os.listdir(hb_dir)
    except OSError:
        beats = []
    for f in beats:
        if not f.startswith("hb_"):
            continue  # join_* requests are not heartbeats
        try:
            age = now - os.path.getmtime(os.path.join(hb_dir, f))
        except OSError:
            continue
        if age > hb_timeout:
            out.append((f, age))
    return out


def _wait_pod(procs, poll_s=0.2, hb_dir=None, hb_timeout=0.0,
              rank_base=0, watch_joins=False, mm=None):
    """Block until all exit ok or one fails (then kill the rest).

    A worker whose heartbeat goes stale for longer than hb_timeout is
    declared HUNG and fails the pod — liveness alone misses a worker
    wedged in a dead collective (reference: etcd heartbeat TTL,
    elastic/manager.py:234). Beats come from the membership master
    (`mm`, launch/master.py — cross-host) or the heartbeat dir
    fallback. Only workers that have beaten at least once are
    monitored, so non-paddle scripts that never call init_parallel_env
    are unaffected. With watch_joins, a pending join request tears the
    pod down with RC_SCALE_OUT so the caller can re-form it at the
    larger size (reference scale-out on node join)."""
    alive = {i: p for i, (p, _) in enumerate(procs)}
    failed_rc = 0
    degraded = set()   # ranks currently marked degraded (log transitions)
    while alive and not failed_rc:
        time.sleep(poll_s)
        if mm is not None:
            # degraded-vs-dead: a rank that beats but reports retry
            # storms is logged, not failed — only beat STALENESS (below)
            # kills the pod
            for r, h in mm.health().items():
                if h["degraded"] and r not in degraded:
                    degraded.add(r)
                    print(f"[launch] worker rank {r} DEGRADED "
                          f"({h['retries']} recent retries; still "
                          "beating — not restarting)",
                          file=sys.stderr, flush=True)
                elif not h["degraded"] and r in degraded:
                    degraded.discard(r)
                    print(f"[launch] worker rank {r} recovered "
                          "(retries subsided)",
                          file=sys.stderr, flush=True)
        if watch_joins and (
                (mm is not None and mm.pending_joins())
                or (hb_dir and _pending_joins(hb_dir))):
            failed_rc = RC_SCALE_OUT
            break
        for i, p in list(alive.items()):
            rc = p.poll()
            if rc is None:
                continue
            del alive[i]
            if rc != 0:
                failed_rc = rc
            else:
                # clean exit: drop the worker's beat so the staleness
                # monitor doesn't mistake "finished" for "wedged" (the
                # worker's own atexit does this too; SIGKILL'd-after-done
                # edge cases land here)
                if mm is not None:
                    mm.clear_rank(rank_base + i)
                if hb_dir:
                    try:
                        os.unlink(os.path.join(hb_dir,
                                               f"hb_{rank_base + i}"))
                    except OSError:
                        pass
        if not failed_rc and hb_timeout > 0 and (mm is not None or hb_dir):
            for name, age in _stale_beats(mm, hb_dir, hb_timeout):
                print(f"[launch] worker {name} heartbeat stale "
                      f"({age:.0f}s > {hb_timeout:.0f}s): pod hung",
                      file=sys.stderr, flush=True)
                failed_rc = 98  # synthetic "hung" exit code
                break
    for p in alive.values():
        p.send_signal(signal.SIGTERM)
    deadline = time.time() + 10
    for p in alive.values():
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
    for _, out in procs:
        if out:
            out.close()
    return failed_rc


def launch(argv=None):
    args = parse_args(argv)
    # the launcher is a supervisor, not the measured workload: under
    # PT_TELEMETRY=1 its own at-exit export would land on rank 0's
    # files (no PADDLE_TRAINER_ID here) and overwrite the worker's real
    # snapshot after the pod exits — drop to counting-only
    from ...observability import full_enabled, set_mode

    if full_enabled():
        set_mode("metrics")
    if args.training_script_args[:1] == ["--"]:
        args.training_script_args = args.training_script_args[1:]
    master = args.master
    if master is None:
        if args.nnodes > 1:
            sys.exit("--master is required when --nnodes > 1")
        master = f"127.0.0.1:{_free_port()}"
    if args.elastic_level >= 1 and args.nnodes > 1:
        # membership (heartbeats/joins) is cross-host via the
        # MembershipMaster, but pod RE-FORMING at a new size is still
        # coordinated per launcher invocation — multi-node re-forms
        # would need the launchers themselves to rendezvous
        sys.exit("--elastic_level>=1 is single-node-pod scoped "
                 "(cross-host membership is available via "
                 "PADDLE_ELASTIC_MASTER, but pod re-forming is not "
                 "multi-node yet)")
    nproc = args.nproc_per_node
    hb_dir = os.path.join(args.log_dir, "hb")
    # Cross-host membership registry (reference ETCDMaster role): beats
    # and join requests flow through it, so elastic monitoring needs no
    # shared filesystem. PADDLE_TPU_MEMBERSHIP=dir forces the legacy
    # heartbeat-directory protocol.
    from .master import MembershipMaster

    # advertise an address routed toward the job coordinator so the
    # endpoint is reachable from other hosts (loopback when single-node)
    mm = (None if os.environ.get("PADDLE_TPU_MEMBERSHIP") == "dir"
          else MembershipMaster(
              route_via=master if args.nnodes > 1 else None))
    # join requests are only meaningful within ONE launch invocation —
    # a leftover from a previous job must not instantly tear down this
    # pod
    for path in _pending_joins(hb_dir):
        try:
            os.unlink(path)
        except OSError:
            pass
    consecutive = 0
    attempt = 0
    # pod incarnation counter: bumped on EVERY re-form (failure restart,
    # scale-in, scale-out) — unlike `attempt`, which only counts failures
    # toward --max_restart. It feeds PADDLE_POD_ATTEMPT, the epoch that
    # namespaces KV-collective keys, so no incarnation can ever read a
    # previous incarnation's leftover keys.
    pod_gen = -1
    rc = 1
    while True:
        pod_gen += 1
        procs = _spawn_pod(args, master, nproc, mm=mm, attempt=pod_gen)
        rc = _wait_pod(procs, hb_dir=hb_dir,
                       hb_timeout=args.elastic_timeout
                       if args.elastic_timeout > 0 else 0.0,
                       rank_base=args.rank * nproc,
                       watch_joins=args.elastic_level >= 1, mm=mm)
        if rc == 0:
            return 0
        n_joins = 0
        if args.elastic_level >= 1:
            join_files = _pending_joins(hb_dir)
            n_joins = len(join_files)
            if mm is not None:
                n_joins += mm.pending_joins()
        if rc == RC_SCALE_OUT and n_joins:
            # node join (reference ETCDMaster re-rank on peer arrival):
            # admit the joiners, re-form the pod at the larger size with
            # contiguous ranks; workers resume from the latest complete
            # checkpoint and re-shard their samplers at the new world
            # size. Not a failure: does not consume --max_restart.
            # Consume EXACTLY the counted requests — one that lands
            # between the count and the respawn survives for the next
            # watch round instead of being silently dropped.
            for path in join_files:
                try:
                    os.unlink(path)
                except OSError:
                    pass
            if mm is not None:
                mm.consume_joins(n_joins - len(join_files))
            nproc += n_joins
            consecutive = 0
            print(f"[launch] elastic scale-out: {n_joins} "
                  f"worker(s) joining; re-forming pod with {nproc} "
                  f"workers (ranks remapped 0..{nproc - 1})",
                  file=sys.stderr, flush=True)
            continue
        # a worker that genuinely exits 97 (without any join request)
        # falls through to the normal failure/restart path
        attempt += 1
        if attempt > args.max_restart:
            break
        print(f"[launch] pod failed; restart {attempt}/{args.max_restart}"
              f" (nproc={nproc})", file=sys.stderr, flush=True)
        consecutive += 1
        # elastic scale-in: the pod keeps dying at this size — re-form it
        # over the surviving slots with a contiguous rank remap
        # (reference elastic/manager.py:127 rank-map regeneration)
        if args.elastic_level >= 1 and consecutive >= 2 and nproc > 1:
            nproc -= 1
            consecutive = 0
            print(f"[launch] elastic scale-in: re-forming pod with "
                  f"{nproc} workers (ranks remapped 0..{nproc - 1})",
                  file=sys.stderr, flush=True)
    sys.exit(rc)
