"""In-XLA quantized gradient collectives — EQuARX inside the compiled step.

The PR-4 int8 wire codec (`quantization.runtime.encode_int8_wire`) only
covers the EAGER socket/KV fallback; the compiled DP/hybrid3d gradient
path still moves fp32 over the mesh (the dp-axis bytes pinned by
tests/golden/hybrid3d_dp2tp2pp2_schedule.json). This module is the
in-program half: a block-scaled int8 all-reduce-mean built from explicit
`shard_map` collectives, so the payload on the wire IS int8 and the
schedule (and its byte accounting) is visible to
`analysis.spmd_analysis.extract_schedule`.

Design (per `quantized_pmean` call, axis group size n):

1. per-block absmax over the flat payload, `lax.pmax` over the axis →
   every rank holds the SAME per-block scales (the only fp32 collective,
   4/block bytes per element). Shared scales are what make step 3's
   accumulation EXACT in int32 — per-rank scales would force a float
   re-quantization per hop (EQuARX's ring error compounding).
2. quantize to int8 codes against the shared scales.
3. reduce-scatter, as n−1 `lax.ppermute` hops of ONE int8 shard each:
   at hop s every rank sends the codes of the shard owned by rank
   (idx − s) mod n straight to its owner and int32-accumulates the shard
   it receives. Direct exchange — codes never re-quantize, and the
   per-axis payload is exactly the (n−1)/n · N int8 bytes a
   reduce-scatter must move (an `all_to_all` would count the full input
   in the schedule's byte accounting).
4. dequant-accumulate: the int32 code sum × shared scale / n = this
   rank's shard of the MEAN gradient, at full precision.
5. re-quantize the finished shard (fresh per-block scales — the mean's
   dynamic range shrank) and `all_gather` int8 codes + fp32 scales;
   every rank dequantizes the identical bytes, so replicas cannot drift.

NaN-poison contract (the PR-4 wire-codec semantics, in-program): a
non-finite gradient value on ANY rank makes its block's absmax — and,
through the pmax, the SHARED scale — NaN/inf. Its codes clamp to finite
int8, and the dequant (codes × non-finite scale) resolves to NaN for the
whole block on EVERY rank identically, so each replica's grad guards
(StepGuard NaN skip-and-journal) fire in lockstep instead of one rank
publishing a poisoned update its peers never see. Eligibility never
depends on the data (same reasoning as `wire_eligible`).

Wiring (docs/QUANTIZATION.md "In-XLA collectives"):
  * `Hybrid3DConfig(quant_allreduce=True)` / `HybridTrainStep(...,
    quant_allreduce=True)` — the pipeline schedules' dp-axis grad pmean.
  * `DistributedTrainStep(..., quant_allreduce=True)` — the pure-DP
    plain-jit step (the grad sync moves into an explicit shard_map).
  * env `PT_QUANT_ALLREDUCE_XLA=1` — the opt-in default for both.
"""
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import mesh as mesh_mod

__all__ = ["xla_quant_enabled", "quantized_pmean", "quantized_pmean_tree",
           "DEFAULT_BLOCK", "MIN_QUANT_SIZE", "QMAX"]

QMAX = 127.0
# per-block shared-scale granularity: scales cost 4/block bytes per
# element (0.8% at 512) and bound each block's quant error to its OWN
# absmax/127 — big layers can't crush small layers' precision (EQuARX)
DEFAULT_BLOCK = 512
# leaves below this many elements ride a plain fp32 pmean: scalars and
# tiny vectors would pay the block machinery for no measurable bytes
MIN_QUANT_SIZE = 64


def xla_quant_enabled():
    """The `PT_QUANT_ALLREDUCE_XLA` env opt-in (the compiled-path
    sibling of `quantization.runtime.quant_allreduce_enabled`, which
    gates the eager wire codec)."""
    return os.environ.get(
        "PT_QUANT_ALLREDUCE_XLA", "0").strip().lower() in (
            "1", "true", "yes", "on")


def _axis_tuple(axes):
    if isinstance(axes, str):
        return (axes,)
    return tuple(axes)


def _quantize_shared(blocks, scale):
    """int8 codes of [nb, block] f32 against per-block `scale` [nb].
    Non-finite ratios (poisoned scale) clamp to finite codes — the
    poison travels in the SCALE, not the payload (wire-codec parity)."""
    ratio = blocks / scale[:, None]
    q = jnp.nan_to_num(jnp.round(ratio), nan=0.0, posinf=QMAX,
                       neginf=-QMAX)
    return jnp.clip(q, -QMAX, QMAX).astype(jnp.int8)


def _block_scales(blocks):
    """Per-block absmax/127 with the poison property: a non-finite
    element makes its block's scale +inf. The poison must ride as inf,
    not NaN — XLA:CPU's all-reduce max silently DROPS NaN (its reduce
    is maxnum-style), while inf orders above every finite value and
    survives the `lax.pmax`; `codes × inf` then decodes the whole block
    to NaN on every rank identically."""
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    absmax = jnp.where(jnp.isfinite(absmax), absmax, jnp.float32(jnp.inf))
    return jnp.maximum(absmax, jnp.float32(1e-12)) / jnp.float32(QMAX)


def _quantized_pmean_one_axis(flat, axis, block):
    """Block-scaled int8 mean over ONE mesh axis, inside shard_map.
    flat: [N] float32 (every rank holds its own full copy — the
    replicated-gradient layout the dp pmean reduces). Returns [N] f32."""
    n = mesh_mod.axis_size(axis)
    if n == 1:
        return flat
    N = flat.shape[0]
    # shard length: a multiple of `block`, n shards cover the payload
    per = int(-(-N // (n * block))) * block
    padded = jnp.pad(flat, (0, per * n - N))
    nb_total = (per * n) // block

    # 1. shared per-block scales (pmax: one rank's non-finite block
    #    poisons the block's scale on EVERY rank — the NaN contract)
    scale_all = _block_scales(padded.reshape(nb_total, block))
    scale_all = lax.pmax(scale_all, axis)

    # 2. int8 codes of MY copy of the whole payload
    q = _quantize_shared(padded.reshape(nb_total, block), scale_all)
    q = q.reshape(n, per)

    # 3. reduce-scatter by direct exchange: hop s sends the shard owned
    #    by rank (idx - s) mod n straight to its owner; the received
    #    shard is always MY own, accumulated exactly in int32
    #    (|codes| <= 127·n << 2^31)
    idx = lax.axis_index(axis).astype(jnp.int32)
    zero = jnp.int32(0)
    acc = lax.dynamic_slice(q, (idx, zero), (1, per)).reshape(per)
    acc = acc.astype(jnp.int32)
    for s in range(1, n):
        dest = ((idx - s) % n).astype(jnp.int32)
        chunk = lax.dynamic_slice(q, (dest, zero), (1, per)).reshape(per)
        recv = lax.ppermute(
            chunk, axis, [(r, (r - s) % n) for r in range(n)])
        acc = acc + recv.astype(jnp.int32)

    # 4. dequant-accumulate: my shard of the mean, full precision
    nb = per // block
    my_scale = lax.dynamic_slice(scale_all,
                                 ((idx * nb).astype(jnp.int32),), (nb,))
    mean = (acc.reshape(nb, block).astype(jnp.float32)
            * my_scale[:, None]) / jnp.float32(n)

    # 5. re-quantize the finished shard and all-gather codes + scales;
    #    every rank decodes identical bytes (replicas cannot drift)
    scale2 = _block_scales(mean)
    q2 = _quantize_shared(mean, scale2).reshape(per)
    full_q = lax.all_gather(q2, axis, tiled=True)         # [n*per] int8
    full_s = lax.all_gather(scale2, axis, tiled=True)     # [n*nb] f32
    out = (full_q.reshape(nb_total, block).astype(jnp.float32)
           * full_s[:, None])
    return out.reshape(-1)[:N]


def quantized_pmean(x, axes, block=DEFAULT_BLOCK):
    """`lax.pmean(x, axes)` with block-scaled int8 payloads — must run
    inside `shard_map` where `axes` are manual mesh axes and `x` is
    replicated over them (each rank holds its own full gradient, the
    layout a DP grad sync reduces). Multiple axes reduce sequentially
    (mean of means == global mean at equal group sizes)."""
    axes = tuple(a for a in _axis_tuple(axes)
                 if mesh_mod.axis_size(a) > 1)
    if not axes:
        return x
    dtype = x.dtype
    flat = x.astype(jnp.float32).reshape(-1)
    for ax in axes:
        flat = _quantized_pmean_one_axis(flat, ax, block)
    return flat.reshape(x.shape).astype(dtype)


def quantized_pmean_tree(tree, axes, block=DEFAULT_BLOCK,
                         min_size=MIN_QUANT_SIZE):
    """Tree-fused `quantized_pmean`: every leaf with >= `min_size`
    elements rides ONE fused flat payload (one scale/exchange/gather
    sequence for the whole gradient tree — blocks may span leaf
    boundaries, the 4/block scale overhead is paid once), tiny leaves
    keep the exact fp32 `lax.pmean`. Leaf dtypes are preserved."""
    axes = tuple(a for a in _axis_tuple(axes)
                 if mesh_mod.axis_size(a) > 1)
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not axes or not leaves:
        return tree
    big = [i for i, v in enumerate(leaves)
           if int(np.prod(v.shape, dtype=np.int64)) >= min_size]
    out = list(leaves)
    if big:
        flat = jnp.concatenate(
            [leaves[i].astype(jnp.float32).reshape(-1) for i in big])
        for ax in axes:
            flat = _quantized_pmean_one_axis(flat, ax, block)
        off = 0
        for i in big:
            v = leaves[i]
            size = int(np.prod(v.shape, dtype=np.int64))
            out[i] = lax.dynamic_slice(flat, (off,), (size,)).reshape(
                v.shape).astype(v.dtype)
            off += size
    for i, v in enumerate(leaves):
        if i not in big:
            out[i] = lax.pmean(v, axes[0] if len(axes) == 1 else axes)
    return jax.tree_util.tree_unflatten(treedef, out)
