"""distributed.passes — program-level distributed optimization passes.

Reference: python/paddle/distributed/passes/ (pass_base.py registry +
auto_parallel_{amp,fp16,recompute,...}.py — protobuf-program rewriters
applied by fleet/auto-parallel before execution).

TPU-native: a "pass" transforms the static facade's Program by WRAPPING
its stage closures — the rewrite happens at trace time, and XLA compiles
the wrapped computation. Implemented passes do real work:

- ``auto_parallel_amp`` / ``auto_parallel_fp16``: stages run under
  `amp.auto_cast` (bf16 / fp16), same cast-list semantics as eager O1.
- ``auto_parallel_recompute``: stages run under `jax.checkpoint`
  (optionally a named policy via the `policy` attr).
- ``fuse_all_reduce`` / ``auto_parallel_sharding`` /
  ``auto_parallel_gradient_merge``: REGISTERED but apply() raises
  NotImplementedError naming the mechanism that replaces them
  (XLA collective fusion; DistributedTrainStep zero_level /
  gradient-merge config). Registering-then-raising keeps the
  reference's discovery surface without pretending a no-op did work.
"""

__all__ = ["PassContext", "PassBase", "PassManager", "new_pass",
           "register_pass"]

_MISSING = object()

_PASS_REGISTRY = {}


class PassContext:
    """(reference pass_base.py:21)."""

    def __init__(self):
        self._attrs = {}
        self._passes = []

    def set_attr(self, key, value):
        self._attrs[key] = value

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    @property
    def passes(self):
        return list(self._passes)


def register_pass(name):
    def deco(cls):
        cls.name = name
        _PASS_REGISTRY[name] = cls
        return cls

    return deco


class PassBase:
    """(reference pass_base.py:52). Subclasses implement
    `_apply_single_impl(main_program, startup_program, context)`."""

    name = None

    def __init__(self):
        self._attrs = {}

    def set_attr(self, key, value):
        self._attrs[key] = value
        return self

    def get_attr(self, key, default=None):
        return self._attrs.get(key, default)

    def apply(self, main_programs, startup_programs=None, context=None):
        context = context or PassContext()
        if not isinstance(main_programs, (list, tuple)):
            main_programs = [main_programs]
        if startup_programs is None:
            startup_programs = [None] * len(main_programs)
        elif not isinstance(startup_programs, (list, tuple)):
            startup_programs = [startup_programs]
        if len(startup_programs) != len(main_programs):
            raise ValueError(
                f"{len(main_programs)} main programs but "
                f"{len(startup_programs)} startup programs — zip would "
                "silently skip the excess")
        for mp, sp in zip(main_programs, startup_programs):
            self._apply_single_impl(mp, sp, context)
        context._passes.append(self)
        return context

    def _apply_single_impl(self, main_program, startup_program, context):
        raise NotImplementedError


def _wrap_stages(program, wrapper):
    program.stages[:] = [wrapper(stage) for stage in program.stages]


@register_pass("auto_parallel_amp")
class AMPPass(PassBase):
    """Stages execute under amp.auto_cast (reference
    auto_parallel_amp.py rewrites cast ops into the program)."""

    dtype = "bfloat16"

    def _apply_single_impl(self, main_program, startup_program, context):
        from .. import amp

        level = self.get_attr("level", "O1")
        dtype = self.get_attr("dtype", self.dtype)

        def wrap(stage):
            def amped(env):
                with amp.auto_cast(level=level, dtype=dtype):
                    return stage(env)

            return amped

        _wrap_stages(main_program, wrap)


@register_pass("auto_parallel_fp16")
class FP16Pass(AMPPass):
    """(reference auto_parallel_fp16.py)."""

    dtype = "float16"


@register_pass("auto_parallel_recompute")
class RecomputePass(PassBase):
    """Stages recompute activations in backward (reference
    auto_parallel_recompute.py inserts the recompute subgraphs)."""

    def _apply_single_impl(self, main_program, startup_program, context):
        from .fleet.recompute import recompute
        from ..tensor_core import Tensor

        policy = self.get_attr("policy")
        # trainable params used INSIDE stages must be declared so the
        # checkpoint tape threads them as inputs (grads only flow to
        # declared inputs — fleet.recompute contract); recompute() picks
        # them up through the `parameters` attribute set below
        params = list(self.get_attr("parameters") or [])

        def wrap(stage):
            # stages communicate by MUTATING the env dict; recompute
            # needs a tensors-in/tensors-out function, so snapshot the
            # env's tensors as inputs, run the stage on a copy, and
            # merge the produced values back (deterministic key order)
            def rc(env):
                keys_in = sorted(k for k, v in env.items()
                                 if isinstance(v, Tensor))
                out_keys = []
                side = {}     # non-Tensor writes (trace-time effects)
                removed = []  # keys the stage deleted

                def fn(*vals):
                    local = dict(env)
                    inserted = dict(zip(keys_in, vals))
                    local.update(inserted)
                    stage(local)
                    # produced = keys the stage (re)assigned — compare
                    # against the wrapper we inserted, NOT env's (inputs
                    # arrive as fresh wrappers, identity vs env is
                    # always False)
                    produced = sorted(
                        k for k, v in local.items()
                        if isinstance(v, Tensor)
                        and v is not inserted.get(k, env.get(k)))
                    out_keys[:] = produced
                    side.clear()
                    side.update({k: v for k, v in local.items()
                                 if not isinstance(v, Tensor)
                                 and env.get(k, _MISSING) is not v})
                    removed[:] = [k for k in env if k not in local]
                    return tuple(local[k] for k in produced)

                fn.parameters = lambda: params
                kwargs = {"policy": policy} if policy else {}
                outs = recompute(fn, *[env[k] for k in keys_in], **kwargs)
                if not isinstance(outs, (tuple, list)):
                    outs = (outs,)
                env.update(zip(out_keys, outs))
                env.update(side)
                for k in removed:
                    env.pop(k, None)

            return rc

        _wrap_stages(main_program, wrap)


class _ReplacedByMechanism(PassBase):
    mechanism = ""

    def _apply_single_impl(self, main_program, startup_program, context):
        raise NotImplementedError(
            f"pass {self.name!r} has no program rewrite on this stack — "
            f"{self.mechanism}")


@register_pass("fuse_all_reduce")
class FuseAllReducePass(_ReplacedByMechanism):
    mechanism = ("XLA fuses/coalesces collectives during compilation; "
                 "eager-path fusion lives in "
                 "fleet.utils.fused_allreduce_gradients")


@register_pass("auto_parallel_sharding")
class ShardingPass(_ReplacedByMechanism):
    mechanism = ("use DistributedTrainStep(zero_level=...) — ZeRO "
                 "placements are PartitionSpecs, not program rewrites")


@register_pass("auto_parallel_gradient_merge")
class GradientMergePass(_ReplacedByMechanism):
    mechanism = ("use DistributedStrategy.gradient_merge / micro-batch "
                 "accumulation in the compiled step")


def new_pass(name, pass_attrs=None):
    """(reference pass_base.py new_pass)."""
    try:
        cls = _PASS_REGISTRY[name]
    except KeyError:
        raise ValueError(
            f"unknown pass {name!r}; registered: "
            f"{sorted(_PASS_REGISTRY)}") from None
    p = cls()
    for k, v in (pass_attrs or {}).items():
        p.set_attr(k, v)
    return p


class PassManager:
    """(reference pass_base.py PassManager) — apply a pass list in
    order."""

    def __init__(self, passes):
        self._passes = [new_pass(p) if isinstance(p, str) else p
                        for p in passes]

    def apply(self, main_programs, startup_programs=None):
        context = PassContext()
        for p in self._passes:
            p.apply(main_programs, startup_programs, context)
        return context

    @property
    def names(self):
        return [p.name for p in self._passes]
