"""Eager cross-process collectives (multi-controller path).

The reference's eager `dist.all_reduce` is a runtime NCCL call between
trainer processes (reference: python/paddle/distributed/collective.py:751,
paddle/fluid/distributed/collective/ProcessGroupNCCL.cc).  The TPU-native
equivalent: each trainer process is one JAX controller; an eager
collective is a tiny jitted SPMD program over a 1-D "proc" mesh holding
one representative device per process.  XLA lowers it to ICI/DCN (gloo on
CPU hosts) — no sidecar runtime, same compiled-collective machinery as
the in-graph path.

Rank semantics match the reference: rank == trainer process index.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["is_multiprocess", "all_reduce_np", "all_gather_np",
           "broadcast_np", "barrier", "all_gather_bytes",
           "all_gather_obj"]

_REDUCERS = {
    "sum": lambda x, ax: lax.psum(x, ax),
    "avg": lambda x, ax: lax.pmean(x, ax),
    "max": lambda x, ax: lax.pmax(x, ax),
    "min": lambda x, ax: lax.pmin(x, ax),
    # gather-then-multiply: exact for negatives/zeros/ints (log-sum-exp isn't)
    "prod": lambda x, ax: jnp.prod(lax.all_gather(x, ax, axis=0), axis=0),
}


def is_multiprocess():
    try:
        return jax.process_count() > 1
    except Exception:
        return False


def _proc_mesh():
    """1-D mesh with one representative device per process, rank-ordered."""
    reps = {}
    for d in jax.devices():
        reps.setdefault(d.process_index, d)
    devs = [reps[i] for i in sorted(reps)]
    return Mesh(np.array(devs), ("proc",))


_cache = {}


def _run(kind, nparr, op="sum", src=0):
    mesh = _proc_mesh()
    key = (kind, nparr.shape, str(nparr.dtype), op, src)
    if key not in _cache:
        if kind == "all_reduce":
            f = shard_map(lambda x: _REDUCERS[op](x, "proc"), mesh=mesh,
                          in_specs=P("proc"), out_specs=P("proc"))
        elif kind == "all_gather":
            f = shard_map(
                lambda x: lax.all_gather(x, "proc", axis=0, tiled=True),
                mesh=mesh, in_specs=P("proc"), out_specs=P(),
                check_vma=False)
        elif kind == "broadcast":
            f = shard_map(
                lambda x: lax.all_gather(x, "proc", axis=0,
                                         tiled=True)[src][None],
                mesh=mesh, in_specs=P("proc"), out_specs=P("proc"),
                check_vma=False)
        else:
            raise ValueError(kind)
        _cache[key] = jax.jit(f)
    sharding = NamedSharding(mesh, P("proc"))
    garr = jax.make_array_from_process_local_data(sharding, nparr[None])
    return _cache[key](garr)


def all_reduce_np(nparr, op="sum"):
    """nparr (local value) -> reduced np.ndarray, same shape."""
    out = _run("all_reduce", np.ascontiguousarray(nparr), op=op)
    return np.asarray(out.addressable_data(0))[0]


def all_gather_np(nparr):
    """nparr (local value) -> stacked (world,)+shape np.ndarray."""
    out = _run("all_gather", np.ascontiguousarray(nparr))
    return np.asarray(out.addressable_data(0))


def broadcast_np(nparr, src=0):
    out = _run("broadcast", np.ascontiguousarray(nparr), src=src)
    return np.asarray(out.addressable_data(0))[0]


def barrier():
    """Completion of a psum across all processes is a barrier."""
    all_reduce_np(np.zeros((1,), np.float32))


def all_gather_obj(obj, max_len=1 << 27):
    """Gather one picklable object per process (pickle + padded byte
    gather) — the shared idiom under ShardedSparseTable routing,
    global_shuffle, and friends."""
    import pickle

    blobs = all_gather_bytes(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
        max_len=max_len)
    return [pickle.loads(b) for b in blobs]


def all_gather_bytes(payload: bytes, max_len=1 << 20):
    """Gather variable-length byte strings (pickled objects) — the
    reference's all_gather_object (collective.py:1056) over the same
    compiled-collective path: length-prefixed, padded uint8 buffers."""
    n = len(payload)
    lens = all_gather_np(np.array([n], np.int32))[:, 0]
    width = int(lens.max())
    stats["gather_bytes"] += width * len(lens)
    if width > max_len:
        # raise on ALL ranks (post-gather) so no peer is left blocking
        raise ValueError(f"object too large to gather ({width} > {max_len})")
    buf = np.zeros((width,), np.uint8)
    buf[:n] = np.frombuffer(payload, np.uint8)
    mat = all_gather_np(buf)
    return [mat[i, : int(lens[i])].tobytes() for i in range(len(lens))]


# ---- point-to-point over the coordination-service KV store ----
# (reference: ProcessGroup::Send/Recv, store/tcp_store.h; here the
# jax.distributed coordination service IS the TCP store)

_p2p_send_seq = {}
_p2p_recv_seq = {}

# traffic accounting (tests assert PS routing is O(batch), not
# O(world·batch); all_gather_bytes counts the full gathered matrix —
# what every rank actually receives)
stats = {"p2p_bytes": 0, "gather_bytes": 0}


def _kv_client():
    from jax._src.distributed import global_state

    client = getattr(global_state, "client", None)
    if client is None:
        raise RuntimeError(
            "p2p send/recv needs the multi-process runtime: start workers "
            "via paddle_tpu.distributed.launch / spawn (jax.distributed)")
    return client


def send_bytes(data: bytes, dst: int, tag: int = 0):
    import base64

    me = jax.process_index()
    seq = _p2p_send_seq.get((me, dst, tag), 0)
    _p2p_send_seq[(me, dst, tag)] = seq + 1
    stats["p2p_bytes"] += len(data)
    _kv_client().key_value_set(
        f"pt_p2p/{me}/{dst}/{tag}/{seq}",
        base64.b64encode(data).decode("ascii"))


def recv_bytes(src: int, tag: int = 0, timeout_ms: int = 60_000) -> bytes:
    import base64

    me = jax.process_index()
    seq = _p2p_recv_seq.get((src, me, tag), 0)
    _p2p_recv_seq[(src, me, tag)] = seq + 1
    key = f"pt_p2p/{src}/{me}/{tag}/{seq}"
    client = _kv_client()
    val = client.blocking_key_value_get(key, timeout_ms)
    # consumed: delete the entry, or bulk transfers (global_shuffle ships
    # whole dataset buckets) grow the coordinator without bound
    try:
        client.key_value_delete(key)
    except Exception:
        pass
    return base64.b64decode(val)


def send_np(arr, dst: int, tag: int = 0):
    import io

    buf = io.BytesIO()
    np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
    send_bytes(buf.getvalue(), dst, tag)


def recv_np(src: int, tag: int = 0, timeout_ms: int = 60_000):
    import io

    return np.load(io.BytesIO(recv_bytes(src, tag, timeout_ms)),
                   allow_pickle=False)


__all__ += ["send_bytes", "recv_bytes", "send_np", "recv_np"]
